"""Live spec: the file ``python sheeprl.py live <spec>`` consumes.

YAML (or JSON — YAML is a superset) with this shape::

    name: cartpole_live             # live-run name (fs-safe)
    checkpoint_path: logs/runs/...  # boot policy (file / run dir / rank set)
    servers: 1                      # serving roles (each one actor rank)
    sessions: 2                     # concurrent env sessions PER server
    session_rounds: 1               # session waves each server drives
    wave_pause_s: 0.0               # pause between waves (paces traffic so the
                                    # learner's publishes land MID-traffic)
    max_session_steps: 200          # per-session episode cap
    log_dir: null                   # default: logs/live/<name>_<timestamp>
    serve:                          # serve.* knobs (slots, explore, deadline_ms...)
      slots: 4
      explore: {fraction: 0.5, noise: 0.3}
    overrides: []                   # raw dotted overrides onto the serve config
    learner:                        # dotted overrides onto the learner config
      - algo.learning_starts=64
      - buffer.service.publish_every=1
    supervisor:                     # gang restart policy (run_restart_policy)
      enabled: false
      max_restarts: 3
      backoff: 1.0
      backoff_cap: 60.0
    drain_grace_s: 10.0             # SIGTERM: in-flight session grace
    ingest:
      max_queue: 64                 # bounded trajectory queue (overflow = shed)
    reload_poll_s: 0.5              # serve-side weight-plane poll cadence

CLI overrides (``key=value`` after the spec path) are dotted paths into this
mapping — ``servers=2`` or ``serve.explore.fraction=0.25`` — applied before
normalization, so a spec file can be a template the operator parameterizes.

The spec describes ONE closed-loop gang: ``servers`` serving roles whose
finished sessions feed a single in-process experience-service learner
(``buffer.backend=service``), whose published weight versions hot-reload into
every server between ticks. ``live.json`` (the marker ``write_marker`` drops in
the live dir) makes the directory self-describing for ``watch``/``diagnose``.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional

LIVE_MARKER = "live.json"

_NAME_RE = re.compile(r"[^A-Za-z0-9._-]+")


def _fs_name(raw: str) -> str:
    return _NAME_RE.sub("-", str(raw)).strip("-") or "live"


def _set_dotted(spec: Dict[str, Any], key: str, value: Any) -> None:
    parts = [p for p in str(key).split(".") if p]
    node: Any = spec
    for i, part in enumerate(parts):
        last = i == len(parts) - 1
        if isinstance(node, list):
            # numeric segments index list-valued spec fields (``learner.2=...``
            # edits the third learner override; index == len appends)
            if not part.isdigit() or int(part) > len(node):
                raise ValueError(
                    f"live override segment {part!r} of {key!r} indexes a list "
                    f"of {len(node)} item(s) — use 0..{len(node)}"
                )
            idx = int(part)
            if last:
                if idx == len(node):
                    node.append(value)
                else:
                    node[idx] = value
                return
            node = node[idx]
            continue
        if last:
            node[part] = value
            return
        child = node.get(part)
        if not isinstance(child, (dict, list)):
            child = {}
            node[part] = child
        node = child


def load_live_spec(path: str, overrides: Optional[List[str]] = None) -> Dict[str, Any]:
    """Load + validate a live spec file, apply dotted CLI ``overrides``, and
    return the normalized spec mapping."""
    import yaml

    if not os.path.isfile(path):
        raise FileNotFoundError(f"live spec {path!r}: no such file")
    with open(path) as fh:
        raw = yaml.safe_load(fh)
    if not isinstance(raw, dict):
        raise ValueError(f"live spec {path!r} must be a mapping, got {type(raw).__name__}")
    spec = dict(raw)
    for item in overrides or []:
        if "=" not in item:
            raise ValueError(f"live override {item!r} must be key=value")
        key, raw_value = item.split("=", 1)
        try:
            value = yaml.safe_load(raw_value)
        except yaml.YAMLError:
            value = raw_value
        _set_dotted(spec, key, value)

    spec["name"] = _fs_name(spec.get("name") or os.path.splitext(os.path.basename(path))[0])
    if not spec.get("checkpoint_path"):
        raise ValueError(
            "live spec needs checkpoint_path: the boot policy every server loads "
            "(a checkpoint file, a run dir, or a multi-rank checkpoint dir)"
        )
    spec["checkpoint_path"] = str(spec["checkpoint_path"])
    spec["servers"] = max(int(spec.get("servers") or 1), 0)
    spec["sessions"] = max(int(spec.get("sessions") or 2), 0)
    spec["session_rounds"] = max(int(spec.get("session_rounds") or 1), 1)
    spec["wave_pause_s"] = max(float(spec.get("wave_pause_s") or 0.0), 0.0)
    spec["max_session_steps"] = max(int(spec.get("max_session_steps") or 200), 1)
    spec["log_dir"] = str(spec["log_dir"]) if spec.get("log_dir") else None
    serve = spec.get("serve") or {}
    if not isinstance(serve, dict):
        raise ValueError("live spec 'serve' must be a mapping of serve.* knobs")
    spec["serve"] = serve
    spec["overrides"] = [str(o) for o in spec.get("overrides") or []]
    spec["learner"] = [str(o) for o in spec.get("learner") or []]
    sup = dict(spec.get("supervisor") or {})
    sup.setdefault("enabled", False)
    sup.setdefault("max_restarts", 3)
    sup.setdefault("backoff", 1.0)
    sup.setdefault("backoff_cap", 60.0)
    spec["supervisor"] = sup
    spec["drain_grace_s"] = float(spec.get("drain_grace_s") or 10.0)
    ingest = dict(spec.get("ingest") or {})
    ingest["max_queue"] = max(int(ingest.get("max_queue") or 64), 1)
    spec["ingest"] = ingest
    spec["reload_poll_s"] = float(spec.get("reload_poll_s") or 0.5)
    return spec


def _flatten(prefix: str, node: Any, out: List[str]) -> None:
    if isinstance(node, dict):
        for key, value in node.items():
            _flatten(f"{prefix}.{key}" if prefix else str(key), value, out)
    else:
        # values round-trip through yaml.safe_load in build_serve_cfg: JSON is
        # a YAML subset, so dumps keeps strings quoted and None spelled null
        out.append(f"{prefix}={json.dumps(node)}")


def serve_overrides(spec: Dict[str, Any]) -> List[str]:
    """The dotted override list :func:`~sheeprl_tpu.serve.main.build_serve_cfg`
    composes the serving config from: the spec's ``serve`` block flattened to
    ``serve.*`` assignments, then the raw ``overrides`` (which therefore win)."""
    out: List[str] = [f"checkpoint_path={spec['checkpoint_path']}"]
    _flatten("serve", spec["serve"], out)
    out.extend(spec["overrides"])
    return out


def write_marker(live_dir: str, spec: Dict[str, Any], streams: Dict[str, str]) -> str:
    """The ``live.json`` marker that makes a live dir self-describing: the gang
    topology and the per-role telemetry stream files."""
    payload = {
        "schema": 1,
        "kind": "live",
        "name": spec["name"],
        "checkpoint_path": spec["checkpoint_path"],
        "servers": spec["servers"],
        "sessions": spec["sessions"],
        "streams": dict(streams),
    }
    path = os.path.join(live_dir, LIVE_MARKER)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def read_marker(path: str) -> Optional[Dict[str, Any]]:
    """The live marker of ``path`` (a live dir), or None when ``path`` is not a
    live dir / the marker is unreadable."""
    marker = os.path.join(str(path), LIVE_MARKER)
    if not os.path.isfile(marker):
        return None
    try:
        with open(marker) as fh:
            payload = json.load(fh)
        return payload if isinstance(payload, dict) else None
    except (OSError, ValueError):
        return None
