"""Cooperative preemption handling (SIGTERM/SIGINT → poll flag → graceful exit).

TPU pods are preemptible infrastructure: maintenance events and spot reclaims
deliver SIGTERM with a short grace window (PAPERS: "Podracer architectures" runs
everything on this assumption). The reference has no signal handling at all — a
SIGTERM between two ``checkpoint.every`` boundaries silently loses everything
since the last checkpoint. Here the CLI installs a process-level handler that
only *records* the signal; the training loops poll :func:`preemption_requested`
at iteration boundaries, write an out-of-cadence emergency checkpoint through
their existing ``on_checkpoint_*`` path, tear down cleanly (the decoupled player
forwards the shutdown over the data channel, so trainer ranks exit too) and the
CLI exits with :data:`PREEMPTED_EXIT_CODE` so external supervisors can tell a
preemption from a crash. A second signal while the flag is set restores the
previous handler and re-raises — the escape hatch when the cooperative path is
itself stuck.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Dict, Optional, Tuple

# Distinct "preempted" exit status (EX_TEMPFAIL: transient, retry later) — not
# 128+signum, which any abnormal SIGTERM death would also produce. External
# supervisors (and the in-process one) key restart policy on this.
PREEMPTED_EXIT_CODE = 75
# Watchdog abort escalation exit status (see resilience/watchdog.py).
WATCHDOG_EXIT_CODE = 76
# A healthy rank that tore itself down because a PEER was declared dead
# (resilience/distributed.py RankFailureError): the gang supervisor must not
# blame this rank for the attempt's death — the dead peer is the culprit.
RANK_FAILED_EXIT_CODE = 77

_DEFAULT_SIGNALS: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)

_state_lock = threading.Lock()
_flag = threading.Event()
# gang-level preemption: set when the distributed coordinator learns the gang
# agreed to preempt (a SIGTERM may have landed on a PEER rank only). Kept
# separate from _flag so the second-signal force-exit escape keys strictly on a
# signal THIS process received — an OS SIGTERM arriving after the gang flag was
# set must take the normal cooperative path, not an immediate re-raise.
_gang_flag = threading.Event()
_signum: Optional[int] = None
_received_at: Optional[float] = None
_prev_handlers: Dict[int, object] = {}


def _handler(signum, frame) -> None:
    global _signum, _received_at
    if _flag.is_set():
        # second signal: the cooperative path did not exit in time — restore the
        # previous disposition and re-deliver so the default behavior (or the
        # caller's original handler) takes over immediately
        prev = _prev_handlers.get(signum, signal.SIG_DFL)
        try:
            signal.signal(signum, prev if callable(prev) or prev in (signal.SIG_DFL, signal.SIG_IGN) else signal.SIG_DFL)
        except (ValueError, OSError):
            pass
        signal.raise_signal(signum)
        return
    _signum = int(signum)
    _received_at = time.monotonic()
    _flag.set()
    try:
        name = signal.Signals(signum).name
    except ValueError:
        name = str(signum)
    print(
        f"[sheeprl-resilience] caught {name}: requesting cooperative preemption — "
        "emergency checkpoint at the next iteration boundary (send again to force exit)",
        file=sys.stderr,
        flush=True,
    )


def install_preemption_handler(signums: Tuple[int, ...] = _DEFAULT_SIGNALS) -> bool:
    """Install the preemption handler (idempotent; resets a stale flag). Returns
    False — and installs nothing — off the main thread, where CPython forbids
    ``signal.signal`` (e.g. a loop launched from a test worker thread)."""
    if threading.current_thread() is not threading.main_thread():
        return False
    with _state_lock:
        reset_preemption()
        installed = []
        for signum in signums:
            prev = signal.getsignal(signum)
            try:
                signal.signal(signum, _handler)
            except (ValueError, OSError):
                # partial install must unwind: the caller records "not
                # installed" and would never uninstall the ones already bound
                for done, done_prev in installed:
                    try:
                        signal.signal(done, done_prev)
                    except (ValueError, OSError, TypeError):
                        pass
                    _prev_handlers.pop(done, None)
                return False
            if prev is not _handler:
                _prev_handlers[signum] = prev
                installed.append((signum, prev))
    return True


def uninstall_preemption_handler() -> None:
    """Restore the dispositions saved by :func:`install_preemption_handler`."""
    if threading.current_thread() is not threading.main_thread():
        return
    with _state_lock:
        for signum, prev in list(_prev_handlers.items()):
            try:
                if signal.getsignal(signum) is _handler:
                    signal.signal(signum, prev)
            except (ValueError, OSError, TypeError):
                pass
            _prev_handlers.pop(signum, None)


def preemption_requested() -> bool:
    """The poll the training loops run at iteration boundaries — true on a
    process-local signal OR a gang-level agreement relayed by the distributed
    coordinator (so every rank of a preempting gang exits preempted, including
    ranks the reclaim signal never reached)."""
    return _flag.is_set() or _gang_flag.is_set()


def local_preemption_requested() -> bool:
    """Strictly the process-local signal flag — what a rank *publishes* to the
    coordination plane (the gang flag is what it *consumes* back)."""
    return _flag.is_set()


def mark_preempted() -> None:
    """Record a gang-level preemption agreement (distributed coordinator only)."""
    _gang_flag.set()


def preempt_signum() -> Optional[int]:
    return _signum if _flag.is_set() else None


def preempt_age_seconds() -> Optional[float]:
    """Seconds since the preemption signal landed (None when not preempted) —
    how much of the grace window the emergency checkpoint has already spent."""
    if not _flag.is_set() or _received_at is None:
        return None
    return time.monotonic() - _received_at


def reset_preemption() -> None:
    """Clear the flags (the supervisors call this between attempts)."""
    global _signum, _received_at
    _flag.clear()
    _gang_flag.clear()
    _signum = None
    _received_at = None


def request_preemption(signum: Optional[int] = None) -> None:
    """Programmatic preemption (fault injection / watchdog): raise the real
    signal when a handler is installed so the full path is exercised, otherwise
    set the flag directly."""
    target = signal.SIGTERM if signum is None else signum
    if signal.getsignal(target) is _handler:
        os.kill(os.getpid(), target)
    else:
        _handler(target, None)
