"""Run supervisor: bounded auto-restart with checkpoint auto-resume.

Wraps the algo entrypoint launch in ``cli.py`` (``resilience.supervisor.enabled``,
off by default). On a crash — or a cooperative preemption, when
``restart_on_preempt`` — it resolves the newest *valid* checkpoint in the run's
log dir (``discovery.py``: both pickle and orbax formats, including the
``.old``/sidecar crash-window variants ``load_checkpoint`` understands), rebuilds
the attempt config through the CLI's resume merge (identity validation + config
restore, with the emergency checkpoint as ``resume_from``), sleeps an exponential
backoff, and re-enters the loop — the single-process analogue of a Podracer pod
controller rescheduling a dead worker. Restarts are bounded by ``max_restarts``;
when the budget is exhausted a crash re-raises and a preemption exits with the
preempted code. Each decision lands as a ``restart``/``giveup`` event in the
run-base ``telemetry.jsonl`` shared by every attempt (the supervisor pins
``metric.telemetry.jsonl_path`` there), so the whole
preempt → checkpoint → restart → resume history is one ordered stream.

Scope: the in-process supervisor drives single-process topologies (SPMD or the
threaded decoupled trainers). Multi-process MPMD roles are restarted by the
external launcher — restarting one role in-process would desync the stateful
channel planes — so the supervisor steps aside with a warning there.
"""

from __future__ import annotations

import copy
import warnings
from typing import Any, Callable, Optional

from sheeprl_tpu.config import dotdict
from sheeprl_tpu.obs.jsonl import JsonlEventSink
from sheeprl_tpu.resilience import faults, signals
from sheeprl_tpu.resilience.discovery import find_latest_checkpoint
from sheeprl_tpu.resilience.restart_policy import RestartPolicy, run_restart_policy
from sheeprl_tpu.resilience.watchdog import stop_all_watchdogs


def supervisor_enabled(cfg: Any) -> bool:
    return bool(((cfg.get("resilience") or {}).get("supervisor") or {}).get("enabled", False))


def _strip_fired_fault(cfg: dotdict) -> None:
    """A fault that already fired must not ride into the retry config (the saved
    run config — merged back on resume — still carries it)."""
    if faults.has_fired():
        fault = (cfg.get("resilience") or {}).get("fault")
        if fault:
            fault["kind"] = None


def supervise(
    cfg: dotdict,
    run_fn: Callable[[dotdict], Any],
    resume_merge: Callable[[dotdict], dotdict],
    argv_cfg: Optional[dotdict] = None,
) -> str:
    """Run ``run_fn(cfg)`` under restart supervision. Returns ``"completed"`` or
    ``"preempted"`` (the CLI maps the latter to the preempted exit code);
    a crash that exhausts the restart budget re-raises.

    ``argv_cfg`` is the original *argv-merged* config — ``compose(overrides)``
    BEFORE any launch-time resume merge. Retry attempts are rebuilt from it
    (not from the resolved ``cfg``) and re-merged against the retry's resolved
    checkpoint through ``resume_merge``, which the CLI closes over the user's
    explicit dotted overrides — so a ``buffer.size=N`` typed on the command
    line survives every attempt instead of being silently replaced by the
    checkpoint's saved config."""
    from sheeprl_tpu.parallel import distributed
    from sheeprl_tpu.utils.logger import run_base_dir

    if distributed.process_count() > 1:
        warnings.warn(
            "resilience.supervisor: multi-process (MPMD/multi-host) topologies are "
            "restarted by the external launcher; in-process supervision is disabled "
            "for this run (the preemption handler and emergency checkpoint still apply)."
        )
        run_fn(cfg)
        return "preempted" if signals.preemption_requested() else "completed"

    policy = RestartPolicy.from_cfg(cfg.resilience.supervisor)

    run_base = run_base_dir(cfg.root_dir, cfg.run_name)
    # one event stream across attempts: every restart appends to the same file.
    # metric.telemetry.jsonl=false disables the stream — supervisor events too.
    cfg.metric.setdefault("telemetry", dotdict({}))
    jsonl_enabled = bool(cfg.metric.telemetry.get("jsonl", True))
    if jsonl_enabled and not cfg.metric.telemetry.get("jsonl_path"):
        cfg.metric.telemetry.jsonl_path = str(run_base / "telemetry.jsonl")

    sink: Optional[JsonlEventSink] = None

    def emit(event: str, **fields: Any) -> None:
        nonlocal sink
        if not jsonl_enabled:
            return
        if sink is None:
            try:
                sink = JsonlEventSink(cfg.metric.telemetry.jsonl_path)
            except OSError:
                return
        # supervisor events are stamped with the attempt they decide ABOUT, not
        # the sink's creation-time default (one sink spans every attempt) — the
        # shared policy loop keeps the live counter on `policy`
        fields.setdefault("attempt", policy.attempt)
        sink.emit(event, **fields)

    # retries rebuild from the argv-merged cfg, NOT the resolved base: when the
    # launch itself resumed, the resolved cfg already had the old run's config
    # merged over it — rebuilding from that bakes the old values in a second
    # time and user overrides can never win the retry merge
    original = dotdict(copy.deepcopy((argv_cfg if argv_cfg is not None else cfg).as_dict()))
    # ...but the resume fallback must be the RESOLVED path (the argv value may
    # be the literal "latest")
    fallback_resume = cfg.checkpoint.get("resume_from") or None
    state: dict = {"current": cfg, "resume_from": None}

    def run_attempt(attempt: int):
        if attempt > 0:
            retry = dotdict(copy.deepcopy(original.as_dict()))
            _strip_fired_fault(retry)
            resume_from = state["resume_from"]
            if resume_from is not None:
                retry.checkpoint.resume_from = resume_from
                retry = resume_merge(retry)
            else:
                # crash before any checkpoint landed: restart from scratch
                retry.checkpoint.resume_from = None
            # every event the retry writes (telemetry, resilience monitor) carries
            # its attempt number — the ordering key obs/streams.py merges on
            # (after resume_merge: `metric` is non-resumable, so this sticks)
            retry.metric.setdefault("telemetry", dotdict({}))
            retry.metric.telemetry.attempt = attempt
            # the retry was rebuilt from the ARGV config, which never carried
            # the run-base stream pin set on the resolved cfg above — re-pin it
            # or attempt 2+ would write its own per-version stream
            if jsonl_enabled:
                retry.metric.telemetry.jsonl_path = cfg.metric.telemetry.jsonl_path
            state["current"] = retry
        error: Optional[BaseException] = None
        try:
            run_fn(state["current"])
        except Exception as e:  # SystemExit/KeyboardInterrupt propagate
            error = e
            # an exception skipped the loop's finalize(): stop any orphaned
            # watchdog NOW — an abort-mode one is in its grace countdown
            # toward os._exit and would kill the restarted attempt
            stop_all_watchdogs()
        preempted = signals.preemption_requested() and error is None
        if error is None and not preempted:
            return "completed", {}
        return ("crash" if error is not None else "preempt"), {"error": error}

    def restart_fields(attempt, outcome, info):
        # nothing in THIS run's dir yet (crash before the first checkpoint)
        # must not discard a resume checkpoint the user originally launched
        # with — fall back to it rather than silently starting from scratch
        state["resume_from"] = find_latest_checkpoint(str(run_base)) or fallback_resume
        error = info.get("error")
        return {
            "resume_from": state["resume_from"],
            "error": repr(error)[:500] if error is not None else None,
        }

    def giveup_fields(info):
        error = info.get("error")
        return {"error": repr(error) if error is not None else None}

    def on_giveup(outcome, info):
        if info.get("error") is not None:
            raise info["error"]
        return "preempted"

    try:
        return run_restart_policy(
            policy,
            run_attempt,
            emit,
            restart_fields=restart_fields,
            giveup_fields=giveup_fields,
            on_giveup=on_giveup,
        )
    finally:
        if sink is not None:
            sink.close()
