"""Distributed resilience: coordinated preemption, rank-failure detection,
checkpoint-set consistency and gang supervision for multi-process runs.

PR 3 made a single-process run preemption-safe; every multi-process topology was
still fragile: preempt agreement had a documented one-iteration rank-skew
window, a crashed peer hung the other side of a decoupled channel forever, and
the in-process supervisor stepped aside with a warning. This module closes all
of that over the jax.distributed COORDINATION SERVICE key-value store — the
same gRPC object plane the decoupled channels already ride, which (unlike XLA
collectives) works across processes on every backend including the CPU test
mesh, and tolerates arbitrarily skewed arrival.

Four pillars:

- **Coordinated preemption** (:class:`DistributedCoordinator`): any rank that
  observes its local SIGTERM flag publishes a preempt *request*; rank 0 turns
  the first request into a *decision* — "every rank stops at policy step >= S" —
  with S placed far enough ahead (``agree_within_iters`` iterations plus the
  control-plane polling skew at the observed step rate) that every rank has
  seen it before reaching it. Because SPMD ranks advance through the same
  policy-step sequence in lockstep, comparing the same S against the same step
  sequence makes every rank take the same emergency checkpoint at the same
  step — the PR 3 skew window is closed by construction. In the decoupled MPMD
  topologies the player (rank 0) is the only loop driver: a learner's SIGTERM
  becomes a request the player consumes, and the existing channel shutdown
  protocol (want_opt_state + final ``None``) carries the coordinated teardown.

- **Rank-failure detection**: every rank runs a heartbeat writer thread
  (``resilience.distributed.heartbeat.interval``) and a failure monitor thread
  that watches every peer's heartbeat *counter* (no cross-host clock
  comparison). A rank silent for ``heartbeat.timeout`` seconds is declared
  dead: a ``health`` event (``status=rank_dead``) names it, and an **abort**
  record is published that every rank's facade — and every bounded channel
  wait — converts into :class:`RankFailureError`, so a dead peer means a
  prompt coordinated teardown instead of an indefinite hang.

- **Checkpoint consistency** (:func:`checkpoint_manifest`): multi-process
  checkpoints get a per-step manifest (``ckpt_{step}.manifest.json``) written
  *before* the save with ``complete: false`` and committed *after* every
  participating rank acks through the KV store — the commit marker is written
  last, so a torn multi-rank save is invalid by construction and
  ``discovery.py`` only resolves checkpoints every rank finished.

- **Gang supervision** (:func:`supervise_gang`): the multi-process
  generalization of ``supervisor.py`` — one parent owns N ``jax.distributed``
  child processes (SPMD ranks or the decoupled player/learner pair), launched
  with a fresh coordinator per attempt. On any child's crash or preemption it
  tears down the survivors, resolves the latest *consistent*
  (manifest-validated) checkpoint, and restarts the whole gang with the attempt
  counter stamped into every rank's telemetry stream. Restart policy
  (``max_restarts``/``backoff``/``restart_on_preempt``) is shared with the
  in-process supervisor.

See ``howto/fault_tolerance.md`` ("Distributed runs") for operational guidance.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence

from sheeprl_tpu.parallel import distributed as par_dist


class RankFailureError(RuntimeError):
    """A peer rank of this multi-process run was declared dead (heartbeat
    timeout or abnormal exit). Raised from the resilience facade's per-iteration
    hook and from bounded channel waits so no rank blocks forever on a dead
    peer; the run unwinds as a crash and the (gang or external) supervisor
    restarts the whole gang."""


# ---------------------------------------------------------------------------------
# KV helpers: the coordination-service store as a tiny control plane
# ---------------------------------------------------------------------------------


def _kv() -> Any:
    return par_dist._kv_client()


def _kv_set(client: Any, key: str, value: str) -> None:
    client.key_value_set(key, value, allow_overwrite=True)


def _kv_dir(client: Any, prefix: str) -> List[tuple]:
    try:
        return list(client.key_value_dir_get(prefix))
    except Exception:
        return []  # NOT_FOUND before the first write, or a dying coordinator


# Per-process count of coordinators built: namespaces the control-plane keyspace
# so a LATER run in the same jax.distributed session (sequential tests in one
# interpreter) never reads the previous run's stale requests/decisions. Aligned
# across processes because every process builds exactly one coordinator per run
# at the same protocol point (its resilience facade construction).
_coordinator_builds = 0

# The process's live coordinator, so bounded channel waits can consult it
# without threading it through every construction site (see channel_options).
_active_coordinator: Optional["DistributedCoordinator"] = None


def active_coordinator() -> Optional["DistributedCoordinator"]:
    return _active_coordinator


def channel_abort_check() -> None:
    """The ``abort_check`` hook bounded channel waits run between poll slices:
    raises :class:`RankFailureError` the moment any peer has been declared dead
    (the coordinator's monitor thread keeps the verdict fresh)."""
    coord = _active_coordinator
    if coord is not None:
        coord.check_abort()


def channel_options(cfg: Any) -> Dict[str, Any]:
    """Keyword arguments for :class:`~sheeprl_tpu.parallel.distributed.BroadcastChannel`
    from the ``resilience.distributed.channel`` config group, with the abort
    hook attached — the decoupled loops build every channel through this."""
    ccfg = (((cfg.get("resilience") or {}).get("distributed") or {}).get("channel")) or {}
    return {
        "timeout_s": float(ccfg.get("timeout") or 1800.0),
        "poll_s": float(ccfg.get("poll") or 30.0),
        "abort_check": channel_abort_check,
    }


# ---------------------------------------------------------------------------------
# Pillars 1 + 2: preempt agreement and heartbeat-based rank-failure detection
# ---------------------------------------------------------------------------------


class DistributedCoordinator:
    """Per-process control-plane presence of a multi-process run. Construct via
    :func:`build_coordinator`; drive with :meth:`step` from the resilience
    facade's per-iteration hook. Threads: a heartbeat writer and a peer-failure
    monitor, both daemons, both stopped by :meth:`close`."""

    def __init__(
        self,
        rank: int,
        nprocs: int,
        *,
        agree_within_iters: int = 2,
        poll_interval: float = 0.25,
        heartbeat_interval: float = 2.0,
        heartbeat_timeout: float = 60.0,
        startup_timeout: float = 300.0,
        heartbeat_enabled: bool = True,
        emit: Optional[Callable[..., None]] = None,
        namespace: Optional[str] = None,
    ) -> None:
        global _coordinator_builds, _active_coordinator
        self.rank = int(rank)
        self.nprocs = int(nprocs)
        self.agree_within_iters = max(int(agree_within_iters), 1)
        self.poll_interval = max(float(poll_interval), 0.01)
        self.heartbeat_interval = max(float(heartbeat_interval), 0.05)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.startup_timeout = max(float(startup_timeout), self.heartbeat_timeout)
        self.heartbeat_enabled = bool(heartbeat_enabled)
        self._emit = emit or (lambda *a, **k: None)
        nonce = _coordinator_builds
        _coordinator_builds += 1
        attempt = os.environ.get("SHEEPRL_GANG_ATTEMPT", "0")
        self.ns = namespace or f"sheeprl_res/i{nonce}/a{attempt}"

        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._last_step: Optional[int] = None
        self._per_iter = 1
        self._rate: Optional[float] = None  # policy steps / second (EMA)
        self._last_step_time: Optional[float] = None
        self._last_poll = 0.0
        self._requests: Dict[int, int] = {}  # rank -> step at request time
        self._published_request = False
        self._published_decision = False
        self._decision: Optional[Dict[str, Any]] = None
        self._abort: Optional[Dict[str, Any]] = None
        self._abort_announced = False
        self._hb_counter = 0
        self._hb_seen: Dict[int, tuple] = {}  # rank -> (counter, last_change_monotonic)
        self._dead: Dict[int, float] = {}  # rank -> silent seconds at declaration
        self._threads: List[threading.Thread] = []
        _active_coordinator = self

    # -- lifecycle ---------------------------------------------------------------

    def start(self) -> "DistributedCoordinator":
        if self.heartbeat_enabled and not self._threads:
            for target, name in (
                (self._heartbeat_loop, "sheeprl-heartbeat"),
                (self._monitor_loop, "sheeprl-rank-monitor"),
            ):
                t = threading.Thread(target=target, name=name, daemon=True)
                t.start()
                self._threads.append(t)
        return self

    def close(self) -> None:
        global _active_coordinator
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        if _active_coordinator is self:
            _active_coordinator = None

    # -- the per-iteration hook --------------------------------------------------

    def step(self, policy_step: int, local_preempt: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if self._last_step is not None and policy_step > self._last_step:
                delta = policy_step - self._last_step
                self._per_iter = max(delta, 1)
                if self._last_step_time is not None and now > self._last_step_time:
                    inst = delta / (now - self._last_step_time)
                    self._rate = inst if self._rate is None else 0.5 * self._rate + 0.5 * inst
            self._last_step = int(policy_step)
            self._last_step_time = now
        client = _kv()
        if client is None:
            return
        if local_preempt and not self._published_request:
            self._publish_request(client, policy_step)
        # throttled control-plane poll; forced while a preempt is pending so the
        # leader's decision (and the final stop step) propagates promptly
        pending = local_preempt or self._requests or self._published_request
        if pending or now - self._last_poll >= self.poll_interval:
            self._last_poll = now
            self._poll_control(client)
        if self.rank == 0 and not self._published_decision and (local_preempt or self._requests):
            self._publish_decision(client, policy_step)

    def preempt_requested(self) -> bool:
        """The agreed verdict every rank folds into its checkpoint condition:
        True once the published decision's stop step is reached by the step
        sequence all ranks share (never on the local flag alone)."""
        with self._lock:
            decision = self._decision
            if decision is None:
                return False
            if self._last_step is None:
                return True  # preempted before the loop produced a step
            return self._last_step + self._per_iter >= int(decision["stop_step"])

    def decision(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._decision) if self._decision else None

    def check_abort(self) -> None:
        """Raise :class:`RankFailureError` if any peer has been declared dead."""
        with self._lock:
            abort = self._abort
        if abort is not None:
            raise RankFailureError(
                f"rank {abort.get('rank')} of this {self.nprocs}-process run was declared "
                f"dead ({abort.get('reason', 'heartbeat timeout')}); tearing down instead of "
                "hanging — the supervisor restarts the gang from the last consistent checkpoint"
            )

    def abort_info(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._abort) if self._abort else None

    # -- control-plane internals -------------------------------------------------

    def _publish_request(self, client: Any, policy_step: Optional[int]) -> None:
        try:
            _kv_set(
                client,
                f"{self.ns}/ctl/req/r{self.rank}",
                json.dumps({"rank": self.rank, "step": int(policy_step or 0)}),
            )
            self._published_request = True
        except Exception:
            pass  # retried from the next step()

    def _publish_decision(self, client: Any, policy_step: int) -> None:
        with self._lock:
            per_iter = self._per_iter
            rate = self._rate
            requests = dict(self._requests)
        # margin: the agreement window in iterations, PLUS however many steps
        # the gang covers in ~3 control-poll periods at the observed rate — so a
        # rank whose throttled poll fires late still sees the decision before
        # the step sequence reaches the stop step
        margin = self.agree_within_iters * per_iter
        if rate is not None:
            margin = max(margin, int(rate * 3.0 * self.poll_interval) + per_iter)
        stop_step = int(policy_step) + margin
        decision = {
            "stop_step": stop_step,
            "decided_at_step": int(policy_step),
            "requested_by": sorted(requests) if requests else [self.rank],
        }
        try:
            _kv_set(client, f"{self.ns}/ctl/decision", json.dumps(decision))
        except Exception:
            return  # retried from the next step()
        self._published_decision = True
        with self._lock:
            self._decision = decision
        from sheeprl_tpu.resilience import signals

        signals.mark_preempted()  # this rank's exit now reports "preempted"

    def _poll_control(self, client: Any) -> None:
        entries = _kv_dir(client, f"{self.ns}/ctl/")
        decision = None
        abort = None
        requests: Dict[int, int] = {}
        for key, value in entries:
            name = key.rsplit("/", 1)[-1]
            try:
                payload = json.loads(value)
            except (TypeError, ValueError):
                continue
            if name == "decision":
                decision = payload
            elif name == "abort":
                abort = payload
            elif name.startswith("r"):
                try:
                    requests[int(name[1:])] = int(payload.get("step") or 0)
                except (TypeError, ValueError):
                    continue
        decision_is_new = False
        with self._lock:
            if requests:
                self._requests.update(requests)
            if decision is not None and self._decision is None:
                self._decision = decision
                decision_is_new = True
            if abort is not None and self._abort is None:
                self._abort = abort
            abort_now = self._abort
        if decision_is_new:
            from sheeprl_tpu.resilience import signals

            # gang-level agreement: this rank exits preempted even though the
            # reclaim signal may only ever have reached a peer
            signals.mark_preempted()

        if abort_now is not None and not self._abort_announced:
            self._abort_announced = True
            self._emit(
                "health",
                status="rank_dead",
                rank=abort_now.get("rank"),
                reason=abort_now.get("reason"),
                observed_by=abort_now.get("observed_by"),
                critical=True,
            )

    # -- heartbeat threads ---------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        from sheeprl_tpu.resilience import faults

        while not self._stop.wait(self.heartbeat_interval):
            client = _kv()
            if client is None:
                continue
            if faults.heartbeat_stalled():
                continue  # injected zombie: alive but silent on the control plane
            self._hb_counter += 1
            with self._lock:
                step = self._last_step
            try:
                _kv_set(
                    client,
                    f"{self.ns}/hb/r{self.rank}",
                    json.dumps({"n": self._hb_counter, "step": step, "pid": os.getpid()}),
                )
            except Exception:
                continue  # a dying coordination service: peers time out anyway

    def _monitor_loop(self) -> None:
        started = time.monotonic()
        poll = max(min(self.heartbeat_interval, self.heartbeat_timeout / 4.0), 0.05)
        while not self._stop.wait(poll):
            client = _kv()
            if client is None:
                continue
            now = time.monotonic()
            seen: Dict[int, int] = {}
            for key, value in _kv_dir(client, f"{self.ns}/hb/"):
                name = key.rsplit("/", 1)[-1]
                if not name.startswith("r"):
                    continue
                try:
                    seen[int(name[1:])] = int(json.loads(value).get("n") or 0)
                except (TypeError, ValueError):
                    continue
            for peer in range(self.nprocs):
                if peer == self.rank or peer in self._dead:
                    continue
                counter = seen.get(peer)
                prev = self._hb_seen.get(peer)
                if counter is None and prev is None:
                    # never heartbeated: allow for process spawn + imports
                    if now - started > self.startup_timeout:
                        self._declare_dead(client, peer, now - started)
                    continue
                if counter is not None and (prev is None or counter != prev[0]):
                    self._hb_seen[peer] = (counter, now)
                elif now - prev[1] > self.heartbeat_timeout:
                    # stale counter — or a key that VANISHED after the peer had
                    # beat (dying KV range): both are the heartbeat-timeout
                    # window, never the startup one
                    self._declare_dead(client, peer, now - prev[1])

    def _declare_dead(self, client: Any, peer: int, silent_seconds: float) -> None:
        self._dead[peer] = silent_seconds
        abort = {
            "reason": "heartbeat timeout",
            "rank": peer,
            "silent_seconds": round(silent_seconds, 1),
            "observed_by": self.rank,
        }
        with self._lock:
            if self._abort is None:
                self._abort = abort
        try:
            _kv_set(client, f"{self.ns}/ctl/abort", json.dumps(abort))
        except Exception:
            pass
        if not self._abort_announced:
            self._abort_announced = True
            self._emit(
                "health",
                status="rank_dead",
                rank=peer,
                reason="heartbeat timeout",
                silent_seconds=round(silent_seconds, 1),
                observed_by=self.rank,
                critical=True,
            )


def build_coordinator(
    cfg: Any, *, rank: int, emit: Optional[Callable[..., None]] = None
) -> Optional[DistributedCoordinator]:
    """Build (and start) the process's coordinator for a multi-process run; None
    on single-process runs or when no jax.distributed client is up — every
    caller treats None as "no coordination plane" and falls back to PR 3's
    process-local semantics."""
    global _manifest_timeout
    nprocs = par_dist.process_count()
    if nprocs <= 1 or _kv() is None:
        return None
    dcfg = ((cfg.get("resilience") or {}).get("distributed")) or {}
    hcfg = dcfg.get("heartbeat") or {}
    _manifest_timeout = float(dcfg.get("manifest_timeout") or 120.0)
    return DistributedCoordinator(
        rank,
        nprocs,
        agree_within_iters=int(dcfg.get("agree_within_iters") or 2),
        poll_interval=float(dcfg.get("poll_interval") or 0.25),
        heartbeat_interval=float(hcfg.get("interval") or 2.0),
        heartbeat_timeout=float(hcfg.get("timeout") or 60.0),
        startup_timeout=float(hcfg.get("startup_timeout") or 300.0),
        heartbeat_enabled=bool(hcfg.get("enabled", True)),
        emit=emit,
    ).start()


# ---------------------------------------------------------------------------------
# Pillar 4: checkpoint-set consistency manifests
# ---------------------------------------------------------------------------------


def _write_manifest(path: str, payload: Dict[str, Any]) -> None:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)  # the begun-marker precedes the save
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


# effective manifest-ack deadline: resilience.distributed.manifest_timeout,
# latched by build_coordinator (the checkpoint callback has no cfg in scope)
_manifest_timeout = 120.0


@contextmanager
def checkpoint_manifest(fabric: Any, ckpt_path: str, timeout_s: Optional[float] = None):
    """Bracket a multi-process checkpoint write with the consistency manifest:
    ``complete: false`` lands atomically BEFORE the save, every participating
    rank acks through the KV store after it, and the writer re-writes the
    manifest with ``complete: true`` (the commit marker, written last) only
    once all acks arrived — so discovery never resolves a checkpoint some rank
    didn't finish. Single-process runs are a no-op (no new artifacts).

    The participating ranks are the processes of ``fabric``'s mesh — the set
    that shares ``fabric.save``'s write + barrier (the whole gang for SPMD, just
    the player for a decoupled role split)."""
    from sheeprl_tpu.resilience.discovery import checkpoint_step, manifest_path

    if par_dist.process_count() <= 1:
        yield
        return
    timeout_s = float(_manifest_timeout if timeout_s is None else timeout_s)
    try:
        expected = sorted({int(d.process_index) for d in fabric.mesh.devices.reshape(-1)})
    except Exception:
        expected = [int(par_dist.process_index())]
    me = int(par_dist.process_index())
    writer = me == min(expected)
    mpath = manifest_path(ckpt_path)
    step = checkpoint_step(ckpt_path)
    # keyed by the SHARED manifest name, never the per-rank ckpt basename
    # (ckpt_{step}_{rank}.ckpt differs per rank; the acks must rendezvous)
    token = f"sheeprl_res/ckptack/{os.path.basename(mpath)}/s{step}"
    if writer:
        if len(expected) > 1:
            # clear acks left by an EARLIER save of this same step (re-save of
            # a path, sequential runs on one coordination service): a stale ack
            # must never satisfy THIS save's rendezvous. Safe pre-save: peers
            # only ack after the collective save, which cannot complete before
            # the writer passes this point.
            client = _kv()
            if client is not None:
                try:
                    client.key_value_delete(token + "/")
                except Exception:
                    pass
        _write_manifest(
            mpath,
            {
                "schema": 1,
                "step": step,
                "path": os.path.basename(str(ckpt_path)),
                "ranks_expected": expected,
                "complete": False,
                "begun_at": round(time.time(), 3),
            },
        )
    yield  # the save itself; an exception here leaves the manifest incomplete

    client = _kv()
    if len(expected) > 1 and client is None:
        # the ack rendezvous is impossible (coordination service already torn
        # down): leave the manifest incomplete rather than commit a consistency
        # that was never verified — discovery falls back to the previous set
        return
    if len(expected) > 1:
        if not writer:
            try:
                _kv_set(client, f"{token}/r{me}", "1")
            except Exception:
                pass
            return
        # writer: bounded wait for every other rank's ack
        need = {r for r in expected if r != me}
        deadline = time.monotonic() + float(timeout_s)
        while need and time.monotonic() < deadline:
            acked = {
                int(k.rsplit("/", 1)[-1][1:])
                for k, _ in _kv_dir(client, token + "/")
                if k.rsplit("/", 1)[-1].startswith("r")
            }
            need -= acked
            if need:
                time.sleep(0.2)
        if need:
            # leave the manifest incomplete: a rank vanished mid-checkpoint, so
            # this set must never be resolved; discovery falls back to the
            # previous complete one
            return
    if writer or not expected or len(expected) == 1:
        _write_manifest(
            mpath,
            {
                "schema": 1,
                "step": step,
                "path": os.path.basename(str(ckpt_path)),
                "ranks_expected": expected,
                "ranks_committed": expected,
                "complete": True,
                "committed_at": round(time.time(), 3),
            },
        )
        if len(expected) > 1:
            try:
                client.key_value_delete(token + "/")  # consumed: no stale acks
            except Exception:
                pass


# ---------------------------------------------------------------------------------
# Pillar 3: gang supervision — N child processes under one supervisor
# ---------------------------------------------------------------------------------


class GangFailureError(RuntimeError):
    """The gang supervisor exhausted its restart budget on crashes."""


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _classify(exit_codes: Dict[int, int]) -> str:
    from sheeprl_tpu.resilience import signals

    if all(rc == 0 for rc in exit_codes.values()):
        return "completed"
    if all(rc in (0, signals.PREEMPTED_EXIT_CODE) for rc in exit_codes.values()):
        return "preempt"
    return "crash"


def supervise_gang(cfg: Any, overrides: Sequence[str]) -> str:
    """Launch ``resilience.distributed.gang.processes`` jax.distributed child
    processes running this config and supervise them as ONE unit: any child's
    crash/preempt tears down the survivors and — per the shared
    ``resilience.supervisor`` policy — restarts the whole gang from the newest
    manifest-consistent checkpoint, with the attempt counter stamped into every
    rank's telemetry stream. Returns ``"completed"`` or ``"preempted"``; raises
    :class:`GangFailureError` when the crash budget is exhausted."""
    import signal as _signal
    import subprocess
    import sys

    from sheeprl_tpu.obs.jsonl import JsonlEventSink
    from sheeprl_tpu.resilience import signals
    from sheeprl_tpu.resilience.discovery import find_latest_checkpoint
    from sheeprl_tpu.utils.logger import run_base_dir

    from sheeprl_tpu.resilience.restart_policy import RestartPolicy, run_restart_policy

    scfg = (cfg.get("resilience") or {}).get("supervisor") or {}
    dcfg = (cfg.get("resilience") or {}).get("distributed") or {}
    gcfg = dcfg.get("gang") or {}
    n = int(gcfg.get("processes") or 0)
    if n < 2:
        raise ValueError("supervise_gang needs resilience.distributed.gang.processes >= 2")
    # restart/backoff/giveup policy shared with the in-process supervisor
    # (resilience/restart_policy.py) — only the attempt mechanics differ here
    policy = RestartPolicy.from_cfg(scfg)
    grace = float(gcfg.get("grace") or 20.0)

    run_base = run_base_dir(cfg.root_dir, cfg.run_name)
    os.makedirs(run_base, exist_ok=True)
    log_dir = run_base / "gang"
    os.makedirs(log_dir, exist_ok=True)
    jsonl_enabled = bool(((cfg.get("metric") or {}).get("telemetry") or {}).get("jsonl", True))
    jsonl_path = str(run_base / "telemetry.jsonl")

    sink: Optional[JsonlEventSink] = None

    def emit(event: str, **fields: Any) -> None:
        nonlocal sink
        if not jsonl_enabled:
            return
        if sink is None:
            try:
                sink = JsonlEventSink(jsonl_path)
            except OSError:
                return
        fields.setdefault("attempt", policy.attempt)
        sink.emit(event, **fields)

    # identity pins every attempt shares: resolved run identity (a timestamped
    # run_name must not re-resolve per child), one run-base telemetry stream,
    # and in-process supervision off (the gang owns restart policy)
    base_args = [str(o) for o in overrides] + [
        f"root_dir={cfg.root_dir}",
        f"run_name={cfg.run_name}",
        "resilience.supervisor.enabled=false",
    ]
    if jsonl_enabled:
        base_args.append(f"metric.telemetry.jsonl_path={jsonl_path}")
    fallback_resume = cfg.checkpoint.get("resume_from") or None

    live_procs: List[subprocess.Popen] = []

    def spawn(attempt_args: List[str], attempt: int) -> List[subprocess.Popen]:
        port = _free_port()
        procs: List[subprocess.Popen] = []
        accelerator = str((cfg.get("fabric") or {}).get("accelerator", "auto")).lower()
        for rank in range(n):
            env = dict(os.environ)
            env["SHEEPRL_COORDINATOR"] = f"127.0.0.1:{port}"
            env["SHEEPRL_GANG_PROCESSES"] = str(n)
            env["SHEEPRL_GANG_RANK"] = str(rank)
            env["SHEEPRL_GANG_ATTEMPT"] = str(attempt)
            if accelerator == "cpu":
                # __main__'s bring-up must pin the platform BEFORE initialize:
                # a cpu gang must never let a child touch an accelerator backend
                env["SHEEPRL_GANG_PLATFORM"] = "cpu"
            log_path = log_dir / f"attempt{attempt}.rank{rank}.log"
            log_fh = open(log_path, "ab")
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m", "sheeprl_tpu"] + attempt_args,
                    env=env,
                    stdout=log_fh,
                    stderr=subprocess.STDOUT,
                    # own session: a process-group SIGTERM/SIGINT (pod reclaim,
                    # Ctrl-C) reaches only the supervisor, whose forward is then
                    # each child's FIRST signal — group delivery plus the
                    # forward would be the second, i.e. an instant force-exit
                    # before any emergency checkpoint
                    start_new_session=True,
                )
            )
            log_fh.close()  # the child holds the descriptor
        live_procs[:] = procs
        return procs

    def wait_gang(procs: List[subprocess.Popen]) -> tuple:
        """Wait for every child; after the first exit survivors get ``grace``
        seconds to finish on their own, then SIGTERM, then SIGKILL. Returns
        ({rank: exit_code}, self_exited_ranks, forwarded) — self_exited holds
        the ranks that exited BEFORE any teardown escalation (the culprits of a
        failed attempt, as opposed to healthy survivors the supervisor itself
        terminated), and forwarded says a preemption was relayed to the gang."""
        forwarded = False
        first_exit: Optional[float] = None
        terminated = killed = False
        self_exited: set = set()
        while True:
            if signals.preemption_requested() and not forwarded:
                forwarded = True
                emit("gang", status="preempt_forward", processes=n)
                for p in procs:
                    if p.poll() is None:
                        try:
                            p.send_signal(_signal.SIGTERM)
                        except OSError:
                            pass
                # the teardown clock deliberately does NOT start here: children
                # need the agreed stop step + the emergency save, which can
                # exceed `grace` (a big replay buffer). The first child to
                # actually exit starts the clock for the stragglers — and the
                # children's own bounded channel/KV ops keep that first exit
                # finite even when a peer is wedged.
            rcs = [p.poll() for p in procs]
            if not terminated and not killed:
                self_exited.update(i for i, rc in enumerate(rcs) if rc is not None)
            if all(rc is not None for rc in rcs):
                return {i: int(rc) for i, rc in enumerate(rcs)}, self_exited, forwarded
            # the first exit — clean or not — starts the teardown clock: healthy
            # staggered completion finishes well inside `grace`, a survivor
            # blocked on a dead peer does not and gets escalated
            if first_exit is None and any(rc is not None for rc in rcs):
                first_exit = time.monotonic()
            if first_exit is not None:
                waited = time.monotonic() - first_exit
                # after a forwarded preempt each child already HOLDS its first
                # signal — a second SIGTERM is the handler's force-exit path and
                # would kill an in-flight emergency save, so the escalation
                # skips straight to SIGKILL for stragglers
                if waited > grace and not terminated and not forwarded:
                    terminated = True
                    for p in procs:
                        if p.poll() is None:
                            try:
                                p.send_signal(_signal.SIGTERM)
                            except OSError:
                                pass
                # the SIGTERM above was the survivor's FIRST signal — it now
                # writes its own emergency checkpoint, which needs a window
                # that scales with grace, not a fixed 10 s
                elif waited > grace + max(10.0, grace) and not killed:
                    killed = True
                    for p in procs:
                        if p.poll() is None:
                            try:
                                p.kill()
                            except OSError:
                                pass
            time.sleep(0.2)

    def run_attempt(attempt: int):
        attempt_args = list(base_args)
        if attempt > 0:
            resume_from = find_latest_checkpoint(str(run_base)) or fallback_resume
            # a fault that (presumably) fired must not ride into the retry —
            # the gang cannot see the child-process fired-ledger, so strip
            # unconditionally, mirroring the in-process supervisor
            attempt_args = [
                a for a in attempt_args if not a.startswith("checkpoint.resume_from=")
            ]
            attempt_args += ["resilience.fault.kind=null"]
            if resume_from is not None:
                attempt_args.append(f"checkpoint.resume_from={resume_from}")
        attempt_args.append(f"metric.telemetry.attempt={attempt}")

        emit("gang", status="spawn", processes=n, args_tail=attempt_args[-3:])
        exit_codes, self_exited, forwarded = wait_gang(spawn(attempt_args, attempt))
        outcome = _classify(exit_codes)
        if (
            outcome == "crash"
            and forwarded
            and all(
                exit_codes[r] in (0, signals.PREEMPTED_EXIT_CODE) for r in self_exited
            )
        ):
            # stragglers the teardown SIGKILLed during a forwarded preempt
            # are reclaim collateral, not crashes: every rank that exited on
            # its own cooperated, so the attempt ended by preemption
            outcome = "preempt"
        # attribution: the ranks that FAILED ON THEIR OWN — never the
        # survivors the teardown escalation itself SIGTERM/SIGKILLed, not
        # cooperative preempt exits (75 is "reschedule me", not death), and
        # not healthy ranks reporting a PEER's death (77, RankFailureError)
        dead_ranks = {
            str(r): rc
            for r, rc in exit_codes.items()
            if rc not in (0, signals.PREEMPTED_EXIT_CODE, signals.RANK_FAILED_EXIT_CODE)
            and r in self_exited
        }
        emit(
            "gang",
            status="attempt_exit",
            exit_codes={str(r): rc for r, rc in exit_codes.items()},
            outcome=outcome,
        )
        return outcome, {"dead_ranks": dead_ranks, "exit_codes": exit_codes}

    def restart_fields(attempt, outcome, info):
        resume_preview = find_latest_checkpoint(str(run_base)) or fallback_resume
        return {
            "dead_ranks": info["dead_ranks"],
            "resume_from": str(resume_preview) if resume_preview else None,
        }

    def giveup_fields(info):
        return {"dead_ranks": info["dead_ranks"]}

    def on_giveup(outcome, info):
        if outcome == "crash":
            raise GangFailureError(
                f"gang of {n} crashed {policy.attempt - 1} time(s) past the restart "
                f"budget (last exit codes: {info['exit_codes']}); see {log_dir}"
            )
        return "preempted"

    try:
        return run_restart_policy(
            policy,
            run_attempt,
            emit,
            restart_fields=restart_fields,
            giveup_fields=giveup_fields,
            on_giveup=on_giveup,
        )
    finally:
        # never orphan the gang: children run in their OWN sessions (see
        # spawn), so a forced supervisor unwind (second Ctrl-C, crash) is the
        # only thing standing between a wedged rank and immortality
        for p in live_procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 5.0
        while any(p.poll() is None for p in live_procs) and time.monotonic() < deadline:
            time.sleep(0.1)
        for p in live_procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        if sink is not None:
            sink.close()


def gang_processes(cfg: Any) -> int:
    """The configured gang size (0 when gang mode is off)."""
    gcfg = (((cfg.get("resilience") or {}).get("distributed") or {}).get("gang")) or {}
    return int(gcfg.get("processes") or 0)
