"""The shared restart/backoff/giveup policy loop of both supervisors.

``resilience/supervisor.py`` (in-process, single-process topologies) and
``resilience/distributed.py``'s ``supervise_gang`` (multi-process gangs) used to
each carry their own copy of the same state machine: check for a preemption
that landed BETWEEN attempts, run an attempt, classify its outcome
(``completed`` / ``preempt`` / ``crash``), decide return-vs-retry under
``restart_on_preempt``, count attempts against ``max_restarts``, emit the
``restart`` / ``giveup`` / ``supervisor`` events, and sleep the exponential
backoff. Only the attempt MECHANICS differ (re-enter ``run_fn`` with a rebuilt
config vs respawn a process gang), so the policy loop lives here once and the
callers plug in callbacks:

- ``run_attempt(attempt) -> (outcome, info)`` — run one attempt; ``info`` is
  an opaque dict threaded to the field builders (error object, dead ranks...).
- ``restart_fields(attempt, outcome, info) -> dict`` — extra fields for the
  ``restart`` event (resume path, error repr, dead ranks).
- ``giveup_fields(info) -> dict`` — extra fields for the ``giveup`` event.
- ``on_giveup(outcome, info)`` — terminal action once the budget is exhausted:
  re-raise the stored error / raise ``GangFailureError`` on a crash, return
  ``"preempted"`` on a preemption.

``policy.attempt`` is the LIVE attempt counter: the callers' ``emit`` wrappers
read it to stamp their own events (spawn, attempt_exit) with the attempt they
describe, exactly as their old nonlocal counters did.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Mapping, Tuple

from sheeprl_tpu.resilience import signals

__all__ = ["RestartPolicy", "run_restart_policy"]


@dataclass
class RestartPolicy:
    """The ``resilience.supervisor`` policy knobs plus the live attempt counter."""

    max_restarts: int = 3
    backoff: float = 1.0
    backoff_cap: float = 60.0
    restart_on_preempt: bool = True
    attempt: int = 0

    @classmethod
    def from_cfg(cls, scfg: Mapping[str, Any]) -> "RestartPolicy":
        get = scfg.get if hasattr(scfg, "get") else (lambda k, d=None: d)
        return cls(
            max_restarts=int(get("max_restarts", 3)),
            backoff=float(get("backoff", 1.0)),
            backoff_cap=float(get("backoff_cap", 60.0)),
            restart_on_preempt=bool(get("restart_on_preempt", True)),
        )

    def backoff_delay(self) -> float:
        """Exponential backoff for the CURRENT (already-incremented) attempt."""
        if self.backoff <= 0:
            return 0.0
        return min(self.backoff * (2.0 ** (self.attempt - 1)), self.backoff_cap)


def run_restart_policy(
    policy: RestartPolicy,
    run_attempt: Callable[[int], Tuple[str, Dict[str, Any]]],
    emit: Callable[..., None],
    *,
    restart_fields: Callable[[int, str, Dict[str, Any]], Dict[str, Any]],
    giveup_fields: Callable[[Dict[str, Any]], Dict[str, Any]],
    on_giveup: Callable[[str, Dict[str, Any]], str],
) -> str:
    """Drive attempts under ``policy`` until completed / preempted / budget
    exhausted. Returns ``"completed"`` or ``"preempted"``; ``on_giveup`` may
    raise instead of returning (the crash-budget path)."""
    while True:
        # a SIGTERM that landed BETWEEN attempts (teardown, backoff sleep) is a
        # real reclaim: blindly resetting it would relaunch a full attempt on a
        # dying node — honor the same policy as an in-run preemption
        if signals.preemption_requested() and not policy.restart_on_preempt:
            emit(
                "supervisor",
                status="preempted",
                attempts=policy.attempt,
                between_attempts=True,
            )
            return "preempted"
        signals.reset_preemption()

        outcome, info = run_attempt(policy.attempt)
        if outcome == "completed":
            if policy.attempt > 0:
                emit("supervisor", status="completed", attempts=policy.attempt)
            return "completed"
        if outcome == "preempt" and not policy.restart_on_preempt:
            emit("supervisor", status="preempted", attempts=policy.attempt)
            return "preempted"

        policy.attempt += 1
        if policy.attempt > policy.max_restarts:
            emit(
                "giveup",
                reason=outcome,
                attempts=policy.attempt - 1,
                max_restarts=policy.max_restarts,
                **giveup_fields(info),
            )
            return on_giveup(outcome, info)

        delay = policy.backoff_delay()
        emit(
            "restart",
            attempt=policy.attempt,
            reason=outcome,
            backoff_seconds=round(delay, 3),
            **restart_fields(policy.attempt, outcome, info),
        )
        if delay > 0:
            time.sleep(delay)
