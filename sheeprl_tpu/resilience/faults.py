"""Deterministic, config-driven fault injection (``resilience.fault``).

The whole recovery path — emergency checkpoint on preemption, supervisor
auto-resume, crash-window checkpoint fallbacks, env crash-restart — is only
trustworthy if it is *exercised*, and real preemptions/crashes are neither
deterministic nor CPU-reproducible. ``resilience.fault={kind, at_policy_step}``
injects exactly one fault at a configured policy step so tier-1 CPU tests drive
end-to-end recovery (MindSpeed RL makes restartable dataflow a tested
first-class requirement for the same reason):

- ``crash``      — raise :class:`InjectedFaultError` from the loop's resilience
                   hook: an uncaught hard crash mid-training;
- ``sigterm``    — deliver a real SIGTERM to this process: the cooperative
                   preemption path (handler → flag → emergency checkpoint →
                   preempted exit), exactly as a pod reclaim would;
- ``env_step``   — arm a one-shot exception inside ``env.step`` (the env fault
                   wrapper in utils/env.py): exercises ``RestartOnException``
                   where present, an ordinary crash elsewhere;
- ``ckpt_kill``  — raise from *inside* the next checkpoint write, at the exact
                   point where a kill would leave the crash-window on-disk state
                   (pickle: tmp written, not yet renamed; sharded: sidecar
                   committed, orbax directory not): recovery must skip the torn
                   artifacts and fall back to the previous valid checkpoint.

Every fault fires at most once per process (the in-process supervisor restarts
within the same process, so a resumed attempt replaying policy steps below
``at_policy_step`` must not re-trigger); the supervisor additionally strips the
fault from retry configs, covering cross-process restarts.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from sheeprl_tpu.resilience import signals

FAULT_KINDS = ("crash", "sigterm", "env_step", "ckpt_kill")


class InjectedFaultError(RuntimeError):
    """The deterministic stand-in for a hard crash."""


_lock = threading.Lock()
_fired: Dict[tuple, int] = {}  # (kind, at_policy_step) -> policy step it fired at
_env_fault_armed = threading.Event()


def normalize_fault_cfg(resilience_cfg: Any) -> Optional[Dict[str, int]]:
    """``{kind, at}`` from ``cfg.resilience.fault``, or None when off. Raises on
    an unknown kind so config policing fails before the run launches."""
    fault = (resilience_cfg or {}).get("fault") or {}
    kind = fault.get("kind")
    if kind is None or str(kind).lower() in ("none", "null", "off", "false"):
        return None
    kind = str(kind).lower()
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"unknown resilience.fault.kind {kind!r}; available: none, " + ", ".join(FAULT_KINDS)
        )
    return {"kind": kind, "at": int(fault.get("at_policy_step") or 0)}


def has_fired() -> bool:
    with _lock:
        return bool(_fired)


def reset_faults() -> None:
    """Forget fired faults and disarm pending ones (test isolation helper)."""
    with _lock:
        _fired.clear()
    _env_fault_armed.clear()
    from sheeprl_tpu.utils import checkpoint

    if checkpoint._fault_hook is _ckpt_kill_hook:
        checkpoint._fault_hook = None


def consume_env_fault() -> bool:
    """One-shot poll the env fault wrapper runs per ``step()`` call. Process-
    global, so it reaches in-process (sync) vector envs; subprocess (async)
    vector envs never see the armed flag — documented in howto/fault_tolerance."""
    if _env_fault_armed.is_set():
        _env_fault_armed.clear()
        return True
    return False


def _ckpt_kill_hook(stage: str, path: str) -> None:
    from sheeprl_tpu.utils import checkpoint

    checkpoint._fault_hook = None  # one shot
    raise InjectedFaultError(
        f"resilience.fault=ckpt_kill: injected kill during checkpoint write "
        f"(stage={stage}, path={path})"
    )


class FaultPlan:
    """The armed fault a :class:`ResilienceMonitor` drives from its per-iteration
    hook. ``maybe_fire`` is idempotent across restarts (process-global ledger)."""

    def __init__(self, kind: str, at_policy_step: int) -> None:
        self.kind = kind
        self.at = int(at_policy_step)

    def maybe_fire(self, policy_step: int, emit: Callable[..., None]) -> None:
        if policy_step < self.at:
            return
        key = (self.kind, self.at)
        with _lock:
            if key in _fired:
                return
            _fired[key] = int(policy_step)
        emit("fault", step=policy_step, kind=self.kind, at_policy_step=self.at)
        if self.kind == "crash":
            raise InjectedFaultError(
                f"resilience.fault=crash: injected hard crash at policy step {policy_step}"
            )
        if self.kind == "sigterm":
            signals.request_preemption()
        elif self.kind == "env_step":
            _env_fault_armed.set()
        elif self.kind == "ckpt_kill":
            from sheeprl_tpu.utils import checkpoint

            checkpoint._fault_hook = _ckpt_kill_hook


def build_fault_plan(resilience_cfg: Any) -> Optional[FaultPlan]:
    spec = normalize_fault_cfg(resilience_cfg)
    if spec is None:
        return None
    return FaultPlan(spec["kind"], spec["at"])
