"""Deterministic, config-driven fault injection (``resilience.fault``).

The whole recovery path — emergency checkpoint on preemption, supervisor
auto-resume, crash-window checkpoint fallbacks, env crash-restart — is only
trustworthy if it is *exercised*, and real preemptions/crashes are neither
deterministic nor CPU-reproducible. ``resilience.fault={kind, at_policy_step}``
injects exactly one fault at a configured policy step so tier-1 CPU tests drive
end-to-end recovery (MindSpeed RL makes restartable dataflow a tested
first-class requirement for the same reason):

- ``crash``      — raise :class:`InjectedFaultError` from the loop's resilience
                   hook: an uncaught hard crash mid-training;
- ``sigterm``    — deliver a real SIGTERM to this process: the cooperative
                   preemption path (handler → flag → emergency checkpoint →
                   preempted exit), exactly as a pod reclaim would;
- ``env_step``   — arm a one-shot exception inside ``env.step`` (the env fault
                   wrapper in utils/env.py): exercises ``RestartOnException``
                   where present, an ordinary crash elsewhere;
- ``ckpt_kill``  — raise from *inside* the next checkpoint write, at the exact
                   point where a kill would leave the crash-window on-disk state
                   (pickle: tmp written, not yet renamed; sharded: sidecar
                   committed, orbax directory not): recovery must skip the torn
                   artifacts and fall back to the previous valid checkpoint.

Rank-targeted faults (multi-process runs; ``resilience.fault.rank`` selects the
target process index, default 0 — the driving rank, which keeps the original
single-process semantics):

- ``kill_rank``        — SIGKILL this process at the configured step: a dead
                         peer with no cleanup, no channel sentinel, no exit
                         handshake — the failure mode heartbeat detection and
                         gang supervision exist for;
- ``stale_heartbeat``  — stop publishing heartbeats while the process keeps
                         running: a zombie rank, detected by the peers'
                         failure monitors;
- ``channel_drop``     — the target's next channel ``put`` is silently lost on
                         the wire (the sequence advances, no payload lands):
                         receivers must exhaust their bounded timeout instead
                         of hanging forever.

Every fault fires at most once per process (the in-process supervisor restarts
within the same process, so a resumed attempt replaying policy steps below
``at_policy_step`` must not re-trigger); the supervisors additionally strip the
fault from retry configs, covering cross-process restarts.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from sheeprl_tpu.resilience import signals

FAULT_KINDS = (
    "crash",
    "sigterm",
    "env_step",
    "ckpt_kill",
    "kill_rank",
    "stale_heartbeat",
    "channel_drop",
)


class InjectedFaultError(RuntimeError):
    """The deterministic stand-in for a hard crash."""


_lock = threading.Lock()
_fired: Dict[tuple, int] = {}  # (kind, at_policy_step) -> policy step it fired at
_env_fault_armed = threading.Event()
_heartbeat_stale = threading.Event()
_channel_drop_armed = threading.Event()


def normalize_fault_cfg(resilience_cfg: Any) -> Optional[Dict[str, Any]]:
    """``{kind, at, rank}`` from ``cfg.resilience.fault``, or None when off.
    Raises on an unknown kind so config policing fails before the run launches.
    ``rank`` is the target process index; None means the driving rank 0."""
    fault = (resilience_cfg or {}).get("fault") or {}
    kind = fault.get("kind")
    if kind is None or str(kind).lower() in ("none", "null", "off", "false"):
        return None
    kind = str(kind).lower()
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"unknown resilience.fault.kind {kind!r}; available: none, " + ", ".join(FAULT_KINDS)
        )
    rank = fault.get("rank")
    return {
        "kind": kind,
        "at": int(fault.get("at_policy_step") or 0),
        "rank": None if rank is None else int(rank),
    }


def has_fired() -> bool:
    with _lock:
        return bool(_fired)


def reset_faults() -> None:
    """Forget fired faults and disarm pending ones (test isolation helper)."""
    with _lock:
        _fired.clear()
    _env_fault_armed.clear()
    _heartbeat_stale.clear()
    _channel_drop_armed.clear()
    from sheeprl_tpu.utils import checkpoint

    if checkpoint._fault_hook is _ckpt_kill_hook:
        checkpoint._fault_hook = None
    from sheeprl_tpu.parallel import distributed as par_dist

    if par_dist._channel_drop_hook is _consume_channel_drop:
        par_dist._channel_drop_hook = None


def heartbeat_stalled() -> bool:
    """Whether the ``stale_heartbeat`` fault silenced this process's heartbeat
    writer (permanent once fired — a zombie does not recover)."""
    return _heartbeat_stale.is_set()


def _consume_channel_drop() -> bool:
    """One-shot poll the channel source runs per ``put`` (see
    ``parallel/distributed.py``'s ``_channel_drop_hook``)."""
    if _channel_drop_armed.is_set():
        _channel_drop_armed.clear()
        return True
    return False


def consume_env_fault() -> bool:
    """One-shot poll the env fault wrapper runs per ``step()`` call. Process-
    global, so it reaches in-process (sync) vector envs; subprocess (async)
    vector envs never see the armed flag — documented in howto/fault_tolerance."""
    if _env_fault_armed.is_set():
        _env_fault_armed.clear()
        return True
    return False


def _ckpt_kill_hook(stage: str, path: str) -> None:
    from sheeprl_tpu.utils import checkpoint

    checkpoint._fault_hook = None  # one shot
    raise InjectedFaultError(
        f"resilience.fault=ckpt_kill: injected kill during checkpoint write "
        f"(stage={stage}, path={path})"
    )


class FaultPlan:
    """The armed fault a resilience facade drives from its per-iteration hook.
    ``maybe_fire`` is idempotent across restarts (process-global ledger)."""

    def __init__(self, kind: str, at_policy_step: int, rank: Optional[int] = None) -> None:
        self.kind = kind
        self.at = int(at_policy_step)
        self.rank = rank

    def maybe_fire(self, policy_step: int, emit: Callable[..., None]) -> None:
        if policy_step < self.at:
            return
        key = (self.kind, self.at)
        with _lock:
            if key in _fired:
                return
            _fired[key] = int(policy_step)
        emit("fault", step=policy_step, kind=self.kind, at_policy_step=self.at, rank=self.rank)
        if self.kind == "crash":
            raise InjectedFaultError(
                f"resilience.fault=crash: injected hard crash at policy step {policy_step}"
            )
        if self.kind == "sigterm":
            signals.request_preemption()
        elif self.kind == "env_step":
            _env_fault_armed.set()
        elif self.kind == "ckpt_kill":
            from sheeprl_tpu.utils import checkpoint

            checkpoint._fault_hook = _ckpt_kill_hook
        elif self.kind == "kill_rank":
            # a DEAD peer, not a crashing one: no exception path, no channel
            # sentinel, no exit handshake — SIGKILL bypasses every cleanup
            import os
            import signal as _stdlib_signal

            os.kill(os.getpid(), _stdlib_signal.SIGKILL)
        elif self.kind == "stale_heartbeat":
            _heartbeat_stale.set()
        elif self.kind == "channel_drop":
            from sheeprl_tpu.parallel import distributed as par_dist

            _channel_drop_armed.set()
            par_dist._channel_drop_hook = _consume_channel_drop


def build_fault_plan(
    resilience_cfg: Any, process_rank: Optional[int] = None
) -> Optional[FaultPlan]:
    """The armed plan for THIS process, or None. ``fault.rank`` targets one
    process of a multi-process run (default 0, the driving rank — which keeps
    single-process semantics unchanged); a non-matching rank arms nothing."""
    spec = normalize_fault_cfg(resilience_cfg)
    if spec is None:
        return None
    target = 0 if spec["rank"] is None else int(spec["rank"])
    if process_rank is not None and target != int(process_rank):
        return None
    return FaultPlan(spec["kind"], spec["at"], rank=target)
