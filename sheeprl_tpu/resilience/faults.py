"""Deterministic, config-driven fault injection (``resilience.fault``).

The whole recovery path — emergency checkpoint on preemption, supervisor
auto-resume, crash-window checkpoint fallbacks, env crash-restart — is only
trustworthy if it is *exercised*, and real preemptions/crashes are neither
deterministic nor CPU-reproducible. ``resilience.fault={kind, at_policy_step}``
injects exactly one fault at a configured policy step so tier-1 CPU tests drive
end-to-end recovery (MindSpeed RL makes restartable dataflow a tested
first-class requirement for the same reason):

- ``crash``      — raise :class:`InjectedFaultError` from the loop's resilience
                   hook: an uncaught hard crash mid-training;
- ``sigterm``    — deliver a real SIGTERM to this process: the cooperative
                   preemption path (handler → flag → emergency checkpoint →
                   preempted exit), exactly as a pod reclaim would;
- ``env_step``   — arm a one-shot exception inside ``env.step`` (the env fault
                   wrapper in utils/env.py): exercises ``RestartOnException``
                   where present, an ordinary crash elsewhere;
- ``ckpt_kill``  — raise from *inside* the next checkpoint write, at the exact
                   point where a kill would leave the crash-window on-disk state
                   (pickle: tmp written, not yet renamed; sharded: sidecar
                   committed, orbax directory not): recovery must skip the torn
                   artifacts and fall back to the previous valid checkpoint.
- ``lr_spike``   — deterministic LEARNING pathology: before the next train
                   round the loop scales every float parameter leaf by
                   ``fault.factor`` (default 32), emulating one grossly
                   mis-scaled update (a transient learning-rate spike). The
                   run keeps running — nothing crashes — but the loss/gradient
                   landscape explodes, which is exactly what the training-health
                   detectors (``grad_explosion`` first) must catch end-to-end,
                   the same way crash/sigterm/ckpt_kill smoke the recovery path.

Rank-targeted faults (multi-process runs; ``resilience.fault.rank`` selects the
target process index, default 0 — the driving rank, which keeps the original
single-process semantics):

- ``kill_rank``        — SIGKILL this process at the configured step: a dead
                         peer with no cleanup, no channel sentinel, no exit
                         handshake — the failure mode heartbeat detection and
                         gang supervision exist for;
- ``stale_heartbeat``  — stop publishing heartbeats while the process keeps
                         running: a zombie rank, detected by the peers'
                         failure monitors;
- ``channel_drop``     — the target's next channel ``put`` is silently lost on
                         the wire (the sequence advances, no payload lands):
                         receivers must exhaust their bounded timeout instead
                         of hanging forever.

Serving faults (``sheeprl.py serve`` — the server's tick loop drives
``maybe_fire`` with SERVED steps as the policy-step axis):

- ``slow_tick``        — every tick after the trigger pays a ``fault.factor``
                         millisecond stall (default 32ms): a degraded device /
                         noisy neighbor; the ``latency_regression`` and
                         ``deadline_misses`` detectors must see it;
- ``session_flood``    — a burst of ``fault.factor`` synthetic sessions storms
                         admission at once: overload shedding (``serve.max_queue``)
                         must reject the excess and the ``shed_rate`` detector
                         must flag the window;
- ``reload_torn``      — the hot-reload path's next checkpoint candidate is
                         torn (corrupted on disk before the read): integrity
                         validation must reject it, the OLD params must keep
                         serving, and ``reload_stall`` must surface it.

Every fault fires at most once per process (the in-process supervisor restarts
within the same process, so a resumed attempt replaying policy steps below
``at_policy_step`` must not re-trigger); the supervisors additionally strip the
fault from retry configs, covering cross-process restarts.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from sheeprl_tpu.resilience import signals

FAULT_KINDS = (
    "crash",
    "sigterm",
    "env_step",
    "ckpt_kill",
    "lr_spike",
    "kill_rank",
    "stale_heartbeat",
    "channel_drop",
    "slow_tick",
    "session_flood",
    "reload_torn",
)

DEFAULT_LR_SPIKE_FACTOR = 32.0


class InjectedFaultError(RuntimeError):
    """The deterministic stand-in for a hard crash."""


_lock = threading.Lock()
_fired: Dict[tuple, int] = {}  # (kind, at_policy_step) -> policy step it fired at
_env_fault_armed = threading.Event()
_heartbeat_stale = threading.Event()
_channel_drop_armed = threading.Event()
_learn_fault_factor: list = [None]  # armed lr_spike scale, consumed by the next train round
_slow_tick_seconds: list = [0.0]  # permanent per-tick stall once slow_tick fired
_session_flood: list = [None]  # one-shot burst size for the serving flood
_reload_torn_armed = threading.Event()


def normalize_fault_cfg(resilience_cfg: Any) -> Optional[Dict[str, Any]]:
    """``{kind, at, rank}`` from ``cfg.resilience.fault``, or None when off.
    Raises on an unknown kind so config policing fails before the run launches.
    ``rank`` is the target process index; None means the driving rank 0."""
    fault = (resilience_cfg or {}).get("fault") or {}
    kind = fault.get("kind")
    if kind is None or str(kind).lower() in ("none", "null", "off", "false"):
        return None
    kind = str(kind).lower()
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"unknown resilience.fault.kind {kind!r}; available: none, " + ", ".join(FAULT_KINDS)
        )
    rank = fault.get("rank")
    return {
        "kind": kind,
        "at": int(fault.get("at_policy_step") or 0),
        "rank": None if rank is None else int(rank),
        "factor": float(fault.get("factor") or DEFAULT_LR_SPIKE_FACTOR),
    }


def has_fired() -> bool:
    with _lock:
        return bool(_fired)


def reset_faults() -> None:
    """Forget fired faults and disarm pending ones (test isolation helper)."""
    with _lock:
        _fired.clear()
    _env_fault_armed.clear()
    _heartbeat_stale.clear()
    _channel_drop_armed.clear()
    _learn_fault_factor[0] = None
    _slow_tick_seconds[0] = 0.0
    _session_flood[0] = None
    _reload_torn_armed.clear()
    from sheeprl_tpu.utils import checkpoint

    if checkpoint._fault_hook is _ckpt_kill_hook:
        checkpoint._fault_hook = None
    from sheeprl_tpu.parallel import distributed as par_dist

    if par_dist._channel_drop_hook is _consume_channel_drop:
        par_dist._channel_drop_hook = None


def heartbeat_stalled() -> bool:
    """Whether the ``stale_heartbeat`` fault silenced this process's heartbeat
    writer (permanent once fired — a zombie does not recover)."""
    return _heartbeat_stale.is_set()


def _consume_channel_drop() -> bool:
    """One-shot poll the channel source runs per ``put`` (see
    ``parallel/distributed.py``'s ``_channel_drop_hook``)."""
    if _channel_drop_armed.is_set():
        _channel_drop_armed.clear()
        return True
    return False


def consume_learn_fault() -> Optional[float]:
    """One-shot poll the loops run right before a train round: the armed
    ``lr_spike`` factor, or None. Consuming disarms it — the spike is exactly
    one mis-scaled 'update', not a persistent corruption."""
    with _lock:
        factor = _learn_fault_factor[0]
        _learn_fault_factor[0] = None
    return factor


def apply_armed_learn_fault(tree: Any) -> Any:
    """Apply a pending ``lr_spike`` to a parameter pytree: every float leaf is
    scaled by the armed factor (identity when nothing is armed — the loops call
    this unconditionally before each train round). Returns a NEW tree of fresh
    arrays, so donation of the inputs stays sound."""
    factor = consume_learn_fault()
    if factor is None:
        return tree
    import jax
    import jax.numpy as jnp

    def scale(leaf: Any) -> Any:
        if hasattr(leaf, "dtype") and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            return leaf * jnp.asarray(factor, dtype=jnp.asarray(leaf).dtype)
        return leaf

    return jax.tree_util.tree_map(scale, tree)


def slow_tick_seconds() -> float:
    """The armed per-tick stall (``slow_tick``), in seconds; 0 when off. NOT
    one-shot — a degraded device stays degraded, which is what the sustained
    latency/deadline detectors need to see."""
    return _slow_tick_seconds[0]


def consume_session_flood() -> Optional[int]:
    """One-shot poll the serving tick loop runs after ``maybe_fire``: the armed
    ``session_flood`` burst size, or None."""
    with _lock:
        count = _session_flood[0]
        _session_flood[0] = None
    return count


def consume_reload_torn() -> bool:
    """One-shot poll the hot-reload source runs before reading a checkpoint
    candidate: True exactly once after ``reload_torn`` fired — the source then
    tears the candidate on disk so the integrity path is exercised end-to-end."""
    if _reload_torn_armed.is_set():
        _reload_torn_armed.clear()
        return True
    return False


def consume_env_fault() -> bool:
    """One-shot poll the env fault wrapper runs per ``step()`` call. Process-
    global, so it reaches in-process (sync) vector envs; subprocess (async)
    vector envs never see the armed flag — documented in howto/fault_tolerance."""
    if _env_fault_armed.is_set():
        _env_fault_armed.clear()
        return True
    return False


def _ckpt_kill_hook(stage: str, path: str) -> None:
    from sheeprl_tpu.utils import checkpoint

    checkpoint._fault_hook = None  # one shot
    raise InjectedFaultError(
        f"resilience.fault=ckpt_kill: injected kill during checkpoint write "
        f"(stage={stage}, path={path})"
    )


class FaultPlan:
    """The armed fault a resilience facade drives from its per-iteration hook.
    ``maybe_fire`` is idempotent across restarts (process-global ledger)."""

    def __init__(
        self,
        kind: str,
        at_policy_step: int,
        rank: Optional[int] = None,
        factor: float = DEFAULT_LR_SPIKE_FACTOR,
    ) -> None:
        self.kind = kind
        self.at = int(at_policy_step)
        self.rank = rank
        self.factor = float(factor)

    def maybe_fire(self, policy_step: int, emit: Callable[..., None]) -> None:
        if policy_step < self.at:
            return
        key = (self.kind, self.at)
        with _lock:
            if key in _fired:
                return
            _fired[key] = int(policy_step)
        emit(
            "fault",
            step=policy_step,
            kind=self.kind,
            at_policy_step=self.at,
            rank=self.rank,
            **(
                {"factor": self.factor}
                if self.kind in ("lr_spike", "slow_tick", "session_flood")
                else {}
            ),
        )
        if self.kind == "crash":
            raise InjectedFaultError(
                f"resilience.fault=crash: injected hard crash at policy step {policy_step}"
            )
        if self.kind == "sigterm":
            signals.request_preemption()
        elif self.kind == "env_step":
            _env_fault_armed.set()
        elif self.kind == "ckpt_kill":
            from sheeprl_tpu.utils import checkpoint

            checkpoint._fault_hook = _ckpt_kill_hook
        elif self.kind == "lr_spike":
            with _lock:
                _learn_fault_factor[0] = self.factor
        elif self.kind == "kill_rank":
            # a DEAD peer, not a crashing one: no exception path, no channel
            # sentinel, no exit handshake — SIGKILL bypasses every cleanup
            import os
            import signal as _stdlib_signal

            os.kill(os.getpid(), _stdlib_signal.SIGKILL)
        elif self.kind == "slow_tick":
            # factor is MILLISECONDS of stall per tick (default 32ms)
            _slow_tick_seconds[0] = max(self.factor, 0.0) / 1000.0
        elif self.kind == "session_flood":
            with _lock:
                _session_flood[0] = max(int(self.factor), 1)
        elif self.kind == "reload_torn":
            _reload_torn_armed.set()
        elif self.kind == "stale_heartbeat":
            _heartbeat_stale.set()
        elif self.kind == "channel_drop":
            from sheeprl_tpu.parallel import distributed as par_dist

            _channel_drop_armed.set()
            par_dist._channel_drop_hook = _consume_channel_drop


def build_fault_plan(
    resilience_cfg: Any, process_rank: Optional[int] = None
) -> Optional[FaultPlan]:
    """The armed plan for THIS process, or None. ``fault.rank`` targets one
    process of a multi-process run (default 0, the driving rank — which keeps
    single-process semantics unchanged); a non-matching rank arms nothing."""
    spec = normalize_fault_cfg(resilience_cfg)
    if spec is None:
        return None
    target = 0 if spec["rank"] is None else int(spec["rank"])
    if process_rank is not None and target != int(process_rank):
        return None
    return FaultPlan(spec["kind"], spec["at"], rank=target, factor=spec["factor"])
