"""``ResilienceMonitor``: the per-run resilience facade every training loop threads.

Shape-parity with the telemetry facade (``obs/telemetry.py``): one instance per
run (``build_resilience``, from the ``resilience`` config group), four hooks the
loops drive, each an attribute-cheap no-op when the feature is off:

- ``step(policy_step)`` — once per loop iteration, next to ``telemetry.step``:
  feeds the progress watchdog, fires a due injected fault, and emits the one-shot
  ``preempt`` event when the signal flag is first observed.
- ``preempt_requested()`` — the poll the loops fold into their checkpoint
  condition (forcing the out-of-cadence emergency checkpoint through the
  existing ``on_checkpoint_*`` path) and their loop-exit ``break``.
- ``observe_checkpoint(ckpt_path, policy_step)`` — right after each checkpoint
  write: a ``checkpoint`` event (``reason=preempt`` for the emergency one) and a
  watchdog feed (a long sharded write is not a stall).
- ``finalize(policy_step)`` — at loop exit, gating the final test: stops the
  watchdog, emits ``preempt_exit``, returns whether the run was preempted.

Events ride the run telemetry's JSONL sink when telemetry is enabled; otherwise
critical events (preempt/stall/fault) lazily open their own sink on the same
``telemetry.jsonl`` path, so a preempted default-config run still leaves an
audit trail — while an uneventful run with telemetry off leaves no new artifact.
The supervisor pins ``metric.telemetry.jsonl_path`` to a run-base path shared by
every restart, so the preempt → checkpoint → restart → resume sequence is one
ordered stream across attempts.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from sheeprl_tpu.obs.jsonl import JsonlEventSink
from sheeprl_tpu.resilience import signals
from sheeprl_tpu.resilience.distributed import build_coordinator
from sheeprl_tpu.resilience.faults import build_fault_plan
from sheeprl_tpu.resilience.watchdog import ProgressWatchdog, stop_all_watchdogs


class NullResilience:
    """The disabled facade: loops never branch on whether resilience is on."""

    enabled = False

    def step(self, policy_step: int) -> None:
        pass

    def preempt_requested(self) -> bool:
        return False

    def observe_checkpoint(
        self, ckpt_path: str, policy_step: int, preempted: Optional[bool] = None
    ) -> None:
        pass

    def finalize(self, policy_step: Optional[int] = None) -> bool:
        return False


class PeerResilience(NullResilience):
    """Non-rank-0 facade for multi-process runs (SPMD ranks, decoupled learner
    processes). Replaces PR 3's ``PollResilience`` live-local-poll caveat: with
    the coordination plane up, the preemption verdict every rank folds into its
    checkpoint condition is the *agreed* decision from
    :class:`~sheeprl_tpu.resilience.distributed.DistributedCoordinator` — a
    local SIGTERM only *publishes a request*; the rank keeps running until the
    rank-0-led stop step, so every rank stops at the same iteration by
    construction (no signal-skew window). Without a coordination plane (no
    jax.distributed KV client) the poll falls back to the live process-local
    flag, which is still strictly better than a hard-coded False.

    Also rank-local concerns the PR 3 facade lacked: heartbeat presence +
    peer-failure detection (a dead peer raises :class:`RankFailureError` from
    ``step`` instead of letting this rank hang), rank-targeted fault plans, and
    critical events (its own ``telemetry.rank{r}.jsonl`` sibling, or the
    provided role telemetry)."""

    enabled = True

    def __init__(self, fabric: Any, cfg: Any, log_dir: Optional[str] = None, telemetry: Any = None) -> None:
        rcfg = cfg.get("resilience") or {}
        tcfg = (cfg.get("metric") or {}).get("telemetry") or {}
        self._telemetry = telemetry
        self._rank = int(getattr(fabric, "global_rank", 0) or 0)
        self._attempt = int(tcfg.get("attempt") or 0)
        self._fault = build_fault_plan(rcfg, process_rank=self._rank)
        self._preempt_seen = False
        self._emit_lock = threading.Lock()
        self._own_sink: Optional[JsonlEventSink] = None
        self._jsonl_enabled = bool(tcfg.get("jsonl", True))
        self._sink_path = _rank_stream_path(tcfg.get("jsonl_path"), log_dir, self._rank)
        self._coord = build_coordinator(cfg, rank=self._rank, emit=self._emit_critical)

    # -- hooks -------------------------------------------------------------------

    def step(self, policy_step: int) -> None:
        if self._fault is not None:
            self._fault.maybe_fire(policy_step, self._emit_critical)
        local = signals.local_preemption_requested()
        if local and not self._preempt_seen:
            self._preempt_seen = True
            self._emit_critical(
                "preempt", step=policy_step, signum=signals.preempt_signum(), rank=self._rank
            )
        if self._coord is not None:
            self._coord.step(policy_step, local_preempt=local)
            self._coord.check_abort()  # a dead peer: tear down, don't hang

    def preempt_requested(self) -> bool:
        if self._coord is not None:
            return self._coord.preempt_requested()
        return signals.preemption_requested()

    def finalize(self, policy_step: Optional[int] = None) -> bool:
        preempted = self.preempt_requested() or signals.preemption_requested()
        if self._coord is not None:
            self._coord.close()
            self._coord = None
        if self._own_sink is not None:
            self._own_sink.close()
            self._own_sink = None
        return preempted

    # -- internals ---------------------------------------------------------------

    def _emit_critical(self, event: str, step: Optional[int] = None, critical: bool = True, **fields: Any) -> None:
        with self._emit_lock:
            if self._telemetry is not None and getattr(self._telemetry, "enabled", False):
                if self._telemetry.emit_event(event, step=step, **fields):
                    return
            if self._own_sink is None:
                if not self._jsonl_enabled or self._sink_path is None:
                    return
                try:
                    self._own_sink = JsonlEventSink(
                        self._sink_path, rank=self._rank, attempt=self._attempt
                    )
                except OSError:
                    return
            self._own_sink.emit(event, step=step, **fields)


def _rank_stream_path(jsonl_path: Any, log_dir: Optional[str], rank: int) -> Optional[str]:
    """A peer rank's own stream: ``telemetry.rank{r}.jsonl`` next to the primary
    stream (never the primary file itself — per-path seq counters are per
    process, so cross-process writers must not share a file)."""
    import os

    if jsonl_path:
        root, ext = os.path.splitext(str(jsonl_path))
        return f"{root}.rank{rank}{ext or '.jsonl'}"
    if log_dir:
        return os.path.join(str(log_dir), f"telemetry.rank{rank}.jsonl")
    return None


class ResilienceMonitor:
    """See the module docstring for the hook contract. Construct via
    :func:`build_resilience` (rank gating and the all-off path)."""

    enabled = True

    def __init__(self, fabric: Any, cfg: Any, log_dir: Optional[str], telemetry: Any = None) -> None:
        # a previous in-process attempt that died on an exception path never ran
        # finalize(): stop its watchdog before starting this run's (an orphaned
        # abort-mode watchdog would os._exit the healthy restarted run)
        stop_all_watchdogs()
        rcfg = cfg.get("resilience") or {}
        tcfg = (cfg.get("metric") or {}).get("telemetry") or {}
        self._fabric = fabric
        self._telemetry = telemetry
        rank0 = int(getattr(fabric, "global_rank", 0) or 0)
        self._fault = build_fault_plan(rcfg, process_rank=rank0)
        self._preempt_seen = False
        self._emit_lock = threading.Lock()
        self._own_sink: Optional[JsonlEventSink] = None
        # metric.telemetry.jsonl=false disables the JSONL stream outright —
        # resilience events honor it too (no lazy sink behind the user's back)
        self._jsonl_enabled = bool(tcfg.get("jsonl", True))
        self._sink_path = str(
            tcfg.get("jsonl_path")
            or (f"{log_dir}/telemetry.jsonl" if log_dir else "telemetry.jsonl")
        )
        # stream identity for the lazy sink (matches the telemetry sink's fields)
        self._rank = int(getattr(fabric, "global_rank", 0) or 0)
        self._attempt = int(tcfg.get("attempt") or 0)
        # with the supervisor (or full telemetry) on, every lifecycle event is
        # recorded; otherwise only critical events open the lazy sink, keeping
        # default-run artifacts unchanged
        self._eager = bool((rcfg.get("supervisor") or {}).get("enabled", False)) or bool(
            getattr(telemetry, "enabled", False)
        )

        wcfg = rcfg.get("watchdog") or {}
        self.watchdog: Optional[ProgressWatchdog] = None
        if bool(wcfg.get("enabled", False)):
            self.watchdog = ProgressWatchdog(
                float(wcfg.get("timeout") or 300.0),
                lambda event, **fields: self._emit(event, critical=True, **fields),
                abort=bool(wcfg.get("abort", False)),
                grace=float(wcfg.get("grace") or 30.0),
            ).start()

        # multi-process runs get the coordination plane: preempt agreement,
        # heartbeats and rank-failure detection (resilience/distributed.py);
        # None on single-process runs — everything below degrades to PR 3's
        # process-local semantics
        self._coord = build_coordinator(
            cfg, rank=self._rank, emit=lambda event, **f: self._emit(event, **f)
        )

        if cfg.get("checkpoint", {}).get("resume_from"):
            self._emit("resume", resume_from=str(cfg.checkpoint.resume_from))

    # -- hooks -------------------------------------------------------------------

    def step(self, policy_step: int) -> None:
        if self.watchdog is not None:
            self.watchdog.feed(policy_step)
        if self._fault is not None:
            self._fault.maybe_fire(policy_step, self._emit_critical)
        local = signals.local_preemption_requested()
        if self._coord is not None:
            self._coord.step(policy_step, local_preempt=local)
            self._coord.check_abort()  # a dead peer: coordinated teardown, not a hang
        if not self._preempt_seen and (local or (self._coord is not None and self._coord.decision() is not None)):
            self._preempt_seen = True
            decision = self._coord.decision() if self._coord is not None else None
            self._emit(
                "preempt",
                step=policy_step,
                signum=signals.preempt_signum(),
                critical=True,
                **(
                    {
                        "stop_step": decision["stop_step"],
                        "requested_by": decision.get("requested_by"),
                    }
                    if decision
                    else {}
                ),
            )
            self._fabric.print(
                f"[sheeprl-resilience] preemption requested at policy step {policy_step}: "
                + (
                    f"all ranks take the emergency checkpoint at step >= {decision['stop_step']}"
                    if decision
                    else "writing emergency checkpoint and shutting down"
                )
            )

    def preempt_requested(self) -> bool:
        # multi-process: the AGREED decision, never the local flag alone — every
        # rank folds the same verdict into the same iteration's checkpoint
        # condition (closing PR 3's one-iteration signal-skew window)
        if self._coord is not None:
            return self._coord.preempt_requested()
        return signals.preemption_requested()

    def observe_checkpoint(
        self, ckpt_path: str, policy_step: int, preempted: Optional[bool] = None
    ) -> None:
        # the loops pass their per-iteration snapshot — the one that actually
        # gated this save; re-polling here would mislabel a cadence-driven
        # checkpoint as reason=preempt when the signal lands mid-write (and
        # spuriously open the lazy sink for it)
        preempt = signals.preemption_requested() if preempted is None else bool(preempted)
        self._emit(
            "checkpoint",
            step=policy_step,
            path=str(ckpt_path),
            reason="preempt" if preempt else "periodic",
            critical=preempt,
        )
        if self.watchdog is not None:
            self.watchdog.feed(policy_step)

    def finalize(self, policy_step: Optional[int] = None) -> bool:
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        # the agreed decision counts even when the signal landed on a PEER rank
        # (this process never saw a local flag but still preempted with the gang)
        preempted = signals.preemption_requested() or (
            self._coord is not None and self._coord.decision() is not None
        )
        if self._coord is not None:
            self._coord.close()
            self._coord = None
        if preempted:
            self._emit(
                "preempt_exit",
                step=policy_step,
                exit_code=signals.PREEMPTED_EXIT_CODE,
                grace_spent_seconds=signals.preempt_age_seconds(),
                critical=True,
            )
        if self._own_sink is not None:
            self._own_sink.close()
            self._own_sink = None
        return preempted

    # -- internals ---------------------------------------------------------------

    def _emit_critical(self, event: str, **fields: Any) -> None:
        self._emit(event, critical=True, **fields)

    def _emit(self, event: str, step: Optional[int] = None, critical: bool = False, **fields: Any) -> None:
        with self._emit_lock:
            if self._telemetry is not None and self._telemetry.emit_event(event, step=step, **fields):
                return
            if self._own_sink is None:
                if not self._jsonl_enabled or not (self._eager or critical):
                    return
                try:
                    self._own_sink = JsonlEventSink(
                        self._sink_path, rank=self._rank, attempt=self._attempt
                    )
                except OSError:
                    return
            self._own_sink.emit(event, step=step, **fields)


def build_resilience(fabric: Any, cfg: Any, log_dir: Optional[str] = None, telemetry: Any = None):
    """Build the run's resilience facade from the ``resilience`` config group:
    the full :class:`ResilienceMonitor` on rank 0 (events, faults, watchdog,
    preempt agreement leadership), :class:`PeerResilience` on every other rank
    of a multi-process run (agreed-preempt consumption, heartbeat presence,
    rank-targeted faults, peer-failure detection). Returns
    :class:`NullResilience` when every feature is off — the loops then behave
    byte-for-byte as before."""
    rcfg = cfg.get("resilience") or {}
    handler = bool(rcfg.get("handler", True))
    if not getattr(fabric, "is_global_zero", True):
        rank = int(getattr(fabric, "global_rank", 0) or 0)
        fault_on = build_fault_plan(rcfg, process_rank=rank) is not None
        # the multi_process term mirrors the rank-0 gate below: rank 0 WILL run
        # the failure monitor, so every peer must heartbeat — a NullResilience
        # peer would be declared dead after startup_timeout on a healthy run
        if not (handler or fault_on or _multi_process()):
            return NullResilience()
        return PeerResilience(fabric, cfg, log_dir, telemetry=telemetry)
    # single source of truth for "is a fault configured" (check_configs already
    # validated, so an unknown kind cannot raise here)
    fault_on = build_fault_plan(rcfg, process_rank=0) is not None
    watchdog_on = bool((rcfg.get("watchdog") or {}).get("enabled", False))
    supervised = bool((rcfg.get("supervisor") or {}).get("enabled", False))
    multi_process = _multi_process()
    if not (handler or fault_on or watchdog_on or supervised or multi_process):
        return NullResilience()
    return ResilienceMonitor(fabric, cfg, log_dir, telemetry=telemetry)


def _multi_process() -> bool:
    from sheeprl_tpu.parallel import distributed as par_dist

    try:
        return par_dist.process_count() > 1
    except Exception:
        return False
