"""Resilience subsystem: preemption-safe checkpointing, crash supervision,
fault injection and a progress watchdog.

A TPU-native port runs on hardware where preemption is the norm (Podracer-style
pod deployments assume workers die and resume — PAPERS.md); the reference has no
signal handling, no auto-resume and no stall detection. This package is the
operational layer that *drives* the crash-atomic checkpoint serialization
(``utils/checkpoint.py``) and the telemetry event stream (``obs/``) already in
the tree:

- :mod:`~sheeprl_tpu.resilience.signals` — cooperative SIGTERM/SIGINT preemption
  handler (installed by the CLI) + the distinct preempted exit code;
- :mod:`~sheeprl_tpu.resilience.monitor` — :func:`build_resilience` /
  :class:`ResilienceMonitor`, the per-run facade every training loop threads
  (watchdog feed, fault trigger, preempt poll → emergency checkpoint);
- :mod:`~sheeprl_tpu.resilience.supervisor` — bounded-restart run supervisor
  with latest-valid-checkpoint auto-resume;
- :mod:`~sheeprl_tpu.resilience.discovery` — checkpoint enumeration/validation
  shared by the supervisor and ``checkpoint.resume_from=latest``;
- :mod:`~sheeprl_tpu.resilience.faults` — deterministic config-driven fault
  injection so the whole recovery path is testable on CPU in tier-1;
- :mod:`~sheeprl_tpu.resilience.watchdog` — progress watchdog dumping all-thread
  stacks into ``telemetry.jsonl`` on a stall, with optional abort.

See ``howto/fault_tolerance.md`` for the config keys and operational semantics.
"""

from sheeprl_tpu.resilience.discovery import (
    find_latest_checkpoint,
    is_valid_checkpoint,
    iter_checkpoints,
    manifest_path,
    read_manifest,
    resolve_latest,
)
from sheeprl_tpu.resilience.distributed import (
    DistributedCoordinator,
    GangFailureError,
    RankFailureError,
    build_coordinator,
    channel_options,
    checkpoint_manifest,
    supervise_gang,
)
from sheeprl_tpu.resilience.faults import (
    FAULT_KINDS,
    InjectedFaultError,
    apply_armed_learn_fault,
    normalize_fault_cfg,
    reset_faults,
)
from sheeprl_tpu.resilience.monitor import (
    NullResilience,
    PeerResilience,
    ResilienceMonitor,
    build_resilience,
)
from sheeprl_tpu.resilience.signals import (
    PREEMPTED_EXIT_CODE,
    RANK_FAILED_EXIT_CODE,
    WATCHDOG_EXIT_CODE,
    install_preemption_handler,
    preemption_requested,
    request_preemption,
    reset_preemption,
    uninstall_preemption_handler,
)
from sheeprl_tpu.resilience.supervisor import supervise, supervisor_enabled
from sheeprl_tpu.resilience.watchdog import ProgressWatchdog, WatchdogError, dump_all_stacks

__all__ = [
    "DistributedCoordinator",
    "FAULT_KINDS",
    "GangFailureError",
    "InjectedFaultError",
    "NullResilience",
    "PeerResilience",
    "PREEMPTED_EXIT_CODE",
    "RANK_FAILED_EXIT_CODE",
    "ProgressWatchdog",
    "RankFailureError",
    "ResilienceMonitor",
    "WATCHDOG_EXIT_CODE",
    "WatchdogError",
    "apply_armed_learn_fault",
    "build_coordinator",
    "build_resilience",
    "channel_options",
    "checkpoint_manifest",
    "dump_all_stacks",
    "find_latest_checkpoint",
    "install_preemption_handler",
    "is_valid_checkpoint",
    "iter_checkpoints",
    "manifest_path",
    "normalize_fault_cfg",
    "preemption_requested",
    "read_manifest",
    "request_preemption",
    "reset_faults",
    "reset_preemption",
    "resolve_latest",
    "supervise",
    "supervise_gang",
    "supervisor_enabled",
    "uninstall_preemption_handler",
]
