"""Progress watchdog: detect a silently hung run and make the hang observable.

A TPU training loop can stall without dying — an env subprocess deadlocks, a
remote compile hangs, a collective waits forever on a dead peer — and nothing in
the reference notices: the process sits between ``checkpoint.every`` boundaries
burning reserved accelerator time. The watchdog is a daemon thread fed by the
loops' existing per-iteration cadence (the same hook that drives
``telemetry.step``). When no feed arrives for ``timeout`` seconds it dumps every
thread's stack as a ``health`` event into ``telemetry.jsonl`` (the one artifact
a post-mortem can always read) and, with ``abort=true``, escalates: first an
async :class:`WatchdogError` raised in the main thread (catches Python-level
stalls, unwinds through the normal teardown, and the supervisor treats it as a
crash), then — if the main thread is stuck in native code and never sees it —
``os._exit`` with :data:`~sheeprl_tpu.resilience.signals.WATCHDOG_EXIT_CODE`
after a grace period, which an *external* supervisor treats as a crash.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from sheeprl_tpu.resilience.signals import WATCHDOG_EXIT_CODE


class WatchdogError(RuntimeError):
    """Raised asynchronously in the main thread on a stalled run (abort mode)."""


def dump_all_stacks() -> Dict[str, str]:
    """``{thread name: formatted stack}`` for every live thread — the payload of
    the stall event, and on its own a useful debugging helper."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks: Dict[str, str] = {}
    for ident, frame in sys._current_frames().items():
        label = names.get(ident, f"thread-{ident}")
        stacks[label] = "".join(traceback.format_stack(frame))
    return stacks


def _async_raise_main(exc_type) -> bool:
    """Schedule ``exc_type`` in the main thread at its next bytecode boundary."""
    import ctypes

    main = threading.main_thread()
    if main.ident is None:
        return False
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(main.ident), ctypes.py_object(exc_type)
    )
    return res == 1


# Live watchdogs (registered by start(), deregistered by stop()). An exception
# unwinding out of a training loop skips monitor.finalize() — the only in-loop
# stop site — so whoever handles the crash (the supervisor between attempts, a
# fresh monitor in the next in-process run) must stop stale instances: with
# abort=true an orphaned watchdog's grace countdown would os._exit(76) the
# healthy restarted run.
_active: list = []
_active_lock = threading.Lock()


def stop_all_watchdogs() -> None:
    """Stop every live watchdog (crash-path cleanup; idempotent)."""
    with _active_lock:
        stale = list(_active)
    for dog in stale:
        dog.stop()


class _PauseAll:
    """Context manager suspending stall detection in every live watchdog — used
    around checkpoint writes, whose duration (a large synchronous orbax save can
    exceed any sane stall timeout) is progress, not a hang."""

    def __enter__(self):
        with _active_lock:
            self._dogs = list(_active)
        for dog in self._dogs:
            dog.pause()
        return self

    def __exit__(self, *exc):
        for dog in self._dogs:
            dog.resume()
        return False


def watchdogs_paused() -> _PauseAll:
    return _PauseAll()


class ProgressWatchdog:
    """Daemon-thread stall detector. ``feed()`` from the loop's iteration hook;
    one stall event per episode (re-arms on the next feed)."""

    def __init__(
        self,
        timeout: float,
        emit: Callable[..., None],
        *,
        abort: bool = False,
        grace: float = 30.0,
        _exit: Callable[[int], None] = os._exit,
    ) -> None:
        self.timeout = float(timeout)
        self.abort = bool(abort)
        self.grace = float(grace)
        self._emit = emit
        self._exit = _exit
        self._last_feed = time.monotonic()
        self._last_step: Optional[int] = None
        self._tripped = False
        self._paused = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stall_count = 0

    def start(self) -> "ProgressWatchdog":
        if self._thread is None:
            self._last_feed = time.monotonic()
            self._thread = threading.Thread(
                target=self._run, name="sheeprl-watchdog", daemon=True
            )
            self._thread.start()
            with _active_lock:
                _active.append(self)
        return self

    def feed(self, policy_step: Optional[int] = None) -> None:
        self._last_feed = time.monotonic()
        if policy_step is not None:
            self._last_step = int(policy_step)
        self._tripped = False  # progress resumed: re-arm

    def pause(self) -> None:
        """Suspend stall detection (a blocking-but-healthy phase, e.g. a long
        synchronous checkpoint write)."""
        self._paused = True

    def resume(self) -> None:
        self.feed()  # the paused span counts as progress, not silence
        self._paused = False

    def stop(self) -> None:
        self._stop.set()
        with _active_lock:
            if self in _active:
                _active.remove(self)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- internals ---------------------------------------------------------------

    def _run(self) -> None:
        poll = max(min(self.timeout / 4.0, 5.0), 0.05)
        while not self._stop.wait(poll):
            if self._paused:
                continue
            stalled_for = time.monotonic() - self._last_feed
            if stalled_for < self.timeout or self._tripped:
                continue
            self._tripped = True
            self.stall_count += 1
            try:
                self._emit(
                    "health",
                    step=self._last_step,
                    status="stalled",
                    stall_seconds=round(stalled_for, 1),
                    timeout=self.timeout,
                    abort=self.abort,
                    stacks=dump_all_stacks(),
                )
            except Exception:
                pass
            if not self.abort:
                continue
            _async_raise_main(WatchdogError)
            # grace period for the async exception to unwind the main thread
            # (feed/stop means it recovered or is tearing down); a main thread
            # pinned inside native code never reaches a bytecode boundary, so
            # escalate to a hard exit an external supervisor restarts
            deadline = time.monotonic() + self.grace
            while time.monotonic() < deadline:
                # a pause during the countdown means the main thread reached a
                # checkpoint write — it is alive; never _exit mid-write
                if (
                    self._stop.wait(0.1)
                    or self._paused
                    or time.monotonic() - self._last_feed < self.timeout
                ):
                    break
            else:
                self._exit(WATCHDOG_EXIT_CODE)
