"""Latest-valid-checkpoint discovery (shared by ``checkpoint.resume_from=latest``
and the crash supervisor's auto-resume).

A run's checkpoints live at ``<base>/<run_name>/version_N/checkpoint/ckpt_{step}_{rank}.ckpt``
in one of two on-disk formats (utils/checkpoint.py): a single pickle FILE
(written crash-atomically via tmp+rename, so existence implies completeness) or
an orbax DIRECTORY paired with a ``.extras.pkl`` sidecar. The sharded writer's
in-place overwrite protocol additionally leaves crash-window variants the loader
understands: a ``<path>.old`` directory displaced before the new write committed,
and a ``<path>.old.extras.pkl`` sidecar whose directory rename never happened.
Discovery enumerates all of these, validates each candidate the same way
``load_checkpoint`` would resolve it, and orders by (mtime, parsed step) so a
restarted run resumes from the newest state that is actually loadable —
skipping torn ``.tmp`` files and orbax directories whose sidecar is missing.

Multi-process runs additionally write a per-step **consistency manifest**
(``ckpt_{step}.manifest.json``, see ``resilience/distributed.py``): begun with
``complete: false`` before the save, committed — the marker written last — only
after every participating rank acked. When a manifest exists for a candidate's
step, discovery trusts it over the artifact heuristics: an incomplete manifest
means some rank never finished that checkpoint iteration, so the whole set is
invalid by construction and resolution falls back to the previous complete one.
Single-process checkpoints (no manifest) keep the original validation.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional

_STEP_RE = re.compile(r"ckpt_(\d+)(?:_\d+)?\.ckpt$")


def manifest_path(path: str) -> str:
    """The consistency manifest governing ``path``'s checkpoint SET: one per
    step per directory (rank-suffixed files of one step share it); foreign
    names fall back to a per-path sibling."""
    path = str(path)
    base = os.path.basename(path).replace(".old", "")
    m = _STEP_RE.search(base)
    name = f"ckpt_{m.group(1)}.manifest.json" if m else base + ".manifest.json"
    return os.path.join(os.path.dirname(path), name)


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The manifest governing ``path``, or None when there is none. A manifest
    that exists but cannot be parsed reads as incomplete (``{}``) — it must veto
    the candidate, not be ignored."""
    mpath = manifest_path(path)
    if not os.path.isfile(mpath):
        return None
    try:
        with open(mpath) as f:
            payload = json.load(f)
        return payload if isinstance(payload, dict) else {}
    except (OSError, ValueError):
        return {}


def _manifest_complete(manifest: Dict[str, Any]) -> bool:
    if not manifest.get("complete"):
        return False
    expected = manifest.get("ranks_expected")
    committed = manifest.get("ranks_committed")
    if expected and set(committed or []) != set(expected):
        return False
    return True


def checkpoint_step(path: str) -> int:
    """Policy step parsed from a ``ckpt_{step}_{rank}.ckpt`` name (-1 if foreign)."""
    m = _STEP_RE.search(os.path.basename(str(path)).replace(".old", ""))
    return int(m.group(1)) if m else -1


def is_valid_checkpoint(path: str) -> bool:
    """Would ``load_checkpoint(path)`` find a complete state at ``path``?

    - pickle file: committed atomically (tmp+``os.replace``), so a non-empty
      ``.ckpt`` file is complete by construction; when a ``<path>.sha256``
      integrity sidecar exists (utils/checkpoint.py writes one per save), the
      digest must ALSO match — a corrupted/torn file is invalid and resolution
      falls back to the previous valid checkpoint (what hot reload's
      ``reload_torn`` fault and ``resume_from=latest`` both lean on);
    - orbax directory: needs its sidecar — at ``<path>.extras.pkl`` or, in the
      mid-displacement crash window, ``<path>.old.extras.pkl``;
    - missing path with a ``<path>.old`` directory: the in-place-overwrite crash
      window; valid when the displaced directory still pairs with a sidecar;
    - a consistency manifest for the candidate's step, when present, overrides
      all of the above: only ``complete: true`` with every expected rank
      committed is valid (torn multi-rank sets are invalid by construction).
    """
    path = str(path)
    manifest = read_manifest(path)
    if manifest is not None and not _manifest_complete(manifest):
        return False
    if os.path.isfile(path):
        try:
            if os.path.getsize(path) <= 0:
                return False
        except OSError:
            return False
        from sheeprl_tpu.utils.checkpoint import verify_sha_sidecar

        # advisory integrity sidecar: absent (None) keeps the size heuristic's
        # verdict; present-but-mismatching vetoes — the file is corrupt
        return verify_sha_sidecar(path) is not False
    if os.path.isdir(path):
        return os.path.isfile(path + ".extras.pkl") or os.path.isfile(path + ".old.extras.pkl")
    old = path + ".old"
    if os.path.isdir(old):
        return os.path.isfile(old + ".extras.pkl")
    return False


def iter_checkpoints(search_dir: str) -> List[str]:
    """All checkpoint candidates under ``search_dir`` (any depth), as the paths
    ``load_checkpoint`` should be handed — i.e. ``.old`` crash-window survivors
    are reported under their base (pre-displacement) path."""
    search_dir = str(search_dir)
    if not os.path.isdir(search_dir):
        return []
    candidates = set(glob.glob(os.path.join(search_dir, "**", "*.ckpt"), recursive=True))
    for old in glob.glob(os.path.join(search_dir, "**", "*.ckpt.old"), recursive=True):
        base = old[: -len(".old")]
        if not os.path.exists(base):
            candidates.add(base)
    return sorted(candidates)


def _candidate_mtime(path: str) -> float:
    for probe in (path, path + ".old", path + ".extras.pkl", path + ".old.extras.pkl"):
        try:
            return os.path.getmtime(probe)
        except OSError:
            continue
    return 0.0


def find_latest_checkpoint(search_dir: str) -> Optional[str]:
    """Newest valid checkpoint under ``search_dir`` (None when there is none).
    Ordered by mtime with the parsed policy step as tiebreak — step counts are
    only comparable within one run, mtime orders across restarts and runs."""
    valid = [c for c in iter_checkpoints(search_dir) if is_valid_checkpoint(c)]
    if not valid:
        return None
    return max(valid, key=lambda c: (_candidate_mtime(c), checkpoint_step(c)))


def resolve_checkpoint_path(path: str) -> str:
    """One checkpoint-resolution rule for every consumer that takes a
    ``checkpoint_path`` (``sheeprl_eval``, ``sheeprl.py serve``): an exact
    checkpoint (pickle file, orbax dir + sidecar, or a ``.old`` crash-window
    survivor) resolves to itself; anything else that is a DIRECTORY — a run
    dir, an experiment tree, a multi-rank checkpoint dir — resolves to its
    newest valid checkpoint under the same manifest-validated rules the crash
    supervisor uses (torn multi-rank sets can never resolve). Raises
    ``FileNotFoundError`` when nothing valid is found."""
    path = str(path)
    if os.path.isfile(path):
        # an exact file wins even without validation: the caller named it
        return path
    if is_valid_checkpoint(path):
        return path
    if os.path.isdir(path):
        found = find_latest_checkpoint(path)
        if found is not None:
            return found
        raise FileNotFoundError(
            f"checkpoint_path={path!r} is a directory with no valid checkpoint under it "
            "(torn multi-rank sets — incomplete manifests — are skipped by construction)"
        )
    raise FileNotFoundError(f"checkpoint_path={path!r}: no such file, directory or checkpoint set")


def resolve_latest(cfg) -> str:
    """Resolve ``checkpoint.resume_from=latest`` for the CLI: newest valid
    checkpoint across every run under this experiment's ``root_dir`` (honoring a
    ``hydra.run.dir`` override, where the runs of one experiment share a base)."""
    from pathlib import Path

    from sheeprl_tpu.utils.logger import run_base_dir

    # the CLI resolves `latest` before `_apply_hydra_cfg` runs, so honor a
    # hydra.run.dir override from the config directly
    hydra_dir = ((cfg.get("hydra") or {}).get("run") or {}).get("dir")
    base = Path(hydra_dir) if hydra_dir else run_base_dir(cfg.root_dir, cfg.run_name)
    # without an override the per-run dir is <logs/runs/root_dir>/<run_name>; the
    # CURRENT run_name is freshly timestamped, so search the whole experiment
    search = base if base.is_dir() else base.parent
    found = find_latest_checkpoint(str(search))
    if found is None:
        raise ValueError(
            f"checkpoint.resume_from=latest: no valid checkpoint found under {search} "
            "(nothing to resume; pass an explicit path or start a fresh run)"
        )
    return found
