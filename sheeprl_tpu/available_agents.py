"""Rich table of registered agents (role of sheeprl/available_agents.py:7-38)."""

from __future__ import annotations


def available_agents() -> None:
    import sheeprl_tpu  # noqa: F401 - populate registries

    from rich.console import Console
    from rich.table import Table

    from sheeprl_tpu.utils.registry import algorithm_registry

    table = Table(title="SheepRL-TPU Agents")
    table.add_column("Module")
    table.add_column("Algorithm")
    table.add_column("Entrypoint")
    table.add_column("Decoupled")
    for algo, regs in sorted(algorithm_registry.items()):
        for reg in regs:
            table.add_row(reg["module"], algo, reg["entrypoint"], str(reg["decoupled"]))
    Console().print(table)


if __name__ == "__main__":
    available_agents()
