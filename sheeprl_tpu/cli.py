"""CLI verbs: run / evaluation / registration (role of sheeprl/cli.py:23-449).

``run`` composes the config from dotted CLI overrides, applies resume-merge and config
policing, resolves the algorithm through the registry, instantiates the Fabric runtime
from config and launches the registered entrypoint — the same flow as the reference
(cli.py:357-365 → run_algorithm cli.py:59-198), minus process spawning: JAX SPMD runs
one controller process per host.
"""

from __future__ import annotations

import importlib
import os
import sys
import warnings
from datetime import datetime
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from sheeprl_tpu.config import Composer, compose, deep_merge, dotdict, instantiate
from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import print_config

# config keys that must not be taken from the old config on resume (reference cli.py:23-56)
# `resilience` is runtime-operational state like `metric`: the saved config may
# carry a supervisor/fault setup that must not silently override this launch's.
# NOTE `hydra` stays RESUMABLE on purpose: the saved hydra.run.dir places a
# resumed run in the original run's tree as the next version_N — the
# continuation semantics the resume tests pin (a gang restart is unaffected:
# it pins root_dir/run_name per attempt, so the old and new dirs coincide).
_NON_RESUMABLE_KEYS = (
    "checkpoint",
    "exp_name",
    "run_name",
    "root_dir",
    "metric",
    "resilience",
)


def resume_from_checkpoint(cfg: dotdict, overrides: Optional[Sequence[str]] = None) -> dotdict:
    """Force-merge the checkpoint's config over the current one, keeping the
    non-resumable keys, and hard-validate env/algo identity (reference cli.py:23-56).
    ``checkpoint.resume_from=latest`` resolves to the newest valid checkpoint under
    this experiment's log tree first (shared with the supervisor's discovery).

    ``overrides`` is this launch's raw CLI override list: explicit dotted values
    the user typed (``buffer.size=N``) are re-applied AFTER the merge, so they
    beat the checkpoint's saved config — on the first attempt and on every
    supervisor retry (which funnels through this same merge)."""
    import yaml

    if str(cfg.checkpoint.resume_from).strip().lower() == "latest":
        from sheeprl_tpu.resilience.discovery import resolve_latest

        cfg.checkpoint.resume_from = resolve_latest(cfg)
    ckpt_path = Path(cfg.checkpoint.resume_from)
    old_cfg_path = ckpt_path.parent.parent / "config.yaml"
    if not old_cfg_path.is_file():
        old_cfg_path = ckpt_path.parent / "config.yaml"
    if not old_cfg_path.is_file():
        raise ValueError(
            f"cannot resume from {ckpt_path}: no config.yaml found next to the checkpoint"
        )
    with open(old_cfg_path) as f:
        old_cfg = yaml.safe_load(f)
    if old_cfg["env"]["id"] != cfg.env.id:
        raise ValueError(
            f"This experiment is run with a different environment from the one of the "
            f"experiment you want to restart: got {cfg.env.id}, expected {old_cfg['env']['id']}"
        )
    if old_cfg["algo"]["name"] != cfg.algo.name:
        raise ValueError(
            f"This experiment is run with a different algorithm from the one of the "
            f"experiment you want to restart: got {cfg.algo.name}, expected {old_cfg['algo']['name']}"
        )
    non_resumable = _NON_RESUMABLE_KEYS
    explicit: dict = {}
    if overrides:
        from sheeprl_tpu.config import explicit_overrides

        explicit = explicit_overrides(overrides)
    # `hydra` is resumable BY DEFAULT: the saved hydra.run.dir places a resumed
    # run in the original run's tree as the next version_N (the continuation
    # semantics the resume tests pin). But when THIS launch names its own run
    # identity on the command line, its hydra layout wins — resuming another
    # run's checkpoint under an explicit run_name must not hijack the old tree.
    if any(
        k in ("exp_name", "run_name", "root_dir") or k.startswith("hydra.")
        for k in explicit
    ):
        non_resumable = non_resumable + ("hydra",)
    preserved = {k: cfg[k] for k in non_resumable if k in cfg}
    merged = dict(old_cfg)
    deep_merge(merged, preserved)
    merged["checkpoint"]["resume_from"] = str(ckpt_path)
    result = dotdict(merged)
    if explicit:
        from sheeprl_tpu.config import set_by_path

        for key, value in explicit.items():
            # never clobber the resolved resume path (the argv value may be the
            # literal "latest", or a base checkpoint a retry has moved past);
            # the rest of `checkpoint` is already preserved from this launch
            if key == "checkpoint.resume_from":
                continue
            try:
                set_by_path(result, key, value, create=True)
            except (KeyError, TypeError):
                continue  # an override targeting a group the old config lacks
    return result


def check_configs(cfg: dotdict) -> None:
    """Config policing (role of reference cli.py:270-344): algorithm existence,
    decoupled × strategy × devices combinations, optional-dependency downgrades,
    and basic value sanity — each with an actionable message."""
    entry = algorithm_registry.get(cfg.algo.name)
    if entry is None:
        available = ", ".join(sorted(algorithm_registry.keys()))
        raise ValueError(f"algorithm {cfg.algo.name!r} is not registered; available: {available}")
    decoupled = entry[0]["decoupled"]
    if decoupled and int(os.environ.get("SHEEPRL_NUM_ACTORS", "1")) < 1:
        raise ValueError("decoupled algorithms need at least one actor process")

    strategy = str(cfg.fabric.strategy)
    if strategy not in ("auto", "dp", "single_device"):
        raise ValueError(
            f"unknown fabric.strategy {strategy!r}; available: auto, dp, single_device "
            "(the reference's DDP/SingleDevice strategies map onto the mesh `dp` and "
            "`single_device` strategies here)"
        )
    devices = int(cfg.fabric.devices)
    if strategy == "single_device" and devices > 1:
        raise ValueError(
            f"single_device strategy requires fabric.devices=1, got {devices}; "
            "launch with 'fabric.strategy=dp' (or 'auto') to use the whole mesh"
        )
    if decoupled and strategy == "single_device":
        # reference parity: decoupled algorithms refuse non-DDP strategies
        # (reference cli.py:290-307) — the player/trainer split needs the mesh
        raise ValueError(
            f"{cfg.algo.name} is decoupled and is not supported by the single_device "
            "strategy; launch with 'fabric.strategy=dp' or 'fabric.strategy=auto'"
        )
    if decoupled and devices < 1:
        raise ValueError(f"decoupled algorithms need fabric.devices >= 1, got {devices}")

    # named-mesh sanity: canonicalize mesh_shape/axis_names (raises on shape/name
    # mismatches, duplicate names, a missing "data" axis, multiple wildcards)
    # before the run launches, and police the strategy interaction
    from sheeprl_tpu.parallel.fabric import normalize_mesh_spec

    mesh_shape, _mesh_axes = normalize_mesh_spec(
        cfg.fabric.get("mesh_shape"), cfg.fabric.get("axis_names")
    )
    if strategy == "single_device" and len(mesh_shape) > 1:
        raise ValueError(
            f"single_device strategy cannot drive a multi-axis mesh "
            f"(fabric.mesh_shape={mesh_shape}); launch with 'fabric.strategy=dp' or 'auto'"
        )
    if decoupled and len(mesh_shape) > 1:
        raise ValueError(
            f"{cfg.algo.name} is decoupled: its player/learner slices run 1-D data "
            f"meshes (a multi-axis fabric.mesh_shape={mesh_shape} is only supported "
            "by the coupled topologies — see howto/model_parallel.md)"
        )
    if "model" in _mesh_axes and len(mesh_shape) > 1:
        module = entry[0]["module"]
        if not any(fam in module for fam in ("dreamer", "p2e")):
            # the mesh layer is generic but only the Dreamer family shards its
            # parameters over `model` (howto/model_parallel.md) — elsewhere the
            # model-axis devices would just repeat replicated work
            warnings.warn(
                f"fabric.mesh_shape={mesh_shape} carries a 'model' axis but "
                f"{cfg.algo.name} does not shard parameters over it; those devices "
                "will do replicated work. The Dreamer family is the wired-up "
                "consumer — see howto/model_parallel.md."
            )

    # experience-backend sanity (sheeprl_tpu/data/service.py, howto/fleet.md):
    # fail before launch on a config that cannot form a service plane
    backend = str(cfg.buffer.get("backend", "local") if cfg.get("buffer") else "local")
    if backend not in ("local", "service", "device"):
        raise ValueError(
            f"unknown buffer.backend {backend!r}; available: local (in-process replay, "
            "the default), service (standalone experience data plane for the "
            "decoupled topologies — see howto/fleet.md) and device (on-mesh replay "
            "ring for the fused off-policy topology — see howto/device_replay.md)"
        )
    if backend == "device" and cfg.algo.name != "sac_anakin":
        raise ValueError(
            f"buffer.backend=device is wired for the fused off-policy topology "
            f"(sac_anakin), not {cfg.algo.name!r} — host loops would round-trip the "
            "ring every step, losing exactly what it buys (howto/device_replay.md)"
        )
    if backend == "service":
        if cfg.algo.name not in ("sac_decoupled", "dreamer_v3_decoupled"):
            raise ValueError(
                f"buffer.backend=service is wired for the decoupled actor/learner "
                f"topologies (sac_decoupled, dreamer_v3_decoupled), not {cfg.algo.name!r}"
            )
        service_cfg = cfg.buffer.get("service") or {}
        actors = int(service_cfg.get("actors") or 1)
        if actors < 1:
            raise ValueError(f"buffer.service.actors must be >= 1, got {actors}")
        from sheeprl_tpu.resilience.distributed import gang_processes

        gang_size = gang_processes(cfg)
        if gang_size and actors >= gang_size:
            raise ValueError(
                f"buffer.service.actors={actors} leaves no learner rank in a "
                f"{gang_size}-process gang (need actors <= gang.processes - 1)"
            )

    # optional-dependency downgrade (reference cli.py:333-340)
    if not cfg.model_manager.get("disabled", True):
        from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

        if not _IS_MLFLOW_AVAILABLE:
            warnings.warn(
                "MLflow is not installed: model registration is disabled for this run. "
                "Install it with 'pip install mlflow' to use the model manager.",
                UserWarning,
            )
            cfg.model_manager.disabled = True

    # observability config sanity: resolve (and thereby validate) the profiler
    # mode — an invalid metric.profiler.mode must fail before the run launches
    from sheeprl_tpu.obs import resolve_profiler_config

    resolve_profiler_config(cfg.metric)

    # resilience config sanity (same fail-before-launch policy)
    from sheeprl_tpu.resilience import normalize_fault_cfg

    rcfg = cfg.get("resilience") or {}
    fault = normalize_fault_cfg(rcfg)  # raises on an unknown fault kind
    if fault is not None and fault["at"] < 0:
        raise ValueError("resilience.fault.at_policy_step must be >= 0")
    if fault is not None and fault["rank"] is not None and fault["rank"] < 0:
        raise ValueError("resilience.fault.rank must be >= 0 (a process index)")
    supervisor_cfg = rcfg.get("supervisor") or {}
    if int(supervisor_cfg.get("max_restarts", 3) or 0) < 0:
        raise ValueError("resilience.supervisor.max_restarts must be >= 0")
    watchdog_cfg = rcfg.get("watchdog") or {}
    if bool(watchdog_cfg.get("enabled", False)) and float(watchdog_cfg.get("timeout") or 0) <= 0:
        raise ValueError("resilience.watchdog.timeout must be > 0 when the watchdog is enabled")
    dist_cfg = rcfg.get("distributed") or {}
    gang_n = int((dist_cfg.get("gang") or {}).get("processes") or 0)
    if gang_n == 1 or gang_n < 0:
        raise ValueError(
            "resilience.distributed.gang.processes must be 0 (off) or >= 2 "
            "(a 1-process run is what the in-process resilience.supervisor is for)"
        )
    if gang_n >= 2 and fault is not None and fault["rank"] is not None and fault["rank"] >= gang_n:
        raise ValueError(
            f"resilience.fault.rank={fault['rank']} targets no process of a "
            f"{gang_n}-process gang — the fault would never fire"
        )
    hb_cfg = dist_cfg.get("heartbeat") or {}
    hb_interval = float(hb_cfg.get("interval") or 2.0)
    hb_timeout = float(hb_cfg.get("timeout") or 60.0)
    if bool(hb_cfg.get("enabled", True)) and hb_timeout <= hb_interval:
        raise ValueError(
            "resilience.distributed.heartbeat.timeout must exceed heartbeat.interval "
            f"(got timeout={hb_timeout}, interval={hb_interval})"
        )

    # value sanity (reference cli.py:341-344)
    learning_starts = cfg.algo.get("learning_starts")
    if learning_starts is not None and int(learning_starts) < 0:
        raise ValueError("The `algo.learning_starts` parameter must be greater or equal to zero.")
    if int(cfg.env.action_repeat) < 1:
        cfg.env.action_repeat = 1


def _apply_hydra_cfg(cfg: dotdict) -> None:
    """Honor the hydra config group's run-dir layout (reference
    sheeprl/configs/hydra/default.yaml: hydra.run.dir places the run directory)."""
    from sheeprl_tpu.utils.logger import set_run_dir

    hydra_cfg = cfg.get("hydra") or {}
    set_run_dir((hydra_cfg.get("run") or {}).get("dir"))


def _apply_distribution_cfg(cfg: dotdict) -> None:
    """Global distribution argument-validation switch (reference cli.py:71 sets the
    torch-distributions default from configs/distribution/default.yaml)."""
    from sheeprl_tpu.utils.distribution import set_validate_args

    dist_cfg = cfg.get("distribution") or {}
    set_validate_args(bool(dist_cfg.get("validate_args", False)))


def _setup_xla_env(cfg: dotdict) -> None:
    """Apply the XLA/runtime knobs (replacing torch/cuDNN knobs, reference cli.py:186-196)."""
    import jax

    from sheeprl_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    # torch set_float32_matmul_precision names map 1:1 onto JAX's tri-state
    # (high → bf16_3x passes, highest → f32, default → bf16 on the MXU)
    prec = str(cfg.get("float32_matmul_precision", "high"))
    try:
        jax.config.update("jax_default_matmul_precision", prec)
    except Exception:
        warnings.warn(f"could not set matmul precision {prec!r}")
    if cfg.get("xla_deterministic_ops", False):
        os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_gpu_deterministic_ops=true"


def run_algorithm(cfg: dotdict) -> None:
    """Registry lookup → module import → fabric instantiation → launch
    (reference cli.py:59-198)."""
    entry = algorithm_registry[cfg.algo.name][0]
    module = importlib.import_module(entry["module"])
    main = getattr(module, entry["entrypoint"])

    # metric key filtering: keep only the algo's whitelisted metrics (reference cli.py:150-164)
    utils_mod = None
    try:
        utils_mod = importlib.import_module(f"{entry['module'].rsplit('.', 1)[0]}.utils")
    except ImportError:
        pass
    if utils_mod is not None and hasattr(utils_mod, "AGGREGATOR_KEYS") and cfg.metric.log_level > 0:
        keys = set(utils_mod.AGGREGATOR_KEYS)
        metrics = cfg.metric.aggregator.metrics
        # prefix matches keep per-stream suffixed metrics (e.g. the p2e exploration
        # critics' Loss/value_loss_exploration_<critic>)
        cfg.metric.aggregator.metrics = dotdict(
            {
                k: v
                for k, v in metrics.items()
                if k in keys or any(k.startswith(p + "_") for p in keys)
            }
        )
    if cfg.metric.log_level == 0 or cfg.metric.disable_timer:
        # telemetry needs the Time/* spans for its train-seconds/MFU accounting
        # and is documented as independent of log_level, so an enabled telemetry
        # keeps the timers alive (two perf_counter calls per span — noise even
        # for bench runs, which enable telemetry with logging off)
        timer.disabled = not bool((cfg.metric.get("telemetry") or {}).get("enabled", False))
    from sheeprl_tpu.utils.metric import MetricAggregator

    MetricAggregator.disabled = cfg.metric.log_level == 0
    MetricAggregator.warn_device_values = cfg.metric.log_level >= 1

    kwargs: Dict[str, Any] = {}
    if "finetuning" in cfg.algo.name and "p2e" in entry["module"]:
        # inherit env/config identity from the exploration run (reference
        # cli.py:116-147)
        import yaml

        ckpt_path = Path(cfg.checkpoint.exploration_ckpt_path)
        expl_cfg_path = ckpt_path.parent.parent / "config.yaml"
        if not expl_cfg_path.is_file():
            expl_cfg_path = ckpt_path.parent / "config.yaml"
        if not expl_cfg_path.is_file():
            raise ValueError(
                f"cannot finetune from {ckpt_path}: no config.yaml found next to the "
                "exploration checkpoint"
            )
        with open(expl_cfg_path) as f:
            exploration_cfg = dotdict(yaml.safe_load(f))
        if exploration_cfg.env.id != cfg.env.id:
            raise ValueError(
                "This experiment is run with a different environment from the one of "
                f"the exploration you want to finetune. Got '{cfg.env.id}', but the "
                f"environment used during exploration was {exploration_cfg.env.id}."
            )
        for k in (
            "frame_stack",
            "screen_size",
            "action_repeat",
            "grayscale",
            "clip_rewards",
            "frame_stack_dilation",
            "max_episode_steps",
            "reward_as_observation",
        ):
            cfg.env[k] = exploration_cfg.env[k]
        if cfg.buffer.get("load_from_exploration", False):
            cfg.fabric.devices = exploration_cfg.fabric.devices
        kwargs["exploration_cfg"] = exploration_cfg

    fabric = instantiate(
        cfg.fabric,
        checkpoint_backend=str(cfg.checkpoint.get("backend", "pickle")),
        checkpoint_async=bool(cfg.checkpoint.get("async_save", False)),
    )

    # Optional XLA trace capture (SURVEY §5.1's TPU equivalent of the reference's
    # profiling story). metric.profiler.mode=run wraps the launched entrypoint in
    # a jax.profiler trace whose dump lands under the run's log tree, viewable in
    # TensorBoard's profile plugin / Perfetto — meant for short diagnostic runs
    # (a full-length training run produces a very large trace; use mode=window,
    # handled by the in-loop RunTelemetry, for a bounded steady-state capture).
    # The trace starts INSIDE the launch, after fabric._setup has pinned the
    # platform: jax.profiler.start_trace initializes the backend, and doing that
    # before the pin would touch the accelerator even for accelerator=cpu runs.
    from sheeprl_tpu.obs import resolve_profiler_config

    profiler_cfg = resolve_profiler_config(cfg.metric)
    if profiler_cfg["mode"] == "run":
        from sheeprl_tpu.utils.logger import run_base_dir

        profiler_dir = profiler_cfg["dir"] or str(
            run_base_dir(cfg.root_dir, cfg.run_name) / "profiler"
        )
        inner_main = main

        def main(fabric_, cfg_, **kw):  # noqa: F811 — deliberate profiled wrapper
            import jax

            os.makedirs(profiler_dir, exist_ok=True)
            jax.profiler.start_trace(profiler_dir)
            try:
                return inner_main(fabric_, cfg_, **kw)
            finally:
                jax.profiler.stop_trace()

    try:
        fabric.launch(main, cfg, **kwargs)
    finally:
        # an exception that unwound past the loop skipped its telemetry.close():
        # flush the summary (clean_exit=False) so crashed/preempted attempts
        # still leave end-of-attempt state in telemetry.jsonl — the loops close
        # their own instance on the normal path, making this a no-op there
        from sheeprl_tpu.obs.telemetry import close_all_live_telemetry

        close_all_live_telemetry(clean_exit=False)
        if fabric.checkpoint_async:
            from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint

            wait_for_checkpoint()


def run(args: Optional[Sequence[str]] = None) -> None:
    """Entry point: ``python -m sheeprl_tpu exp=ppo env=gym ...``.

    Resilience wiring (sheeprl_tpu/resilience, howto/fault_tolerance.md): the
    cooperative SIGTERM/SIGINT preemption handler is installed around the launch
    (``resilience.handler``, default on) — the loops poll it at iteration
    boundaries and write an emergency checkpoint before exiting, and a preempted
    run exits with the distinct :data:`PREEMPTED_EXIT_CODE`. With
    ``resilience.supervisor.enabled`` the launch runs under the bounded-restart
    supervisor, auto-resuming from the newest valid checkpoint on crash or
    preemption."""
    import copy

    import sheeprl_tpu  # ensure registries are populated

    from sheeprl_tpu.resilience import (
        PREEMPTED_EXIT_CODE,
        RANK_FAILED_EXIT_CODE,
        install_preemption_handler,
        preemption_requested,
        supervisor_enabled,
        uninstall_preemption_handler,
    )
    from sheeprl_tpu.resilience.distributed import RankFailureError, gang_processes

    overrides = list(args if args is not None else sys.argv[1:])
    cfg = compose(overrides)

    # gang children (SHEEPRL_COORDINATOR set) had jax.distributed brought up by
    # __main__._gang_child_bringup BEFORE any sheeprl_tpu import — it cannot be
    # done here, the registry imports above already ran jax computations

    # the argv-merged cfg BEFORE any resume merge: supervisor retries rebuild
    # from it so this launch's explicit overrides survive every attempt
    argv_cfg = dotdict(copy.deepcopy(cfg.as_dict()))
    if cfg.checkpoint.resume_from:
        cfg = resume_from_checkpoint(cfg, overrides=overrides)
    check_configs(cfg)
    _setup_xla_env(cfg)
    _apply_distribution_cfg(cfg)
    _apply_hydra_cfg(cfg)
    if cfg.metric.log_level > 0:
        print_config(cfg)

    handler_installed = False
    if bool((cfg.get("resilience") or {}).get("handler", True)):
        handler_installed = install_preemption_handler()
    try:
        if gang_processes(cfg) >= 2 and not os.environ.get("SHEEPRL_GANG_RANK"):
            # gang mode: this process never trains — it spawns and supervises
            # the N-rank gang (resilience/distributed.py), forwarding its own
            # SIGTERM to the children and restarting the whole gang on failure
            from sheeprl_tpu.resilience.distributed import supervise_gang

            outcome = supervise_gang(cfg, overrides)
        elif supervisor_enabled(cfg):
            from sheeprl_tpu.resilience.supervisor import supervise

            outcome = supervise(
                cfg,
                run_algorithm,
                lambda c: resume_from_checkpoint(c, overrides=overrides),
                argv_cfg=argv_cfg,
            )
        else:
            run_algorithm(cfg)
            outcome = "preempted" if preemption_requested() else "completed"
    except RankFailureError as err:
        # a PEER died and this rank tore itself down (directly, or escaping the
        # in-process supervisor's multi-process step-aside path): exit with the
        # distinct code so whatever supervises the gang never blames this rank
        print(f"[sheeprl-resilience] {err}", file=sys.stderr)
        raise SystemExit(RANK_FAILED_EXIT_CODE) from err
    finally:
        # a crash that unwound past the loop's finalize() leaves its watchdog
        # running (an abort-mode one would os._exit a later in-process run)
        from sheeprl_tpu.resilience.watchdog import stop_all_watchdogs

        stop_all_watchdogs()
        if handler_installed:
            uninstall_preemption_handler()
    if outcome == "preempted":
        raise SystemExit(PREEMPTED_EXIT_CODE)


def diagnose(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py diagnose <run_dir>`` — merge the run's telemetry
    stream(s) (per-process files of decoupled topologies, supervisor attempts)
    and print a rule-based bottleneck report, writing machine-readable
    ``diagnosis.json`` next to the streams. See ``howto/observability.md``
    ("Diagnosing a run") for the detector catalog."""
    from sheeprl_tpu.obs.diagnose import main as diagnose_main

    return diagnose_main(list(args if args is not None else sys.argv[1:]))


def slo(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py slo <run_dir|fleet_dir|live_dir>`` — replay the
    run's telemetry windows through its declared SLOs (``metric.telemetry.slo``
    + per-run ``slo.yaml``): per-objective burn rates, error budget remaining,
    and the alert lifecycle recomputed offline and cross-checked against the
    in-loop ``alert`` events; writes machine-readable ``slo.json`` next to the
    streams. ``--fail-on warning|critical`` gates on FIRING alerts. See
    ``howto/observability.md`` ("SLOs, error budgets, and alerts")."""
    from sheeprl_tpu.obs.slo import main as slo_main

    return slo_main(list(args if args is not None else sys.argv[1:]))


def profile(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py profile <run_dir>`` — parse the run's
    ``jax.profiler`` window capture(s) (``metric.profiler.mode=window``) into
    op-category attribution (comm/mxu/elementwise/copy/loop/host/idle shares of
    device time), achieved FLOP/s + roofline position per registered fused
    program, writing machine-readable ``profile.json`` next to the streams.
    ``--fail-on warning|critical`` gates on the comm_bound/copy_bound/host_gap
    detectors. See ``howto/observability.md`` ("Profiling a fused program")."""
    from sheeprl_tpu.obs.xprof import main as profile_main

    return profile_main(list(args if args is not None else sys.argv[1:]))


def fault_matrix(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py fault-matrix`` — run the resilience fault matrix on
    the CPU mesh: every ``resilience``-marked smoke (single-process preempt /
    crash / ckpt_kill / env_step recovery AND the rank-targeted distributed
    smokes — kill_rank, stale_heartbeat, sigterm-to-one-rank under the gang
    supervisor, which gate on ``diagnose --fail-on critical`` internally).
    Extra arguments pass through to pytest (e.g. ``-k gang`` to scope, ``-q``).
    Exit code is pytest's — non-zero means a recovery path regressed."""
    import subprocess

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        os.path.join(repo_root, "tests", "test_resilience"),
        "-m",
        "resilience",
        "-q",
        "-p",
        "no:cacheprovider",
    ] + list(args if args is not None else sys.argv[1:])
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.call(cmd, env=env, cwd=repo_root)


def lint(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py lint [--aot] [--json] [--fail-on warning|critical]``
    — the JAX-aware static-analysis gate (howto/static_analysis.md): ~8 AST
    rules codifying the repo's known JAX/TPU hazard classes (global
    ``jax.devices()`` views, ungated ``platform_dependent`` TPU branches,
    unpinned Pallas dot precisions, host views feeding donated programs,
    host syncs inside jitted programs, unregistered telemetry events,
    training-loop hook completeness, config/code key drift), plus — with
    ``--aot`` — the fused-program contract sweep: every registered donated
    program is lowered for cpu+tpu off-chip and its donation/no-host-callback/
    collective contract asserted. Exceptions live in ``analysis/waivers.toml``,
    each with a reason; the gate holds at zero unwaived findings."""
    from sheeprl_tpu.analysis.engine import lint_main

    return lint_main(list(args if args is not None else sys.argv[1:]))


def fleet(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py fleet <spec.yaml>`` — schedule N member runs (seed/env
    sweeps) as one fleet: per-member bounded-restart supervision (resume strictly
    inside the member's dir), a SHARED persistent XLA compile cache (the first
    member compiles, the rest cold-start as cache hits), and fleet-level rollups
    — ``leaderboard.json`` ranked from the members' telemetry fingerprints +
    summaries, ``obs/compare`` findings across the sweep, ``--fail-on`` CI gate.
    See ``howto/fleet.md`` for the spec format and the leaderboard schema."""
    from sheeprl_tpu.fleet.runner import main as fleet_main

    return fleet_main(list(args if args is not None else sys.argv[1:]))


def trace(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py trace <run_dir|fleet_dir>`` — convert the run's
    merged telemetry stream(s) into a Perfetto/Chrome-trace JSON: one track per
    member/rank/role, phase spans per window, flow events linking the
    experience plane's ingest→sample and publish→refresh across process
    tracks. See ``howto/observability.md`` ("Tracing the dataflow")."""
    from sheeprl_tpu.obs.trace import main as trace_main

    return trace_main(list(args if args is not None else sys.argv[1:]))


def watch(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py watch <run_dir>`` — live terminal monitor over the
    run's telemetry stream(s) (follow mode: torn lines retried, late per-role
    streams and supervisor attempts picked up); exits with the run's status
    when its summary event lands. See ``howto/observability.md``
    ("Watching a live run")."""
    from sheeprl_tpu.obs.watch import main as watch_main

    return watch_main(list(args if args is not None else sys.argv[1:]))


def compare(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py compare <run_a> <run_b>`` — fingerprint-aware diff of
    two run dirs: per-window distributions (median/p90) of throughput, MFU and
    phases, compile/memory/restart totals, deltas flagged beyond the runs' own
    window spread, written to ``comparison.json``. See
    ``howto/observability.md`` ("Comparing runs / gating benchmarks")."""
    from sheeprl_tpu.obs.compare import main as compare_main

    return compare_main(list(args if args is not None else sys.argv[1:]))


def bench_diff(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py bench-diff <old.json> <new.json>`` — the BENCH_*.json
    regression gate (also available as ``bench.py --against``): workloads
    matched by metric name + fingerprint-compatible conditions, per-metric
    relative thresholds, ``--fail-on regression`` for CI."""
    from sheeprl_tpu.obs.compare import bench_diff_main

    return bench_diff_main(list(args if args is not None else sys.argv[1:]))


def serve(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py serve checkpoint_path=<ckpt> [serve.* overrides]`` —
    the policy serving tier (howto/serving.md): load any registered agent
    checkpoint (``checkpoint_path`` may be a file, a run dir, or a multi-rank
    checkpoint set — resolved through the supervisor's manifest-validated
    discovery), compile ONE donated fixed-shape step program, and serve
    concurrent sessions via continuous batching over a device-resident slot
    table. The robustness plane (howto/serving.md "Operating a server"): hot
    weight reload (``serve.reload.enabled``, zero recompiles), overload
    shedding (``serve.max_queue``) + per-request deadlines
    (``serve.deadline_ms``), SIGTERM → graceful drain (exit 75), ``/healthz``
    readiness on the metrics port, and ``serve.supervisor.*`` bounded-restart
    supervision. ``serve.prime=true`` compiles the serving programs into the
    persistent XLA cache and exits (cold-start priming, the ``sheeprl-compile``
    story for serving)."""
    from sheeprl_tpu.serve.main import serve_main

    return serve_main(list(args if args is not None else sys.argv[1:]))


def live(args: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py live <spec.yaml> [key=value ...]`` — the closed-loop
    flywheel (howto/live.md): serving slots double as actors. One supervised
    in-process gang runs N :class:`PolicyServer` roles (booted from the spec's
    ``checkpoint_path``, explore slots injecting session-seeded noise), every
    finished session's trajectory rides the experience service into ONE
    ``buffer.backend=service`` learner, and each published weight version
    hot-reloads into every server between ticks — zero recompiles. SIGTERM
    drains the whole gang (exit 75); ``watch``/``diagnose``/``trace`` stitch
    the session→ingest→train→publish→reload flow across the live dir's
    per-role telemetry streams."""
    from sheeprl_tpu.live.runner import live_main

    return live_main(list(args if args is not None else sys.argv[1:]))


def check_configs_evaluation(cfg: dotdict) -> None:
    if cfg.float32_matmul_precision not in ("default", "high", "highest"):
        raise ValueError(
            f"float32_matmul_precision must be one of default/high/highest, got {cfg.float32_matmul_precision}"
        )
    if cfg.checkpoint_path is None:
        raise ValueError("checkpoint_path must be specified")


def eval_algorithm(cfg: dotdict) -> None:
    """Single-device evaluation dispatch (reference cli.py:201-267)."""
    from sheeprl_tpu.parallel.fabric import Fabric

    entry = evaluation_registry.get(cfg.algo.name)
    if entry is None:
        available = ", ".join(sorted(evaluation_registry.keys()))
        raise ValueError(
            f"no evaluation registered for algorithm {cfg.algo.name!r}; available: {available}"
        )
    entry = entry[0]
    module = importlib.import_module(entry["module"])
    evaluate_fn = getattr(module, entry["entrypoint"])
    fabric = Fabric(
        devices=1,
        accelerator=cfg.fabric.get("accelerator", "auto"),
        precision=cfg.fabric.get("precision", "32-true"),
        checkpoint_backend=str((cfg.get("checkpoint") or {}).get("backend", "pickle")),
    )
    # pin the platform BEFORE loading: the sharded (orbax) checkpoint reader touches
    # jax, and backend discovery must respect fabric.accelerator=cpu (otherwise a
    # cpu-pinned eval would still initialize — and possibly block on — the TPU)
    fabric._setup()
    state = None
    if cfg.checkpoint_path:
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        state = load_checkpoint(cfg.checkpoint_path)
    fabric.launch(evaluate_fn, cfg, state)


def evaluation(args: Optional[Sequence[str]] = None) -> None:
    """``sheeprl-eval checkpoint_path=... [overrides]`` (reference cli.py:368-404)."""
    import yaml

    import sheeprl_tpu  # noqa: F401 - populate registries

    overrides = list(args if args is not None else sys.argv[1:])
    kv = dict(o.split("=", 1) for o in overrides if "=" in o)
    ckpt_path = kv.get("checkpoint_path")
    if ckpt_path is None:
        raise ValueError("you must specify checkpoint_path=...")
    # a run dir / experiment tree / multi-rank checkpoint set resolves to its
    # newest manifest-valid checkpoint — the same discovery rules the crash
    # supervisor and the serving tier use (resilience/discovery.py)
    from sheeprl_tpu.resilience.discovery import resolve_checkpoint_path

    ckpt_path = Path(resolve_checkpoint_path(ckpt_path))
    cfg_path = ckpt_path.parent.parent / "config.yaml"
    if not cfg_path.is_file():
        cfg_path = ckpt_path.parent / "config.yaml"
    with open(cfg_path) as f:
        base = yaml.safe_load(f)
    base["env"]["num_envs"] = 1
    base["env"]["capture_video"] = yaml.safe_load(kv.get("env.capture_video", "true"))
    base.setdefault("fabric", {})
    base["fabric"]["devices"] = 1
    base["checkpoint_path"] = str(ckpt_path)
    base["seed"] = int(kv.get("seed", base.get("seed", 42)))
    if "fabric.accelerator" in kv:
        base["fabric"]["accelerator"] = kv["fabric.accelerator"]
    cfg = dotdict(base)
    check_configs_evaluation(cfg)
    _apply_distribution_cfg(cfg)
    eval_algorithm(cfg)


def one_train_phase_steps(cfg: dotdict) -> int:
    """Smallest ``total_steps`` that carries a run through its FIRST gradient
    phase (compiling every act + train program the full run would compile):
    one rollout for on-policy algorithms; learning_starts plus enough steps for
    the replay-ratio governor to grant a gradient step for off-policy ones.

    Step accounting is GLOBAL (``policy_steps_per_iter = num_envs * world_size``
    in every training loop), so the budget scales with ``fabric.devices`` — a
    priming run at devices=4 must still reach its first train phase."""
    algo = cfg.algo
    devices = cfg.fabric.get("devices", 1)
    try:
        world_size = int(devices)
    except (TypeError, ValueError):
        # "auto" (and any other non-integer spelling) means "all local devices"
        # exactly like -1 — resolving it to 1 would under-budget a multi-device
        # priming run, which then never reaches its first train phase
        world_size = 0
    if world_size <= 0:  # -1 = "all local devices" (dp-cpu/dp-tpu fabric configs)
        import jax

        # resolve the count the way the Fabric will: pin the platform FIRST for
        # cpu fabrics, so counting devices can never initialize (and on a TPU
        # box, claim) the accelerator backend for a run that won't use it
        if str(cfg.fabric.get("accelerator", "auto")) == "cpu":
            jax.config.update("jax_platforms", "cpu")
        world_size = jax.local_device_count()
    steps_per_iter = int(cfg.env.num_envs) * max(world_size, 1)
    if "learning_starts" in algo:
        ratio = float(algo.get("replay_ratio", 1.0) or 1.0)
        return int(algo.learning_starts) + (int(1.0 / ratio) + 2) * steps_per_iter
    if "rollout_steps" in algo:
        return int(algo.rollout_steps) * steps_per_iter
    raise ValueError(
        f"cannot derive a one-train-phase step budget for {algo.name!r} "
        "(no rollout_steps or learning_starts); pass algo.total_steps yourself and use `sheeprl`"
    )


def compile_warm(args: Optional[Sequence[str]] = None) -> None:
    """``sheeprl-compile exp=... [overrides]`` — prime the persistent XLA compile
    cache for an experiment WITHOUT doing a real training run.

    TPU-first rationale: the fused train programs are compiled remotely on
    TPU backends, which takes MINUTES cold (observed >9 min for the Dreamer-V3
    train program over a tunneled v5e — see TPU_PROBE_LOG.md). Because compiled
    executables are keyed by (program, shapes) and every shape in a run is
    config-derived, running the exp for just long enough to reach its first
    train phase compiles the exact act + train programs the real run will use
    and lands them in the persistent cache (``sheeprl_tpu/utils/compile_cache.py``)
    — so the real job, a pod launch, or a benchmark run starts hot. No analogue
    exists in the reference (torch is eager); this is XLA-specific operational
    surface.

    The priming run disables logging/checkpointing/video/final-test and shrinks
    ``total_steps`` to one train phase:

    - on-policy (``algo.rollout_steps``): one rollout → one update,
    - off-policy / world-model (``algo.learning_starts`` + ``algo.replay_ratio``):
      learning_starts, then enough env steps for the replay-ratio governor to
      grant the first gradient step.

    Model/batch/sequence config is untouched — shapes must match the real run.
    Finetuning/offline entrypoints that need a checkpoint or dataset are not
    supported (prime their base exp instead).

    Serving: ``sheeprl-compile checkpoint_path=<ckpt> [serve.* overrides]``
    primes the SERVING tier instead — it AOT-compiles the batched slot-table
    step/attach programs for that checkpoint (exact slot count and obs shapes)
    into the same persistent cache, so ``sheeprl.py serve`` cold-starts as a
    cache hit. Equivalent to ``sheeprl.py serve ... serve.prime=true``."""
    import time

    import sheeprl_tpu  # noqa: F401 - populate registries

    overrides = list(args if args is not None else sys.argv[1:])
    if any(o.startswith("checkpoint_path=") for o in overrides):
        # serving-tier priming: the step program's shapes come from the
        # checkpoint + serve.* knobs, not from an exp config
        raise SystemExit(serve(overrides + ["serve.prime=true"]))
    cfg = compose(overrides)
    total = one_train_phase_steps(cfg)
    import tempfile

    scratch = tempfile.mkdtemp(prefix="sheeprl-compile-")
    prime_overrides = [
        f"algo.total_steps={total}",
        "algo.run_test=False",
        "metric.log_level=0",
        "metric.disable_timer=True",
        "checkpoint.save_last=False",
        f"checkpoint.every={max(total * 2, 1_000_000)}",
        # buffer capacity does not affect compiled program shapes, so the priming
        # buffer only needs to hold the priming steps — at real exp sizes (DV2:
        # 5M transitions) a memmap=False preallocation would OOM the host
        "buffer.memmap=False",
        f"buffer.size={max(total, 1)}",
        "env.capture_video=False",
        # artifacts (run dir, stray checkpoints) go to a throwaway dir — priming
        # must leave the user's logs/ tree untouched
        f"hydra.run.dir={scratch}",
    ]
    print(f"[sheeprl-compile] priming {cfg.algo.name} for {total} env steps: one full train phase")
    start = time.perf_counter()
    try:
        run(overrides + prime_overrides)
    finally:
        import shutil

        shutil.rmtree(scratch, ignore_errors=True)
    elapsed = time.perf_counter() - start
    import jax

    cache_dir = jax.config.jax_compilation_cache_dir
    if not cache_dir:
        print(
            f"[sheeprl-compile] WARNING: ran in {elapsed:.1f}s but the persistent "
            "compile cache is DISABLED (SHEEPRL_JAX_CACHE=0?) — nothing was "
            "persisted, the real run will still compile cold"
        )
        return
    n_entries = len(os.listdir(cache_dir)) if os.path.isdir(cache_dir) else 0
    print(
        f"[sheeprl-compile] done in {elapsed:.1f}s — persistent cache at "
        f"{cache_dir} now holds {n_entries} entries; the real run starts hot"
    )


def registration(args: Optional[Sequence[str]] = None) -> None:
    """Model-registry publication from a checkpoint (reference cli.py:407-449).
    Requires mlflow, which is optional."""
    from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

    if not _IS_MLFLOW_AVAILABLE:
        raise ModuleNotFoundError(
            "mlflow is not installed; the model-manager CLI requires it. "
            "Install mlflow to register models."
        )
    from sheeprl_tpu.utils.mlflow import register_model_from_checkpoint

    overrides = list(args if args is not None else sys.argv[1:])
    kv = dict(o.split("=", 1) for o in overrides if "=" in o)
    register_model_from_checkpoint(kv)
