"""Optimizers: reference-style constructor signatures mapped onto optax.

The config tree instantiates optimizers with torch-style keys (lr/eps/betas/alpha/
weight_decay — see sheeprl/configs/optim/*.yaml); these helpers translate them into
optax gradient transformations. ``rmsprop_tf`` reimplements the reference's TF-flavored
RMSprop (eps inside the sqrt, momentum applied on lr-scaled update —
sheeprl/optim/rmsprop_tf.py:14-156) as an optax transform.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax


def _maybe_weight_decay(tx: optax.GradientTransformation, weight_decay: float) -> optax.GradientTransformation:
    if weight_decay and weight_decay > 0:
        return optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


def adam(
    lr: float = 1e-3,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    betas: Sequence[float] = (0.9, 0.999),
    **_: Any,
) -> optax.GradientTransformation:
    b1, b2 = float(betas[0]), float(betas[1])
    if weight_decay and weight_decay > 0:
        return optax.adamw(lr, b1=b1, b2=b2, eps=float(eps), weight_decay=float(weight_decay))
    return optax.adam(lr, b1=b1, b2=b2, eps=float(eps))


def sgd(
    lr: float = 1e-3,
    momentum: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    **_: Any,
) -> optax.GradientTransformation:
    tx = optax.sgd(lr, momentum=float(momentum) or None, nesterov=bool(nesterov))
    return _maybe_weight_decay(tx, weight_decay)


def rmsprop(
    lr: float = 1e-2,
    alpha: float = 0.99,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    momentum: float = 0.0,
    centered: bool = False,
    **_: Any,
) -> optax.GradientTransformation:
    tx = optax.rmsprop(
        lr, decay=float(alpha), eps=float(eps), centered=bool(centered), momentum=float(momentum) or None
    )
    return _maybe_weight_decay(tx, weight_decay)


def scale_by_rms_tf(alpha: float = 0.99, eps: float = 1e-8, centered: bool = False) -> optax.GradientTransformation:
    """RMS scaling with epsilon *inside* the square root (TF semantics), matching the
    reference's RMSpropTF update rule (sheeprl/optim/rmsprop_tf.py:103-156: square_avg
    initialized at ones, ``avg = sqrt(square_avg + eps)``)."""

    def init(params):
        sq = jax.tree_util.tree_map(jnp.ones_like, params)
        if centered:
            return {"square_avg": sq, "grad_avg": jax.tree_util.tree_map(jnp.zeros_like, params)}
        return {"square_avg": sq}

    def update(updates, state, params=None):
        del params
        square_avg = jax.tree_util.tree_map(
            lambda s, g: alpha * s + (1 - alpha) * jnp.square(g), state["square_avg"], updates
        )
        if centered:
            grad_avg = jax.tree_util.tree_map(
                lambda m, g: alpha * m + (1 - alpha) * g, state["grad_avg"], updates
            )
            denom = jax.tree_util.tree_map(
                lambda s, m: jnp.sqrt(s - jnp.square(m) + eps), square_avg, grad_avg
            )
            new_state = {"square_avg": square_avg, "grad_avg": grad_avg}
        else:
            denom = jax.tree_util.tree_map(lambda s: jnp.sqrt(s + eps), square_avg)
            new_state = {"square_avg": square_avg}
        scaled = jax.tree_util.tree_map(lambda g, d: g / d, updates, denom)
        return scaled, new_state

    return optax.GradientTransformation(init, update)


def rmsprop_tf(
    lr: float = 1e-2,
    alpha: float = 0.99,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    momentum: float = 0.0,
    centered: bool = False,
    **_: Any,
) -> optax.GradientTransformation:
    parts = [scale_by_rms_tf(alpha=float(alpha), eps=float(eps), centered=bool(centered))]
    if momentum and momentum > 0:
        # TF-style: momentum buffer accumulates the lr-scaled update
        parts.append(optax.scale(float(lr)))
        parts.append(optax.trace(decay=float(momentum)))
        parts.append(optax.scale(-1.0))
        tx = optax.chain(*parts)
    else:
        parts.append(optax.scale(-float(lr)))
        tx = optax.chain(*parts)
    return _maybe_weight_decay(tx, weight_decay)
