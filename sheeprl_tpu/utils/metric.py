"""Metric aggregation without torchmetrics.

Provides the same surface the reference gets from torchmetrics + MetricAggregator
(sheeprl/utils/metric.py:17-195): named metrics with ``update/compute/reset``, a
class-level disable switch, NaN dropping on compute, and an optional cross-host sync.
State lives in plain Python floats on the host — metric updates must never force a
device sync on the hot path, so callers pass in numpy/float values they already have.
"""

from __future__ import annotations

import math
import sys
import warnings
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

Number = Union[int, float, np.ndarray]


def _is_device_array(value: Any) -> bool:
    """True for a live ``jax.Array`` — WITHOUT importing jax (the aggregator must
    stay importable in jax-free tooling, and an un-imported jax means no caller
    could have produced one anyway)."""
    jax_mod = sys.modules.get("jax")
    return jax_mod is not None and isinstance(value, jax_mod.Array)


def _to_float(value: Any) -> float:
    """Best-effort scalar conversion; jax/numpy arrays become their mean."""
    if isinstance(value, (int, float)):
        return float(value)
    arr = np.asarray(value)
    if arr.size == 0:
        return math.nan
    return float(arr.mean())


class Metric:
    """Minimal metric protocol: update(value) / compute() -> float / reset()."""

    def __init__(self, sync_on_compute: bool = False, **_: Any) -> None:
        self.sync_on_compute = sync_on_compute

    def update(self, value: Any) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def compute(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError

    def reset(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class MeanMetric(Metric):
    def __init__(self, sync_on_compute: bool = False, **kwargs: Any) -> None:
        super().__init__(sync_on_compute=sync_on_compute, **kwargs)
        self._total = 0.0
        self._count = 0

    def update(self, value: Any) -> None:
        v = _to_float(value)
        if math.isnan(v):
            return
        self._total += v
        self._count += 1

    def compute(self) -> float:
        if self._count == 0:
            return math.nan
        total, count = self._total, self._count
        if self.sync_on_compute:
            from sheeprl_tpu.parallel import distributed

            total = distributed.host_allsum(total)
            count = int(distributed.host_allsum(count))
        return total / count if count else math.nan

    def reset(self) -> None:
        self._total = 0.0
        self._count = 0


class SumMetric(Metric):
    def __init__(self, sync_on_compute: bool = False, **kwargs: Any) -> None:
        super().__init__(sync_on_compute=sync_on_compute, **kwargs)
        self._total = 0.0

    def update(self, value: Any) -> None:
        v = _to_float(value)
        if not math.isnan(v):
            self._total += v

    def compute(self) -> float:
        total = self._total
        if self.sync_on_compute:
            from sheeprl_tpu.parallel import distributed

            total = distributed.host_allsum(total)
        return total

    def reset(self) -> None:
        self._total = 0.0


class MaxMetric(Metric):
    def __init__(self, sync_on_compute: bool = False, **kwargs: Any) -> None:
        super().__init__(sync_on_compute=sync_on_compute, **kwargs)
        self._max = -math.inf

    def update(self, value: Any) -> None:
        v = _to_float(value)
        if not math.isnan(v):
            self._max = max(self._max, v)

    def compute(self) -> float:
        return self._max if self._max != -math.inf else math.nan

    def reset(self) -> None:
        self._max = -math.inf


class LastValueMetric(Metric):
    def __init__(self, sync_on_compute: bool = False, **kwargs: Any) -> None:
        super().__init__(sync_on_compute=sync_on_compute, **kwargs)
        self._value = math.nan

    def update(self, value: Any) -> None:
        self._value = _to_float(value)

    def compute(self) -> float:
        return self._value

    def reset(self) -> None:
        self._value = math.nan


class MetricAggregator:
    """Name → Metric dict with a class-level disable switch and NaN-dropping compute
    (mirrors sheeprl/utils/metric.py:17-146)."""

    disabled: bool = False
    # one-time-per-metric warning when a hot-path update is handed a device array
    # (np.asarray on a jax.Array blocks on the device — callers should pass host
    # values they already have). Set from cfg.metric.log_level in cli.run_algorithm.
    warn_device_values: bool = True
    _device_value_warned: set = set()

    def __init__(self, metrics: Optional[Dict[str, Any]] = None, raise_on_missing: bool = False) -> None:
        self.metrics: Dict[str, Metric] = {}
        for name, metric in dict(metrics or {}).items():
            if isinstance(metric, dict) and "_target_" in metric:
                from sheeprl_tpu.config import instantiate

                metric = instantiate(dict(metric))
            self.metrics[name] = metric
        self.raise_on_missing = raise_on_missing

    def add(self, name: str, metric: Metric) -> None:
        if name in self.metrics:
            raise ValueError(f"metric {name} already present")
        self.metrics[name] = metric

    def pop(self, name: str) -> None:
        if name not in self.metrics and self.raise_on_missing:
            raise KeyError(name)
        self.metrics.pop(name, None)

    def __contains__(self, name: str) -> bool:
        return name in self.metrics

    def update(self, name: str, value: Any) -> None:
        if self.disabled:
            return
        metric = self.metrics.get(name)
        if metric is None:
            if self.raise_on_missing:
                raise KeyError(name)
            return
        if (
            MetricAggregator.warn_device_values
            and name not in MetricAggregator._device_value_warned
            and _is_device_array(value)
        ):
            MetricAggregator._device_value_warned.add(name)
            warnings.warn(
                f"MetricAggregator.update({name!r}) received a jax.Array: converting it "
                "forces a blocking device sync on the training hot path. Pass a host "
                "value (np.asarray the batch of metrics once, or use packed_device_get).",
                stacklevel=2,
            )
        metric.update(value)

    def compute(self) -> Dict[str, float]:
        if self.disabled:
            return {}
        out: Dict[str, float] = {}
        for name, metric in self.metrics.items():
            value = metric.compute()
            if not (isinstance(value, float) and math.isnan(value)):
                out[name] = value
        return out

    def reset(self) -> None:
        for metric in self.metrics.values():
            metric.reset()

    def keys(self) -> Iterable[str]:
        return self.metrics.keys()


class RankIndependentMetricAggregator:
    """Per-rank metrics gathered host-side at compute (sheeprl/utils/metric.py:149-195)."""

    def __init__(self, metrics: Dict[str, Metric]) -> None:
        self.aggregator = MetricAggregator(metrics)

    def update(self, name: str, value: Any) -> None:
        self.aggregator.update(name, value)

    def compute(self) -> List[Dict[str, float]]:
        from sheeprl_tpu.parallel import distributed

        return distributed.host_allgather_object(self.aggregator.compute())

    def reset(self) -> None:
        self.aggregator.reset()
