"""Memory-mapped numpy arrays with file-ownership + spawn-safe pickling.

Same capability surface as the reference's MemmapArray (sheeprl/utils/memmap.py:22-270):
a disk-backed array container that can be sent across process boundaries (pickled as
metadata, re-opened on the other side without taking ownership) so replay buffers larger
than RAM can back the host side of the TPU input pipeline.
"""

from __future__ import annotations

import os
import tempfile
import warnings
from pathlib import Path
from typing import Any, Tuple

import numpy as np

_VALID_MODES = ("r+", "w+", "c", "copyonwrite", "readwrite", "write")


class MemmapArray(np.lib.mixins.NDArrayOperatorsMixin):
    def __init__(
        self,
        shape: int | Tuple[int, ...],
        dtype: Any = None,
        mode: str = "r+",
        reset: bool = False,
        filename: str | os.PathLike | None = None,
    ):
        if mode not in _VALID_MODES:
            raise ValueError(f"mode must be one of {_VALID_MODES}, got {mode!r}")
        if filename is None:
            fd, path = tempfile.mkstemp(".memmap")
            os.close(fd)
            self._filename = Path(path).resolve()
        else:
            path = Path(filename).resolve()
            if path.exists():
                warnings.warn(
                    "The specified filename already exists; modifications may be reflected.",
                    category=UserWarning,
                )
            path.parent.mkdir(parents=True, exist_ok=True)
            path.touch(exist_ok=True)
            self._filename = path
        self._dtype = np.dtype(dtype) if dtype is not None else None
        self._shape = tuple(shape) if not isinstance(shape, int) else (shape,)
        self._mode = mode
        self._array: np.memmap | None = np.memmap(
            filename=self._filename, dtype=self._dtype, shape=self._shape, mode=self._mode
        )
        if reset:
            self._array[:] = 0
        self._has_ownership = True

    # -- properties -----------------------------------------------------------------

    @property
    def filename(self) -> Path:
        return self._filename

    @property
    def dtype(self) -> Any:
        return self._dtype

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def has_ownership(self) -> bool:
        return self._has_ownership

    @has_ownership.setter
    def has_ownership(self, value: bool) -> None:
        self._has_ownership = bool(value)

    @property
    def array(self) -> np.memmap:
        if self._array is None:
            self._array = np.memmap(
                filename=self._filename, dtype=self._dtype, shape=self._shape, mode=self._mode
            )
        return self._array

    @array.setter
    def array(self, value: np.ndarray | "MemmapArray") -> None:
        if isinstance(value, MemmapArray):
            # ownership transfer: point at the other file, stealing ownership
            if os.path.abspath(value.filename) != os.path.abspath(self._filename):
                self.__del__()
                self._filename = value.filename
                self._dtype = value.dtype
                self._shape = value.shape
                self._mode = value.mode
                self._array = None
            value.has_ownership = False
            self._has_ownership = True
        else:
            value = np.asarray(value)
            if value.shape != self._shape:
                raise ValueError(f"shape mismatch: {value.shape} vs {self._shape}")
            self.array[:] = value

    # -- construction ----------------------------------------------------------------

    @classmethod
    def from_array(
        cls,
        array: np.ndarray | "MemmapArray",
        mode: str = "r+",
        filename: str | os.PathLike | None = None,
    ) -> "MemmapArray":
        is_memmap = isinstance(array, MemmapArray)
        source = array.array if is_memmap else np.asarray(array)
        same_file = (
            is_memmap
            and filename is not None
            and os.path.abspath(filename) == os.path.abspath(array.filename)
        )
        out = cls(shape=source.shape, dtype=source.dtype, mode=mode, filename=filename)
        if same_file:
            array.has_ownership = False
        else:
            out.array[:] = source[:]
            out.array.flush()
        return out

    # -- numpy interop ---------------------------------------------------------------

    def __array__(self, dtype: Any = None) -> np.ndarray:
        arr = self.array
        return np.asarray(arr, dtype=dtype) if dtype is not None else np.asarray(arr)

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        inputs = tuple(np.asarray(i.array) if isinstance(i, MemmapArray) else i for i in inputs)
        return getattr(ufunc, method)(*inputs, **kwargs)

    def __getitem__(self, idx: Any) -> np.ndarray:
        return self.array[idx]

    def __setitem__(self, idx: Any, value: Any) -> None:
        self.array[idx] = value

    def __len__(self) -> int:
        return self._shape[0]

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def size(self) -> int:
        return int(np.prod(self._shape))

    def reshape(self, *shape: int) -> np.ndarray:
        return self.array.reshape(*shape)

    def flush(self) -> None:
        if self._array is not None:
            self._array.flush()

    # -- pickling across process boundaries (spawn-safe) -----------------------------

    def __getstate__(self) -> dict:
        state = self.__dict__.copy()
        state["_array"] = None
        # the receiving process must never delete the file
        state["_has_ownership"] = False
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)

    def __del__(self) -> None:
        try:
            if getattr(self, "_has_ownership", False) and self._array is not None:
                self._array.flush()
            if getattr(self, "_has_ownership", False) and getattr(self, "_filename", None) is not None:
                self._array = None
                if os.path.isfile(self._filename):
                    os.unlink(self._filename)
        except Exception:
            pass

    def __repr__(self) -> str:
        return f"MemmapArray(shape={self._shape}, dtype={self._dtype}, mode={self._mode}, filename={self._filename})"
