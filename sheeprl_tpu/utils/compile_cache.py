"""Persistent XLA compilation cache policy, shared by every entry point (CLI,
tests, driver hooks). The fused train programs take tens of seconds to compile;
caching them on disk lets later processes skip the compile entirely. Opt out with
``SHEEPRL_JAX_CACHE=0`` or point ``SHEEPRL_JAX_CACHE`` at another directory."""

from __future__ import annotations

import os


def enable_compile_cache() -> None:
    import jax

    cache_dir = os.environ.get(
        "SHEEPRL_JAX_CACHE", os.path.expanduser("~/.cache/sheeprl_tpu/jax")
    )
    if cache_dir not in ("0", ""):
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass
