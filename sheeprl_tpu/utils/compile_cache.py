"""Persistent XLA compilation cache policy, shared by every entry point (CLI,
tests, driver hooks). The fused train programs take tens of seconds to compile;
caching them on disk lets later processes skip the compile entirely. Opt out with
``SHEEPRL_JAX_CACHE=0`` or point ``SHEEPRL_JAX_CACHE`` at another directory.

The default cache dir is suffixed with a host-CPU-feature fingerprint: XLA:CPU
AOT-compiles against the build machine's feature set, and loading such an entry
on a machine with different features can SIGILL (cpu_aot_loader warns about
exactly this). Fingerprinting the dir means a cache written on one machine is
simply invisible on a different one instead of a hazard. An explicit
``SHEEPRL_JAX_CACHE=<dir>`` is used verbatim — the caller owns the key."""

from __future__ import annotations

import hashlib
import os
import platform


def _cpu_fingerprint() -> str:
    """Short stable hash of the host's CPU ISA features (+ arch)."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    # sorted: flag ORDER is not guaranteed stable across kernels
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    if not flags:
        # Non-Linux host (no /proc/cpuinfo): fall back to the coarser
        # OS/release/processor identity for per-machine-class separation. Linux
        # keeps the pure ISA-flags key so kernel upgrades don't churn the cache.
        flags = f"{platform.platform()}|{platform.processor()}"
    raw = f"{platform.machine()}|{flags}"
    return hashlib.sha256(raw.encode()).hexdigest()[:12]


def enable_compile_cache() -> None:
    import jax

    cache_dir = os.environ.get("SHEEPRL_JAX_CACHE")
    if cache_dir is None:
        cache_dir = os.path.expanduser(f"~/.cache/sheeprl_tpu/jax-{_cpu_fingerprint()}")
    if cache_dir not in ("0", ""):
        # Persistence threshold: programs compiling faster than this are not
        # written to the cache (default 1 s — sub-second CPU programs are cheaper
        # to recompile than to deserialize on a real chip). The fleet runner
        # (sheeprl_tpu/fleet) sets the env override to 0 so EVERY member program
        # persists and the sweep's later members cold-start as pure cache hits.
        try:
            min_secs = float(os.environ.get("SHEEPRL_JAX_CACHE_MIN_COMPILE_SECS", "1.0"))
        except ValueError:
            min_secs = 1.0
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", min_secs)
        except Exception:
            pass
