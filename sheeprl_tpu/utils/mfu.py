"""Model-FLOPs-utilization accounting.

The reference publishes wall-clocks only (README.md:99-189); on TPU the honest
efficiency metric is MFU: FLOPs the compiled program performs per second, over the
chip's peak. XLA already knows the program's FLOPs — ``compiled.cost_analysis()``
— so no analytic per-layer counting is needed; this works for any jitted program
(train steps, act steps, kernels alike).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Mapping, Optional, Sequence

# bf16 peak FLOP/s per chip (public spec sheets). Keyed by lowercase substrings of
# jax's Device.device_kind.
_TPU_PEAK_BF16: Dict[str, float] = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops(device) -> Optional[float]:
    """Peak bf16 FLOP/s for ``device``, or None when unknown (e.g. host CPU)."""
    if device.platform not in ("tpu", "axon"):  # axon = tunneled-TPU plugin platform
        return None
    kind = (getattr(device, "device_kind", "") or "").lower()
    for tag, peak in sorted(_TPU_PEAK_BF16.items(), key=lambda kv: -len(kv[0])):
        if tag in kind:
            return peak
    return None


def compiled_flops(compiled) -> Optional[float]:
    """Total FLOPs of a compiled program, from XLA's own cost model. Handles both
    cost_analysis() return conventions (dict, or list of one dict per program)."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    flops = cost.get("flops") if isinstance(cost, dict) else None
    return float(flops) if flops and flops > 0 else None


def abstractify(tree: Any) -> Any:
    """Replace every array leaf of a pytree with a ``jax.ShapeDtypeStruct`` so a
    jitted program can be re-lowered from METADATA only — no device reads, and
    safe to build from values that were donated to the program being analyzed.
    ``jax.Array`` leaves keep their sharding (a dp-sharded program must be
    analyzed as the sharded program XLA actually runs); non-array leaves
    (python scalars) pass through untouched.
    """
    import jax
    import numpy as np

    def _leaf(x: Any) -> Any:
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if isinstance(x, jax.Array):
            try:
                from jax.sharding import NamedSharding

                # only mesh shardings carry placement the program depends on; a
                # SingleDeviceSharding (e.g. an uncommitted scalar that landed on
                # device 0) must stay unspecified, or lowering rejects the mix of
                # device sets that the real call happily accepts
                if isinstance(x.sharding, NamedSharding):
                    return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            except Exception:
                pass
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        if isinstance(x, np.ndarray):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(_leaf, tree)


def unit_avals(tree: Any) -> Any:
    """Per-unit avals of a ``[G, ...]`` replay block: each leaf's leading
    (gradient-step) axis dropped, SHARDING PRESERVED for the remaining axes.

    The dreamer-family loops drive a single-step jitted program over the block's
    leading axis, so the program's batch aval is the ``a[0]`` slice — and on a dp
    mesh that slice is still batch-axis sharded. Rebuilding the aval from
    ``(a.shape[1:], a.dtype)`` alone would make :func:`program_analysis` lower a
    REPLICATED variant: wrong FLOPs/memory for MFU, and a compile-cache MISS that
    turns the analysis compile into a cold one. The loops stage blocks with the
    leading axis unsharded, so dropping the spec's first entry yields the live
    per-unit sharding exactly.
    """
    import jax
    import numpy as np

    def _leaf(a: Any) -> Any:
        shape, dtype = a.shape[1:], a.dtype
        if isinstance(a, jax.Array) and not isinstance(a, jax.core.Tracer):
            try:
                from jax.sharding import NamedSharding, PartitionSpec

                sharding = a.sharding
                if isinstance(sharding, NamedSharding):
                    spec = tuple(sharding.spec)
                    unit_spec = PartitionSpec(*spec[1:]) if len(spec) > 1 else PartitionSpec()
                    return jax.ShapeDtypeStruct(
                        shape, dtype, sharding=NamedSharding(sharding.mesh, unit_spec)
                    )
            except Exception:
                pass
        if isinstance(a, (jax.Array, np.ndarray)):
            return jax.ShapeDtypeStruct(shape, dtype)
        return a

    return jax.tree_util.tree_map(_leaf, tree)


def program_analysis(
    fn: Callable,
    args: Sequence[Any],
    kwargs: Optional[Mapping[str, Any]] = None,
    *,
    compile: bool = True,
) -> Dict[str, Any]:
    """One-shot static analysis of a jitted program at the given argument shapes:
    FLOPs/bytes from XLA's cost model plus (when ``compile``) the compiled
    executable's ``memory_analysis()`` buffer sizes.

    The arguments are abstracted to avals first (see :func:`abstractify`), so
    nothing executes and donated inputs are never touched. With ``compile`` the
    lowering is backend-compiled — on a run that already compiled the same
    program this hits the in-process/persistent compile cache rather than paying
    a second cold compile; the observed compile wall time is returned either way
    (``compile_seconds``).
    """
    lowered = fn.lower(*abstractify(tuple(args)), **(kwargs or {}))
    out: Dict[str, Any] = {
        "flops": None,
        "bytes_accessed": None,
        "compile_seconds": None,
        "memory": None,
    }
    cost_src = lowered
    if compile:
        t0 = time.perf_counter()
        compiled = lowered.compile()
        out["compile_seconds"] = time.perf_counter() - t0
        cost_src = compiled
        try:
            mem = compiled.memory_analysis()
            if mem is not None:
                out["memory"] = {
                    "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
                    "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
                    "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
                    "alias_bytes": int(getattr(mem, "alias_size_in_bytes", 0)),
                    "generated_code_bytes": int(getattr(mem, "generated_code_size_in_bytes", 0)),
                }
        except Exception:
            pass
    out["flops"] = compiled_flops(cost_src)
    try:
        cost = cost_src.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if isinstance(cost, dict):
            ba = cost.get("bytes accessed")
            out["bytes_accessed"] = float(ba) if ba else None
    except Exception:
        pass
    return out


def measure_mfu(
    fn: Callable,
    args: Sequence[Any],
    *,
    warmup: int = 2,
    reps: int = 5,
    device=None,
) -> Dict[str, Any]:
    """Jit ``fn``, read its FLOPs from the compiled cost model, time ``reps``
    steady-state executions, and relate the achieved FLOP/s to the chip peak.

    Returns flops_per_step / step_seconds / flops_per_sec always; ``mfu`` is None
    off-TPU (no meaningful peak) or when XLA reports no FLOPs.
    """
    import jax

    jitted = jax.jit(fn)
    compiled = jitted.lower(*args).compile()
    flops = compiled_flops(compiled)
    for _ in range(max(1, warmup)):
        out = compiled(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = compiled(*args)
    jax.block_until_ready(out)
    step_seconds = (time.perf_counter() - t0) / reps

    if device is None:
        leaves = jax.tree_util.tree_leaves(out)
        # local_devices, not jax.devices(): the global list spans every process
        # of a multi-process run, so index 0 may be ANOTHER process's chip — a
        # non-rank-0 caller must fall back to a device it actually owns
        # (graftlint jax-devices-global-view)
        device = next(iter(leaves[0].devices())) if leaves else jax.local_devices()[0]
    peak = peak_flops(device)
    flops_per_sec = (flops / step_seconds) if flops else None
    return {
        "flops_per_step": flops,
        "step_seconds": step_seconds,
        "flops_per_sec": flops_per_sec,
        "peak_flops": peak,
        "device_kind": getattr(device, "device_kind", device.platform),
        "mfu": (flops_per_sec / peak) if (flops_per_sec and peak) else None,
    }
