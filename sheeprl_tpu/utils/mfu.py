"""Model-FLOPs-utilization accounting.

The reference publishes wall-clocks only (README.md:99-189); on TPU the honest
efficiency metric is MFU: FLOPs the compiled program performs per second, over the
chip's peak. XLA already knows the program's FLOPs — ``compiled.cost_analysis()``
— so no analytic per-layer counting is needed; this works for any jitted program
(train steps, act steps, kernels alike).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Sequence

# bf16 peak FLOP/s per chip (public spec sheets). Keyed by lowercase substrings of
# jax's Device.device_kind.
_TPU_PEAK_BF16: Dict[str, float] = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def peak_flops(device) -> Optional[float]:
    """Peak bf16 FLOP/s for ``device``, or None when unknown (e.g. host CPU)."""
    if device.platform not in ("tpu", "axon"):  # axon = tunneled-TPU plugin platform
        return None
    kind = (getattr(device, "device_kind", "") or "").lower()
    for tag, peak in sorted(_TPU_PEAK_BF16.items(), key=lambda kv: -len(kv[0])):
        if tag in kind:
            return peak
    return None


def compiled_flops(compiled) -> Optional[float]:
    """Total FLOPs of a compiled program, from XLA's own cost model. Handles both
    cost_analysis() return conventions (dict, or list of one dict per program)."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    flops = cost.get("flops") if isinstance(cost, dict) else None
    return float(flops) if flops and flops > 0 else None


def measure_mfu(
    fn: Callable,
    args: Sequence[Any],
    *,
    warmup: int = 2,
    reps: int = 5,
    device=None,
) -> Dict[str, Any]:
    """Jit ``fn``, read its FLOPs from the compiled cost model, time ``reps``
    steady-state executions, and relate the achieved FLOP/s to the chip peak.

    Returns flops_per_step / step_seconds / flops_per_sec always; ``mfu`` is None
    off-TPU (no meaningful peak) or when XLA reports no FLOPs.
    """
    import jax

    jitted = jax.jit(fn)
    compiled = jitted.lower(*args).compile()
    flops = compiled_flops(compiled)
    for _ in range(max(1, warmup)):
        out = compiled(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = compiled(*args)
    jax.block_until_ready(out)
    step_seconds = (time.perf_counter() - t0) / reps

    if device is None:
        leaves = jax.tree_util.tree_leaves(out)
        device = next(iter(leaves[0].devices())) if leaves else jax.devices()[0]
    peak = peak_flops(device)
    flops_per_sec = (flops / step_seconds) if flops else None
    return {
        "flops_per_step": flops,
        "step_seconds": step_seconds,
        "flops_per_sec": flops_per_sec,
        "peak_flops": peak,
        "device_kind": getattr(device, "device_kind", device.platform),
        "mfu": (flops_per_sec / peak) if (flops_per_sec and peak) else None,
    }
