"""MLflow model-registry integration (role of sheeprl/utils/mlflow.py:35-427).

TPU-native twist: there are no torch ``nn.Module``s to pickle — a "model" here is a
named parameter pytree (the same subtrees the checkpoints store, e.g. Dreamer's
``world_model`` / ``actor`` / ``critic``). Each registered model version is an MLflow
run artifact holding the flax-serialized pytree plus a small JSON manifest, and the
registry CRUD (versions, stage transitions, deletion, best-model selection, download)
matches the reference ``MlflowModelManager`` surface.

Every entrypoint import-gates on mlflow (optional dependency, reference
utils/imports.py) — importing this module without mlflow raises a clear error.
"""

from __future__ import annotations

import json
import os
import tempfile
from datetime import datetime
from typing import Any, Dict, Mapping, Optional

from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

if not _IS_MLFLOW_AVAILABLE:
    raise ModuleNotFoundError("mlflow is not installed: pip install mlflow")

import mlflow  # noqa: E402

MODEL_ARTIFACT_NAME = "params.msgpack"


def get_or_create_experiment(experiment_name: str) -> str:
    """Shared get-or-create for MLflow experiments (used by both the logger and the
    registration flow so deleted-experiment edge-case fixes live in one place)."""
    experiment = mlflow.get_experiment_by_name(experiment_name)
    if experiment is None:
        return mlflow.create_experiment(experiment_name)
    return experiment.experiment_id


def _serialize_params(params: Any) -> bytes:
    from flax import serialization

    return serialization.to_bytes(params)


def log_params_as_model(name: str, params: Any, extra_manifest: Optional[Dict[str, Any]] = None):
    """Log one named parameter pytree as an artifact directory of the ACTIVE run and
    return its ``runs:/`` model URI (the role of mlflow.pytorch.log_model in the
    reference's per-algo ``log_models``)."""
    import jax

    with tempfile.TemporaryDirectory() as tmp:
        model_dir = os.path.join(tmp, name)
        os.makedirs(model_dir, exist_ok=True)
        with open(os.path.join(model_dir, MODEL_ARTIFACT_NAME), "wb") as f:
            f.write(_serialize_params(params))
        manifest = {
            "name": name,
            "format": "flax.serialization.to_bytes",
            "n_leaves": len(jax.tree_util.tree_leaves(params)),
            **(extra_manifest or {}),
        }
        with open(os.path.join(model_dir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2, default=str)
        mlflow.log_artifacts(model_dir, artifact_path=name)
    run = mlflow.active_run()
    return f"runs:/{run.info.run_id}/{name}"


class MlflowModelManager:
    """Registry CRUD over MlflowClient (reference MlflowModelManager,
    sheeprl/utils/mlflow.py:75-327: register/get_latest_version/transition/delete/
    register_best_models/download)."""

    def __init__(self, tracking_uri: Optional[str] = None):
        self.tracking_uri = tracking_uri or os.getenv("MLFLOW_TRACKING_URI")
        if self.tracking_uri is None:
            raise ValueError(
                "The tracking uri is not defined: pass tracking_uri or set the "
                "MLFLOW_TRACKING_URI environment variable."
            )
        mlflow.set_tracking_uri(self.tracking_uri)
        self.client = mlflow.MlflowClient(self.tracking_uri)

    @staticmethod
    def _stamp(description: Optional[str]) -> str:
        when = datetime.today().strftime("%Y-%m-%d %H:%M:%S")
        return f"{description or ''}\nRegistered at: {when}".strip()

    def register_model(
        self,
        model_uri: str,
        model_name: str,
        description: Optional[str] = None,
        tags: Optional[Mapping[str, Any]] = None,
    ):
        version = mlflow.register_model(model_uri=model_uri, name=model_name, tags=dict(tags or {}))
        self.client.update_model_version(model_name, version.version, self._stamp(description))
        return version

    def get_latest_version(self, model_name: str):
        versions = self.client.search_model_versions(f"name = '{model_name}'")
        if not versions:
            raise ValueError(f"no versions registered for model {model_name!r}")
        return max(versions, key=lambda v: int(v.version))

    def transition_model(
        self,
        model_name: str,
        version: int,
        stage: str,
        description: Optional[str] = None,
    ):
        self.client.transition_model_version_stage(model_name, str(version), stage)
        if description:
            self.client.update_model_version(model_name, str(version), self._stamp(description))
        return self.client.get_model_version(model_name, str(version))

    def delete_model(self, model_name: str, version: int) -> None:
        self.client.delete_model_version(model_name, str(version))

    def register_best_models(
        self,
        experiment_name: str,
        models_info: Mapping[str, Mapping[str, Any]],
        metric: str = "Test/cumulative_reward",
        mode: str = "max",
    ) -> None:
        """Select the best run of an experiment by ``metric`` and register its models
        (reference mlflow.py:214-279)."""
        if mode not in ("max", "min"):
            raise ValueError(f"mode must be 'max' or 'min', got {mode!r}")
        experiment = mlflow.get_experiment_by_name(experiment_name)
        if experiment is None:
            raise ValueError(f"experiment {experiment_name!r} not found")
        order = "DESC" if mode == "max" else "ASC"
        runs = self.client.search_runs(
            [experiment.experiment_id], order_by=[f"metrics.`{metric}` {order}"], max_results=1
        )
        if not runs:
            raise ValueError(f"no runs found for experiment {experiment_name!r}")
        best = runs[0]
        for name, info in models_info.items():
            self.register_model(
                f"runs:/{best.info.run_id}/{name}",
                info["model_name"],
                info.get("description"),
                info.get("tags"),
            )

    def download_model(self, model_name: str, version: int, output_path: str) -> None:
        os.makedirs(output_path, exist_ok=True)
        uri = f"models:/{model_name}/{version}"
        mlflow.artifacts.download_artifacts(artifact_uri=uri, dst_path=output_path)


def _walk_named_subtree(node: Any, name: str):
    """Resolve a registry model name against a nested param mapping by greedy
    longest-key prefix matching: ``moments_exploration_intrinsic`` walks
    ``node['exploration']['intrinsic']``, ``world_model`` matches the literal key."""
    if isinstance(node, Mapping) and name in node:
        return node[name]
    if isinstance(node, Mapping):
        for key in sorted(node, key=len, reverse=True):
            if name.startswith(key + "_"):
                try:
                    return _walk_named_subtree(node[key], name[len(key) + 1 :])
                except KeyError:
                    continue
    raise KeyError(name)


def models_from_checkpoint_state(state: Dict[str, Any], model_names) -> Dict[str, Any]:
    """Map registry model names onto checkpoint subtrees: ``agent`` is the whole
    parameter tree, ``moments*`` resolve inside the ``moments`` state (per-stream
    Moments like p2e_dv3's ``{'task', 'exploration': {'intrinsic', 'extrinsic'}}``
    resolve to their own subtree, never the whole dict), anything else is a named
    subtree of ``state['agent']`` (Dreamer world_model/actor/critic/...)."""
    params = state["agent"]
    out: Dict[str, Any] = {}
    for name in model_names:
        if name == "agent":
            out[name] = params
        elif name == "moments" or name.startswith("moments_"):
            if "moments" not in state:
                raise KeyError(f"checkpoint has no moments state for model {name!r}")
            if name == "moments":
                out[name] = state["moments"]
            else:
                try:
                    out[name] = _walk_named_subtree(state["moments"], name[len("moments_") :])
                except KeyError:
                    raise KeyError(
                        f"model {name!r} does not resolve inside the checkpoint's moments "
                        f"state (top-level keys: {list(state['moments'])})"
                    ) from None
        else:
            try:
                out[name] = _walk_named_subtree(params, name)
            except KeyError:
                raise KeyError(
                    f"model {name!r} not found in the checkpoint "
                    f"(available: {list(params.keys()) if isinstance(params, Mapping) else 'agent'})"
                ) from None
    return out


def register_model_from_checkpoint(kv: Dict[str, str]) -> Dict[str, Any]:
    """``sheeprl-registration checkpoint_path=... [tracking_uri=...]`` — load the
    checkpoint + its run config, log each model_manager-selected parameter tree as a
    run artifact and register it (reference cli.py:407-449 +
    utils/mlflow.py:330-381). Returns {model_name: registered version}."""
    import yaml

    from sheeprl_tpu.config.dotdict import dotdict
    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    ckpt_path = kv.get("checkpoint_path")
    if ckpt_path is None:
        raise ValueError("you must specify checkpoint_path=...")
    cfg_path = os.path.join(os.path.dirname(ckpt_path), "..", "config.yaml")
    if not os.path.isfile(cfg_path):
        cfg_path = os.path.join(os.path.dirname(ckpt_path), "config.yaml")
    with open(cfg_path) as f:
        cfg = dotdict(yaml.safe_load(f))

    tracking_uri = kv.get("tracking_uri") or os.getenv("MLFLOW_TRACKING_URI")
    manager = MlflowModelManager(tracking_uri)

    state = load_checkpoint(ckpt_path)

    mm = cfg.get("model_manager") or {}
    models_cfg = dict(mm.get("models") or {})
    if not models_cfg:
        raise RuntimeError(
            "model_manager.models is empty; select a model_manager config for this "
            "algorithm (e.g. model_manager@model_manager=dreamer_v3)"
        )
    models = models_from_checkpoint_state(state, models_cfg.keys())

    exp_name = kv.get("experiment_name", cfg.get("exp_name", cfg.algo.name))
    experiment_id = get_or_create_experiment(exp_name)
    run_name = f"{cfg.algo.name}_{cfg.env.id}_{datetime.today().strftime('%Y-%m-%d %H:%M:%S')}"
    registered: Dict[str, Any] = {}
    with mlflow.start_run(experiment_id=experiment_id, run_name=run_name):
        for name, model_cfg in models_cfg.items():
            uri = log_params_as_model(name, models[name], {"checkpoint_path": ckpt_path})
            registered[model_cfg["model_name"]] = manager.register_model(
                uri, model_cfg["model_name"], model_cfg.get("description"), model_cfg.get("tags")
            )
    return registered
