"""Probability distributions, JAX-native.

Re-provides the reference's distribution toolbox (sheeprl/utils/distribution.py:
TruncatedNormal:55, SymlogDistribution:152, MSEDistribution:196,
TwoHotEncodingDistribution:224, OneHotCategorical(+ST):281/386, BernoulliSafeMode:407)
as lightweight stateless classes. Everything is traceable under jit: sampling takes an
explicit PRNG key, straight-through gradients use ``stop_gradient`` composition.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.utils import symexp, symlog

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)

# Module-level argument-validation switch, set from ``cfg.distribution.validate_args``
# at CLI startup (role of the reference's global torch-distribution toggle,
# sheeprl/cli.py:71 + configs/distribution/default.yaml). Under jit only *static*
# properties (shapes, dtypes, broadcastability) can be validated — value-dependent
# checks would need checkify — and shape bugs are exactly what the toggle catches.
_VALIDATE_ARGS = False


def set_validate_args(enabled: bool) -> None:
    global _VALIDATE_ARGS
    _VALIDATE_ARGS = bool(enabled)


def validate_args_enabled() -> bool:
    return _VALIDATE_ARGS


def _check_broadcastable(name: str, value: jax.Array, *params: jax.Array) -> None:
    if not _VALIDATE_ARGS:
        return
    batch_shape = jnp.broadcast_shapes(*(jnp.shape(p) for p in params))
    try:
        jnp.broadcast_shapes(jnp.shape(value), batch_shape)
    except ValueError as err:
        raise ValueError(
            f"{name}.log_prob: value shape {tuple(jnp.shape(value))} is not broadcastable "
            f"against the distribution's batch shape {tuple(batch_shape)}"
        ) from err


def _check_last_dim(name: str, value: jax.Array, size: int) -> None:
    if not _VALIDATE_ARGS:
        return
    if value.shape[-1] != size:
        raise ValueError(
            f"{name}.log_prob: value's event dimension is {value.shape[-1]}, expected {size}"
        )


def _sum_rightmost(x: jax.Array, ndims: int) -> jax.Array:
    if ndims == 0:
        return x
    return x.sum(axis=tuple(range(-ndims, 0)))


class Distribution:
    """Minimal distribution protocol: mean/mode/sample/log_prob/entropy."""

    @property
    def mean(self) -> jax.Array:  # pragma: no cover - interface
        raise NotImplementedError

    @property
    def mode(self) -> jax.Array:  # pragma: no cover - interface
        raise NotImplementedError

    def sample(self, key: jax.Array) -> jax.Array:  # pragma: no cover - interface
        raise NotImplementedError

    def log_prob(self, value: jax.Array) -> jax.Array:  # pragma: no cover - interface
        raise NotImplementedError

    def entropy(self) -> jax.Array:  # pragma: no cover - interface
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc: jax.Array, scale: jax.Array):
        self.loc = loc
        self.scale = scale

    @property
    def mean(self) -> jax.Array:
        return self.loc

    @property
    def mode(self) -> jax.Array:
        return self.loc

    @property
    def stddev(self) -> jax.Array:
        return self.scale

    def sample(self, key: jax.Array) -> jax.Array:
        return self.loc + self.scale * jax.random.normal(key, self.loc.shape, self.loc.dtype)

    def rsample(self, key: jax.Array) -> jax.Array:
        return self.sample(key)

    def log_prob(self, value: jax.Array) -> jax.Array:
        _check_broadcastable("Normal", value, self.loc, self.scale)
        var = jnp.square(self.scale)
        return -jnp.square(value - self.loc) / (2 * var) - jnp.log(self.scale) - _HALF_LOG_2PI

    def entropy(self) -> jax.Array:
        return 0.5 + _HALF_LOG_2PI + jnp.log(self.scale)


class Independent(Distribution):
    """Reinterpret the rightmost batch dims of a base distribution as event dims."""

    def __init__(self, base: Distribution, reinterpreted_batch_ndims: int = 1):
        self.base = base
        self.ndims = reinterpreted_batch_ndims

    @property
    def mean(self) -> jax.Array:
        return self.base.mean

    @property
    def mode(self) -> jax.Array:
        return self.base.mode

    def sample(self, key: jax.Array) -> jax.Array:
        return self.base.sample(key)

    def rsample(self, key: jax.Array) -> jax.Array:
        return self.base.rsample(key) if hasattr(self.base, "rsample") else self.base.sample(key)

    def log_prob(self, value: jax.Array) -> jax.Array:
        return _sum_rightmost(self.base.log_prob(value), self.ndims)

    def entropy(self) -> jax.Array:
        return _sum_rightmost(self.base.entropy(), self.ndims)


class Categorical(Distribution):
    """Integer-valued categorical over the last axis of ``logits``."""

    def __init__(self, logits: Optional[jax.Array] = None, probs: Optional[jax.Array] = None):
        if logits is None and probs is None:
            raise ValueError("either logits or probs must be given")
        if logits is None:
            logits = jnp.log(jnp.clip(probs, 1e-38, None))
        self.logits = logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)

    @property
    def probs(self) -> jax.Array:
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def num_categories(self) -> int:
        return self.logits.shape[-1]

    @property
    def mean(self) -> jax.Array:
        return jnp.sum(self.probs * jnp.arange(self.num_categories), axis=-1)

    @property
    def mode(self) -> jax.Array:
        return jnp.argmax(self.logits, axis=-1)

    def sample(self, key: jax.Array) -> jax.Array:
        return jax.random.categorical(key, self.logits, axis=-1)

    def log_prob(self, value: jax.Array) -> jax.Array:
        value = value.astype(jnp.int32)
        return jnp.take_along_axis(self.logits, value[..., None], axis=-1)[..., 0]

    def entropy(self) -> jax.Array:
        return -jnp.sum(self.probs * self.logits, axis=-1)


class OneHotCategorical(Distribution):
    """One-hot-valued categorical (reference OneHotCategoricalValidateArgs:281)."""

    def __init__(self, logits: Optional[jax.Array] = None, probs: Optional[jax.Array] = None):
        self._cat = Categorical(logits=logits, probs=probs)

    @property
    def logits(self) -> jax.Array:
        return self._cat.logits

    @property
    def probs(self) -> jax.Array:
        return self._cat.probs

    @property
    def mean(self) -> jax.Array:
        return self._cat.probs

    @property
    def mode(self) -> jax.Array:
        return jax.nn.one_hot(self._cat.mode, self._cat.num_categories, dtype=self.logits.dtype)

    def sample(self, key: jax.Array) -> jax.Array:
        idx = self._cat.sample(key)
        return jax.nn.one_hot(idx, self._cat.num_categories, dtype=self.logits.dtype)

    def log_prob(self, value: jax.Array) -> jax.Array:
        _check_last_dim("OneHotCategorical", value, self.logits.shape[-1])
        return jnp.sum(self.logits * value, axis=-1)

    def entropy(self) -> jax.Array:
        return self._cat.entropy()


class OneHotCategoricalStraightThrough(OneHotCategorical):
    """Sampling carries straight-through gradients w.r.t. the probs
    (reference OneHotCategoricalStraightThroughValidateArgs:386) — the discrete-latent
    sampler of Dreamer-V2/V3."""

    def rsample(self, key: jax.Array) -> jax.Array:
        sample = jax.lax.stop_gradient(self.sample(key))
        probs = self.probs
        return sample + probs - jax.lax.stop_gradient(probs)


class TruncatedNormal(Distribution):
    """Normal truncated to [low, high] (reference TruncatedNormal:55-147, used for
    Dreamer-V1/V2 continuous actions)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, low: float = -1.0, high: float = 1.0):
        self.loc = loc
        self.scale = scale
        self.low = low
        self.high = high
        self._alpha = (low - loc) / scale
        self._beta = (high - loc) / scale
        sqrt2 = math.sqrt(2.0)
        self._big_phi_alpha = 0.5 * (1 + jax.scipy.special.erf(self._alpha / sqrt2))
        self._big_phi_beta = 0.5 * (1 + jax.scipy.special.erf(self._beta / sqrt2))
        self._z = jnp.clip(self._big_phi_beta - self._big_phi_alpha, 1e-8, None)

    @property
    def mean(self) -> jax.Array:
        phi_a = jnp.exp(-0.5 * jnp.square(self._alpha)) / math.sqrt(2 * math.pi)
        phi_b = jnp.exp(-0.5 * jnp.square(self._beta)) / math.sqrt(2 * math.pi)
        return self.loc + self.scale * (phi_a - phi_b) / self._z

    @property
    def mode(self) -> jax.Array:
        return jnp.clip(self.loc, self.low, self.high)

    def sample(self, key: jax.Array) -> jax.Array:
        raw = jax.random.truncated_normal(key, self._alpha, self._beta, self.loc.shape)
        return self.loc + self.scale * raw

    def rsample(self, key: jax.Array) -> jax.Array:
        # reparameterized via inverse-cdf with straight-through clipping
        u = jax.random.uniform(key, self.loc.shape, minval=1e-6, maxval=1 - 1e-6)
        p = self._big_phi_alpha + u * (self._big_phi_beta - self._big_phi_alpha)
        raw = jnp.sqrt(2.0) * jax.scipy.special.erfinv(2 * p - 1)
        return jnp.clip(self.loc + self.scale * raw, self.low, self.high)

    def log_prob(self, value: jax.Array) -> jax.Array:
        std_lp = -jnp.square((value - self.loc) / self.scale) / 2 - _HALF_LOG_2PI
        return std_lp - jnp.log(self.scale) - jnp.log(self._z)

    def entropy(self) -> jax.Array:
        # differential entropy of the untruncated normal as an upper bound surrogate
        return 0.5 + _HALF_LOG_2PI + jnp.log(self.scale)


class TanhTransformedNormal(Distribution):
    """Normal squashed through tanh with exact log-prob correction — the SAC policy
    head (the reference computes the correction inline, sheeprl/algos/sac/agent.py)."""

    def __init__(self, loc: jax.Array, scale: jax.Array, eps: float = 1e-6):
        self.base = Normal(loc, scale)
        self._eps = eps

    @property
    def mean(self) -> jax.Array:
        return jnp.tanh(self.base.mean)

    @property
    def mode(self) -> jax.Array:
        return jnp.tanh(self.base.mode)

    def sample_and_log_prob(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = self.base.sample(key)
        y = jnp.tanh(x)
        lp = self.base.log_prob(x) - jnp.log1p(-jnp.square(y) + self._eps)
        return y, lp

    def sample(self, key: jax.Array) -> jax.Array:
        return jnp.tanh(self.base.sample(key))

    def rsample(self, key: jax.Array) -> jax.Array:
        return self.sample(key)

    def log_prob(self, value: jax.Array) -> jax.Array:
        value = jnp.clip(value, -1 + self._eps, 1 - self._eps)
        x = jnp.arctanh(value)
        return self.base.log_prob(x) - jnp.log1p(-jnp.square(value) + self._eps)

    def entropy(self) -> jax.Array:
        return self.base.entropy()


class SymlogDistribution(Distribution):
    """-||pred - symlog(x)||^2 surrogate log-prob (reference distribution.py:152-193)."""

    def __init__(self, mode: jax.Array, dims: int, dist: str = "mse", agg: str = "sum", tol: float = 1e-8):
        self._mode = mode
        self._dims = dims
        self._dist = dist
        self._agg = agg
        self._tol = tol

    @property
    def mode(self) -> jax.Array:
        return symexp(self._mode)

    @property
    def mean(self) -> jax.Array:
        return symexp(self._mode)

    def log_prob(self, value: jax.Array) -> jax.Array:
        assert self._mode.shape == value.shape, (self._mode.shape, value.shape)
        if self._dist == "mse":
            distance = jnp.square(self._mode - symlog(value))
        elif self._dist == "abs":
            distance = jnp.abs(self._mode - symlog(value))
        else:
            raise NotImplementedError(self._dist)
        distance = jnp.where(distance < self._tol, 0.0, distance)
        if self._agg == "mean":
            return -distance.mean(axis=tuple(range(-self._dims, 0)))
        if self._agg == "sum":
            return -_sum_rightmost(distance, self._dims)
        raise NotImplementedError(self._agg)


class MSEDistribution(Distribution):
    """-||pred - x||^2 surrogate log-prob (reference distribution.py:196-221)."""

    def __init__(self, mode: jax.Array, dims: int, agg: str = "sum"):
        self._mode = mode
        self._dims = dims
        self._agg = agg

    @property
    def mode(self) -> jax.Array:
        return self._mode

    @property
    def mean(self) -> jax.Array:
        return self._mode

    def log_prob(self, value: jax.Array) -> jax.Array:
        assert self._mode.shape == value.shape, (self._mode.shape, value.shape)
        distance = jnp.square(self._mode - value)
        if self._agg == "mean":
            return -distance.mean(axis=tuple(range(-self._dims, 0)))
        if self._agg == "sum":
            return -_sum_rightmost(distance, self._dims)
        raise NotImplementedError(self._agg)


class TwoHotEncodingDistribution(Distribution):
    """255-bin symexp-twohot distribution (reference distribution.py:224-278) — the
    reward/value head of Dreamer-V3."""

    def __init__(
        self,
        logits: jax.Array,
        dims: int = 0,
        low: float = -20.0,
        high: float = 20.0,
        transfwd: Callable[[jax.Array], jax.Array] = symlog,
        transbwd: Callable[[jax.Array], jax.Array] = symexp,
    ):
        self.logits = logits
        self.dims = dims
        self.bins = jnp.linspace(low, high, logits.shape[-1], dtype=logits.dtype)
        self.low = low
        self.high = high
        self.transfwd = transfwd
        self.transbwd = transbwd

    @property
    def probs(self) -> jax.Array:
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def mean(self) -> jax.Array:
        agg = jnp.sum(self.probs * self.bins, axis=-1, keepdims=True)
        if self.dims > 1:
            agg = agg.sum(axis=tuple(range(-self.dims, -1)))
        return self.transbwd(agg)

    @property
    def mode(self) -> jax.Array:
        return self.mean

    def log_prob(self, x: jax.Array) -> jax.Array:
        x = self.transfwd(x)
        n_bins = self.bins.shape[-1]
        below = jnp.sum((self.bins <= x).astype(jnp.int32), axis=-1, keepdims=True) - 1
        above = below + 1
        above = jnp.minimum(above, n_bins - 1)
        below = jnp.maximum(below, 0)
        equal = below == above
        dist_to_below = jnp.where(equal, 1, jnp.abs(self.bins[below] - x))
        dist_to_above = jnp.where(equal, 1, jnp.abs(self.bins[above] - x))
        total = dist_to_below + dist_to_above
        weight_below = dist_to_above / total
        weight_above = dist_to_below / total
        target = (
            jax.nn.one_hot(below, n_bins, dtype=self.logits.dtype) * weight_below[..., None]
            + jax.nn.one_hot(above, n_bins, dtype=self.logits.dtype) * weight_above[..., None]
        )[..., 0, :]
        log_pred = self.logits - jax.nn.logsumexp(self.logits, axis=-1, keepdims=True)
        lp = jnp.sum(target * log_pred, axis=-1, keepdims=True)
        return _sum_rightmost(lp, self.dims) if self.dims > 0 else lp[..., 0]


class Bernoulli(Distribution):
    def __init__(self, logits: Optional[jax.Array] = None, probs: Optional[jax.Array] = None):
        if logits is None and probs is None:
            raise ValueError("either logits or probs must be given")
        if logits is None:
            probs = jnp.clip(probs, 1e-7, 1 - 1e-7)
            logits = jnp.log(probs) - jnp.log1p(-probs)
        self.logits = logits

    @property
    def probs(self) -> jax.Array:
        return jax.nn.sigmoid(self.logits)

    @property
    def mean(self) -> jax.Array:
        return self.probs

    @property
    def mode(self) -> jax.Array:
        return (self.probs > 0.5).astype(self.logits.dtype)

    def sample(self, key: jax.Array) -> jax.Array:
        return jax.random.bernoulli(key, self.probs).astype(self.logits.dtype)

    def log_prob(self, value: jax.Array) -> jax.Array:
        return -jnp.logaddexp(0.0, jnp.where(value > 0.5, -self.logits, self.logits))

    def entropy(self) -> jax.Array:
        p = self.probs
        return -(p * jnp.log(jnp.clip(p, 1e-8, None)) + (1 - p) * jnp.log(jnp.clip(1 - p, 1e-8, None)))


class BernoulliSafeMode(Bernoulli):
    """Bernoulli whose mode never NaNs at p=0.5 (reference distribution.py:407-414) —
    the continue head of Dreamer."""
