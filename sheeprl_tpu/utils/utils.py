"""Core math + run utilities, JAX-native.

Re-provides the reference's math toolbox (sheeprl/utils/utils.py) with XLA-friendly
implementations: GAE is a ``lax.scan`` over reversed time instead of a Python loop
(reference: utils.py:63-100), twohot encode/decode use vectorized searchsorted/scatter
(reference: utils.py:156-207), and the replay-ratio governor ``Ratio`` keeps identical
host-side semantics (reference: utils.py:266-319).
"""

from __future__ import annotations

import copy
import os
import warnings
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.config.dotdict import dotdict


# ---------------------------------------------------------------------------------
# symlog / symexp (Dreamer-V3 eq. 10)
# ---------------------------------------------------------------------------------
def symlog(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * (jnp.expm1(jnp.abs(x)))


# ---------------------------------------------------------------------------------
# twohot encoding (Dreamer-V3 eq. 9) — semantics match reference utils.py:156-207
# ---------------------------------------------------------------------------------
def two_hot_encoder(x: jax.Array, support_range: int = 300, num_buckets: Optional[int] = None) -> jax.Array:
    """Encode scalars (..., 1) into twohot vectors (..., num_buckets) over a symmetric
    linear support [-support_range, support_range]."""
    if x.ndim == 0:
        x = x[None]
    if num_buckets is None:
        num_buckets = support_range * 2 + 1
    if num_buckets % 2 == 0:
        raise ValueError("support_size must be odd")
    x = jnp.clip(x, -support_range, support_range)
    buckets = jnp.linspace(-support_range, support_range, num_buckets, dtype=x.dtype)
    bucket_size = (buckets[1] - buckets[0]) if num_buckets > 1 else jnp.asarray(1.0, x.dtype)

    right_idxs = jnp.searchsorted(buckets, x, side="left")
    left_idxs = jnp.clip(right_idxs - 1, 0, num_buckets - 1)
    right_idxs = jnp.clip(right_idxs, 0, num_buckets - 1)

    left_value = jnp.abs(buckets[right_idxs] - x) / bucket_size
    right_value = 1.0 - left_value

    left_oh = jax.nn.one_hot(left_idxs[..., 0], num_buckets, dtype=x.dtype)
    right_oh = jax.nn.one_hot(right_idxs[..., 0], num_buckets, dtype=x.dtype)
    return left_oh * left_value + right_oh * right_value


def two_hot_decoder(t: jax.Array, support_range: int) -> jax.Array:
    num_buckets = t.shape[-1]
    if num_buckets % 2 == 0:
        raise ValueError("support_size must be odd")
    support = jnp.linspace(-support_range, support_range, num_buckets, dtype=t.dtype)
    return jnp.sum(t * support, axis=-1, keepdims=True)


# ---------------------------------------------------------------------------------
# GAE — lax.scan over reversed time (reference python loop: utils.py:92-98)
# ---------------------------------------------------------------------------------
def gae(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    next_value: jax.Array,
    num_steps: int,
    gamma: float,
    gae_lambda: float,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (returns, advantages), shapes like ``rewards`` ([T, B, ...]).

    ``dones[t]`` flags termination *at* step t; the bootstrap value for the last step is
    ``next_value`` masked by ``1 - dones[-1]`` — identical recursion to the reference.
    """
    dtype = rewards.dtype
    not_dones = 1.0 - dones.astype(dtype)
    values = values.astype(dtype)
    next_values = jnp.concatenate([values[1:], next_value[None].astype(dtype)], axis=0)

    def step(carry, inp):
        lastgaelam = carry
        reward, value, next_val, nonterminal = inp
        delta = reward + gamma * next_val * nonterminal - value
        lastgaelam = delta + gamma * gae_lambda * nonterminal * lastgaelam
        return lastgaelam, lastgaelam

    init = jnp.zeros_like(rewards[0])
    _, adv_rev = jax.lax.scan(
        step,
        init,
        (rewards[::-1], values[::-1], next_values[::-1], not_dones[::-1]),
    )
    advantages = adv_rev[::-1]
    returns = advantages + values
    return returns, advantages


# ---------------------------------------------------------------------------------
# lambda returns (Dreamer) — scan form of the reversed loop
# ---------------------------------------------------------------------------------
def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """TD(lambda) returns over an imagined trajectory — exact recursion of the
    reference's ``compute_lambda_values`` (sheeprl/algos/dreamer_v3/utils.py:67-78):
    ``ret[t] = r[t] + c[t] * ((1-lambda) * v[t] + lambda * ret[t+1])`` with carry
    initialized at ``v[T-1]``. Callers pass the inputs already shifted the way the
    reference does (rewards[1:], values[1:], continues[1:] * gamma).

    Return accumulation runs in float32 regardless of the compute precision (the
    same spirit as the reference's GAE-in-float64, ppo.py:350): it is a tiny
    tensor, the recursion compounds rounding over the horizon, and mixed
    bf16/fp32 inputs would otherwise break the scan's carry-type invariant."""
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    continues = continues.astype(jnp.float32)
    interm = rewards + continues * values * (1 - lmbda)

    def step(carry, inp):
        ret = carry
        interm_t, cont_t = inp
        ret = interm_t + cont_t * lmbda * ret
        return ret, ret

    _, lv_rev = jax.lax.scan(step, values[-1], (interm[::-1], continues[::-1]))
    return lv_rev[::-1]


# ---------------------------------------------------------------------------------
# misc numerics
# ---------------------------------------------------------------------------------
def epoch_permutation(
    key: jax.Array,
    num_rows: int,
    world_size: int,
    share_data: bool,
    minibatch_size: Optional[int] = None,
) -> jax.Array:
    """Row-visit order for one optimization epoch over a ``data``-axis-sharded rollout.

    The TPU-native reading of the reference's ``buffer.share_data`` switch
    (sheeprl/algos/ppo/ppo.py:40-50,362-369): with ``share_data`` each rank optimizes a
    shard of the *globally shuffled* rollout (reference: ``fabric.all_gather`` +
    ``DistributedSampler``) — here a global permutation whose gathers XLA turns into
    ICI collectives; without it every device samples only its own rows (reference:
    ``RandomSampler`` on local data) — here a per-shard permutation, so minibatch
    gathers can stay device-local and no collective is needed for the data plane.

    Rows MUST be laid out contiguous per device shard — i.e. the flat axis carries a
    plain leading-axis ``P("data")`` sharding, shard ``s`` owning rows
    ``[s*rows_per_shard, (s+1)*rows_per_shard)``. (PPO flattens its ``(T, E)`` rollout
    env-major — ``swapaxes(0, 1)`` before the reshape — precisely so the env-axis
    sharding becomes this contiguous block layout.)

    When ``minibatch_size`` is given (and divisible by ``world_size`` with
    ``num_rows`` a multiple of it), each consecutive ``minibatch_size`` slice of the
    returned order is arranged as per-shard contiguous blocks
    ``[shard0 rows | shard1 rows | ...]`` — gathering such a minibatch from the
    block-sharded operand leaves each output block on the shard that owns its rows,
    so the take requires no cross-device movement. Otherwise the shards are
    interleaved cyclically (position ``i`` belongs to shard ``i % world_size``),
    which still draws equally from every shard per slice.
    """
    if share_data or world_size == 1 or num_rows % world_size != 0:
        return jax.random.permutation(key, num_rows)
    rows_per_shard = num_rows // world_size
    keys = jax.random.split(key, world_size)
    local = jnp.stack(
        [jax.random.permutation(k, rows_per_shard) for k in keys]
    ) + jnp.arange(world_size)[:, None] * rows_per_shard
    if (
        minibatch_size is not None
        and minibatch_size % world_size == 0
        and num_rows % minibatch_size == 0
    ):
        num_minibatches = num_rows // minibatch_size
        block = minibatch_size // world_size
        return local.reshape(world_size, num_minibatches, block).transpose(1, 0, 2).reshape(-1)
    return local.T.reshape(-1)


@jax.jit
def _pack_leaves(leaves):
    return jnp.concatenate([jnp.asarray(x).reshape(-1) for x in leaves])


def packed_device_get(tree: Any) -> Any:
    """Fetch a device pytree to host numpy with ONE transfer per dtype group.

    ``jax.device_get`` issues one device→host round-trip per leaf; on a remote
    accelerator (e.g. a tunneled TPU) each round-trip costs a full RTT, so a
    ~60-leaf params tree takes ~60 RTTs. Packing all leaves into a single flat
    device array first makes it one RTT per distinct dtype (usually one).
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    out: list = [None] * len(leaves)
    by_dtype: Dict[Any, list] = {}
    for i, x in enumerate(leaves):
        if isinstance(x, np.ndarray) or np.isscalar(x):
            out[i] = np.asarray(x)
        else:
            by_dtype.setdefault(jnp.asarray(x).dtype, []).append(i)
    for idxs in by_dtype.values():
        flat = np.asarray(_pack_leaves([leaves[i] for i in idxs]))
        off = 0
        for i in idxs:
            size = int(np.prod(np.shape(leaves[i])))
            out[i] = flat[off : off + size].reshape(np.shape(leaves[i]))
            off += size
    return jax.tree_util.tree_unflatten(treedef, out)


class ActPlacement:
    """Act/train device-placement split, shared by every per-step-acting algorithm.

    The one-frame act program runs on the host CPU backend — per-step dispatch
    latency to an accelerator dwarfs the forward — while the fused train program
    runs on the accelerator; only the player-visible subtree (``select``) crosses
    back per train call, as one packed transfer. On a CPU fabric everything is the
    identity, so call sites need no branching.
    """

    def __init__(self, fabric, select: Optional[Callable[[Any], Any]] = None) -> None:
        # local_devices: jax.devices() spans ALL processes of a multi-process run,
        # and a non-rank-0 role (a service actor) must pin ITS host device
        self.cpu_device = jax.local_devices(backend="cpu")[0]
        self.on_cpu = fabric.device.platform != "cpu"
        self._select = select or (lambda p: p)

    def view(self, params: Any) -> Any:
        """The player-visible act params: ``select(params)``, landed host-side.

        Note ``select`` narrows the tree on EVERY fabric, CPU included — a test()
        path that reads keys outside the act view would break identically on all
        placements, rather than only when an accelerator is attached."""
        view = self._select(params)
        return packed_device_put(view, self.cpu_device) if self.on_cpu else view

    def place(self, tree: Any) -> Any:
        """Land an arbitrary pytree (PRNG key, frozen exploration params) host-side
        so the act program's dispatch and key chain never touch the accelerator."""
        return packed_device_put(tree, self.cpu_device) if self.on_cpu else tree


def packed_device_put(tree: Any, device: jax.Device) -> Any:
    """Move a pytree onto ``device`` with one bulk transfer off the source device
    (see :func:`packed_device_get`), then cheap local placements onto the target."""
    host = packed_device_get(tree)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, device), host)


def normalize_tensor(x: jax.Array, eps: float = 1e-8, mask: Optional[jax.Array] = None) -> jax.Array:
    if mask is None:
        return (x - x.mean()) / (x.std() + eps)
    n = jnp.maximum(mask.sum(), 1)
    mean = jnp.sum(x * mask) / n
    var = jnp.sum(jnp.square(x - mean) * mask) / n
    return (x - mean) / (jnp.sqrt(var) + eps)


def polynomial_decay(
    current_step: int,
    *,
    initial: float = 1.0,
    final: float = 0.0,
    max_decay_steps: int = 100,
    power: float = 1.0,
) -> float:
    if current_step > max_decay_steps or initial == final:
        return final
    return (initial - final) * ((1 - current_step / max_decay_steps) ** power) + final


class Ratio:
    """Replay-ratio governor: decides how many gradient steps to run per batch of new
    env steps (identical host-side semantics to reference utils.py:266-319)."""

    def __init__(self, ratio: float, pretrain_steps: int = 0):
        if pretrain_steps < 0:
            raise ValueError(f"'pretrain_steps' must be non-negative, got {pretrain_steps}")
        if ratio < 0:
            raise ValueError(f"'ratio' must be non-negative, got {ratio}")
        self._pretrain_steps = pretrain_steps
        self._ratio = ratio
        self._prev: Optional[float] = None

    def __call__(self, step: int) -> int:
        if self._ratio == 0:
            return 0
        if self._prev is None:
            self._prev = step
            repeats = int(step * self._ratio)
            if self._pretrain_steps > 0:
                if step < self._pretrain_steps:
                    warnings.warn(
                        "The number of pretrain steps is greater than the number of current steps. "
                        f"This could lead to a higher ratio than the one specified ({self._ratio}). "
                        "Setting the 'pretrain_steps' equal to the number of current steps."
                    )
                    self._pretrain_steps = step
                repeats = int(self._pretrain_steps * self._ratio)
            return repeats
        repeats = int((step - self._prev) * self._ratio)
        self._prev += repeats / self._ratio
        return repeats

    def state_dict(self) -> Dict[str, Any]:
        return {"_ratio": self._ratio, "_prev": self._prev, "_pretrain_steps": self._pretrain_steps}

    def load_state_dict(self, state: Mapping[str, Any]) -> "Ratio":
        self._ratio = state["_ratio"]
        self._prev = state["_prev"]
        self._pretrain_steps = state["_pretrain_steps"]
        return self


# ---------------------------------------------------------------------------------
# config helpers
# ---------------------------------------------------------------------------------
def print_config(cfg: Mapping[str, Any]) -> None:
    try:
        import yaml
        from rich.syntax import Syntax
        from rich.console import Console

        text = yaml.safe_dump(cfg.as_dict() if isinstance(cfg, dotdict) else dict(cfg), sort_keys=False)
        Console().print(Syntax(text, "yaml", theme="ansi_dark"))
    except Exception:
        import pprint

        pprint.pprint(cfg)


def save_configs(cfg: dotdict, log_dir: str) -> None:
    import yaml

    os.makedirs(log_dir, exist_ok=True)
    with open(os.path.join(log_dir, "config.yaml"), "w") as f:
        yaml.safe_dump(cfg.as_dict(), f, sort_keys=False)


def copy_cfg(cfg: dotdict) -> dotdict:
    return dotdict(copy.deepcopy(cfg.as_dict()))


def foreach_gradient_step(train_step, state, data, train_key, cum_steps=None):
    """Drive a jitted single-gradient-step program over a ``[G, ...]`` replay block
    with a host loop.

    This is the Dreamer-family training-phase harness (the role of the reference's
    per-gradient-step python loop, sheeprl/algos/dreamer_v3/dreamer_v3.py:741-783) —
    but around ONE fused XLA program per step instead of three torch.compile regions.
    A host loop beats an outer ``lax.scan`` over G here for two measured reasons:
    (a) ~3.6x faster steady-state on XLA CPU — scan-carried params/opt-state force
    layout copies and block fusion across the while-loop body; (b) the Ratio governor
    produces varying ``per_rank_gradient_steps``, and a scanned program recompiles for
    every distinct G (~45 s each on the benchmark model) while the single-step
    program compiles once.

    ``train_step`` takes ``(*state, batch, key)`` — or ``(*state, batch, cum, key)``
    when ``cum_steps`` is given — and returns ``(*new_state, metrics)``.
    Returns ``(*final_state, mean_metrics)``.
    """
    G = int(jax.tree_util.tree_leaves(data)[0].shape[0])
    if G == 0:
        raise ValueError("foreach_gradient_step needs a non-empty [G, ...] block (G >= 1)")
    keys = jax.random.split(jnp.asarray(train_key), G)
    cum = None if cum_steps is None else int(cum_steps)
    state = tuple(state)
    all_metrics = []
    for g in range(G):
        batch = jax.tree_util.tree_map(lambda a: a[g], data)
        if cum is None:
            *state, metrics = train_step(*state, batch, keys[g])
        else:
            *state, metrics = train_step(*state, batch, jnp.asarray(cum + g), keys[g])
        all_metrics.append(metrics)
    if len(all_metrics) > 1:
        metrics = jax.tree_util.tree_map(lambda *ms: jnp.stack(ms).mean(), *all_metrics)
    else:
        metrics = all_metrics[0]
    return (*state, metrics)


class BenchWindow:
    """Steady-state wall-clock window for bench.py: starts timing once the policy
    step passes SHEEPRL_BENCH_STEADY_START (set past warmup+compile) and writes
    {steps, seconds} to SHEEPRL_BENCH_STEADY_FILE at the end of the run. Inactive
    (zero overhead beyond two attribute checks per iteration) when the env vars are
    unset. Shared by the Dreamer-family training loops."""

    def __init__(self) -> None:
        self.file = os.environ.get("SHEEPRL_BENCH_STEADY_FILE")
        self.start_step = int(os.environ.get("SHEEPRL_BENCH_STEADY_START", "0"))
        self._t0: Optional[float] = None
        self._step0 = 0

    def maybe_start(self, policy_step: int, sync_tree: Any = None) -> None:
        if self.file and self._t0 is None and policy_step >= self.start_step:
            import time

            if sync_tree is not None:
                jax.block_until_ready(sync_tree)
            self._t0 = time.perf_counter()
            self._step0 = policy_step

    def finish(self, policy_step: int, sync_tree: Any = None) -> None:
        if self.file and self._t0 is not None:
            import json
            import time

            if sync_tree is not None:
                jax.block_until_ready(sync_tree)
            with open(self.file, "w") as f:
                json.dump(
                    {"steps": policy_step - self._step0, "seconds": time.perf_counter() - self._t0},
                    f,
                )
