"""Environment factory — builds thunks that normalize every env to dict observations.

Same pipeline as the reference's ``make_env`` (sheeprl/utils/env.py:26-237): instantiate
``cfg.env.wrapper`` from config, apply action repeat / velocity masking, coerce the
observation space to ``gym.spaces.Dict``, run images through a resize/grayscale/
channel-first pipeline, frame stacking, actions/reward-as-observation, TimeLimit,
episode statistics and optional video capture. Written against gymnasium 1.x (the
reference's PixelObservationWrapper / TransformObservation idioms are 0.x-only, so the
dict coercion and pixel pipeline are dedicated wrappers here).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Callable, Dict, Optional

import gymnasium as gym
import numpy as np

from sheeprl_tpu.config import instantiate
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    FrameStack,
    GrayscaleRenderWrapper,
    InjectedEnvFault,
    MaskVelocityWrapper,
    RewardAsObservationWrapper,
)


class _DictObservation(gym.ObservationWrapper):
    """Coerce a Box observation space into a single-key Dict space."""

    def __init__(self, env: gym.Env, key: str):
        super().__init__(env)
        self._key = key
        self.observation_space = gym.spaces.Dict({key: env.observation_space})

    def observation(self, observation):
        return {self._key: observation}


class _RenderObservation(gym.Wrapper):
    """Add the rendered frame as a pixel observation next to (or instead of) the
    vector state (role of the reference's PixelObservationWrapper usage)."""

    def __init__(self, env: gym.Env, pixel_key: str, state_key: Optional[str] = None):
        super().__init__(env)
        self._pixel_key = pixel_key
        self._state_key = state_key
        frame = self._render_frame(env)
        spaces = {pixel_key: gym.spaces.Box(0, 255, frame.shape, np.uint8)}
        if state_key is not None:
            spaces[state_key] = env.observation_space
        self.observation_space = gym.spaces.Dict(spaces)

    @staticmethod
    def _render_frame(env: gym.Env) -> np.ndarray:
        frame = env.render()
        if frame is None:
            raise RuntimeError(
                "The environment returned no render frame; set render_mode='rgb_array' "
                "to use pixel observations"
            )
        return np.asarray(frame)

    def _convert(self, obs):
        out = {self._pixel_key: self._render_frame(self.env)}
        if self._state_key is not None:
            out[self._state_key] = obs
        return out

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        return self._convert(obs), reward, terminated, truncated, info

    def reset(self, *, seed=None, options=None):
        obs, info = self.env.reset(seed=seed, options=options)
        return self._convert(obs), info


class _PixelPipeline(gym.ObservationWrapper):
    """Resize / grayscale / channel-first pipeline for the cnn keys (the reference's
    ``transform_obs`` closure, sheeprl/utils/env.py:163-196)."""

    def __init__(self, env: gym.Env, cnn_keys, screen_size: int, grayscale: bool):
        super().__init__(env)
        self._cnn_keys = list(cnn_keys)
        self._screen_size = screen_size
        self._grayscale = grayscale
        self.observation_space = gym.spaces.Dict(dict(env.observation_space.spaces.items()))
        for k in self._cnn_keys:
            self.observation_space[k] = gym.spaces.Box(
                0, 255, (1 if grayscale else 3, screen_size, screen_size), np.uint8
            )

    def observation(self, obs):
        import cv2

        for k in self._cnn_keys:
            current = obs[k]
            shape = current.shape
            is_3d = len(shape) == 3
            is_grayscale = not is_3d or shape[0] == 1 or shape[-1] == 1
            channel_first = not is_3d or shape[0] in (1, 3)
            if not is_3d:
                current = np.expand_dims(current, axis=0)
            if channel_first:
                current = np.transpose(current, (1, 2, 0))
            if current.shape[:-1] != (self._screen_size, self._screen_size):
                current = cv2.resize(
                    current, (self._screen_size, self._screen_size), interpolation=cv2.INTER_AREA
                )
            if self._grayscale and not is_grayscale:
                current = cv2.cvtColor(current, cv2.COLOR_RGB2GRAY)
            if current.ndim == 2:
                current = np.expand_dims(current, axis=-1)
                if not self._grayscale:
                    current = np.repeat(current, 3, axis=-1)
            obs[k] = current.transpose(2, 0, 1)
        return obs


def make_env(
    cfg: Dict[str, Any],
    seed: int,
    rank: int,
    run_name: Optional[str] = None,
    prefix: str = "",
    vector_env_idx: int = 0,
) -> Callable[[], gym.Env]:
    """Build a thunk creating a fully-wrapped env with Dict observations."""

    def thunk() -> gym.Env:
        backend = str(cfg.env.get("backend", "host") or "host").lower()
        if backend == "jax":
            # on-device env plane (sheeprl_tpu/envs/jax) behind the same
            # factory: the pure env steps through a host-side gymnasium
            # adapter, so every wrapper below stacks on it unchanged. The
            # adapter only applies the id's default step budget when the
            # config does not install its own TimeLimit further down.
            from sheeprl_tpu.envs.jax import JaxToGymEnv

            env: gym.Env = JaxToGymEnv(
                str(cfg.env.id),
                seed=seed if seed is not None else 0,
                apply_default_time_limit=not (
                    cfg.env.max_episode_steps and cfg.env.max_episode_steps > 0
                ),
            )
        elif backend != "host":
            raise ValueError(f"unknown env.backend {backend!r}; choose host or jax")
        else:
            instantiate_kwargs = {}
            if "seed" in cfg.env.wrapper:
                instantiate_kwargs["seed"] = seed
            if "rank" in cfg.env.wrapper:
                instantiate_kwargs["rank"] = rank + vector_env_idx
            env = instantiate(cfg.env.wrapper, **instantiate_kwargs)

        try:
            env_spec = str(gym.spec(cfg.env.id).entry_point)
        except Exception:
            env_spec = ""

        if cfg.env.action_repeat > 1 and "atari" not in env_spec:
            env = ActionRepeat(env, cfg.env.action_repeat)

        if cfg.env.get("mask_velocities", False):
            env = MaskVelocityWrapper(env)

        cnn_enc = cfg.algo.cnn_keys.encoder
        mlp_enc = cfg.algo.mlp_keys.encoder
        if not (isinstance(mlp_enc, list) and isinstance(cnn_enc, list) and len(cnn_enc + mlp_enc) > 0):
            raise ValueError(
                "`algo.cnn_keys.encoder` and `algo.mlp_keys.encoder` must be non-empty lists of "
                f"strings, got cnn={cnn_enc!r} and mlp={mlp_enc!r}"
            )

        # --- dict observation coercion (reference env.py:100-146)
        obs_space = env.observation_space
        if isinstance(obs_space, gym.spaces.Box) and len(obs_space.shape) < 2:
            # vector-only observation
            if len(cnn_enc) > 0:
                if len(cnn_enc) > 1:
                    warnings.warn(
                        f"Multiple cnn keys specified but only one pixel observation is allowed in "
                        f"{cfg.env.id}; keeping the first: {cnn_enc[0]}"
                    )
                state_key = mlp_enc[0] if len(mlp_enc) > 0 else None
                env = _RenderObservation(env, pixel_key=cnn_enc[0], state_key=state_key)
            else:
                if len(mlp_enc) > 1:
                    warnings.warn(
                        f"Multiple mlp keys specified but only one observation is allowed in "
                        f"{cfg.env.id}; keeping the first: {mlp_enc[0]}"
                    )
                env = _DictObservation(env, mlp_enc[0])
        elif isinstance(obs_space, gym.spaces.Box) and 2 <= len(obs_space.shape) <= 3:
            # pixel-only observation
            if len(cnn_enc) > 1:
                warnings.warn(
                    f"Multiple cnn keys specified but only one pixel observation is allowed in "
                    f"{cfg.env.id}; keeping the first: {cnn_enc[0]}"
                )
            elif len(cnn_enc) == 0:
                raise ValueError(
                    "You have selected a pixel observation but no cnn key has been specified. "
                    "Set at least one cnn key: `algo.cnn_keys.encoder=[your_cnn_key]`"
                )
            env = _DictObservation(env, cnn_enc[0])

        if len(set(env.observation_space.keys()).intersection(set(mlp_enc + cnn_enc))) == 0:
            raise ValueError(
                f"The user-specified keys {mlp_enc + cnn_enc} are not a subset of the environment "
                f"observation keys {list(env.observation_space.keys())}; check your config."
            )

        env_cnn_keys = set(
            k for k in env.observation_space.spaces.keys() if len(env.observation_space[k].shape) in (2, 3)
        )
        cnn_keys = env_cnn_keys.intersection(set(cnn_enc))

        if cnn_keys:
            env = _PixelPipeline(env, cnn_keys, cfg.env.screen_size, cfg.env.grayscale)

        if cnn_keys and cfg.env.frame_stack > 1:
            if cfg.env.frame_stack_dilation <= 0:
                raise ValueError(
                    f"The frame stack dilation argument must be greater than zero, got: {cfg.env.frame_stack_dilation}"
                )
            env = FrameStack(env, cfg.env.frame_stack, cnn_keys, cfg.env.frame_stack_dilation)

        if cfg.env.actions_as_observation.num_stack > 0:
            env = ActionsAsObservationWrapper(env, **cfg.env.actions_as_observation)

        if cfg.env.reward_as_observation:
            env = RewardAsObservationWrapper(env)

        # resilience fault injection: the env_step fault raises from inside step()
        # — wrapped late so RestartOnException (applied by the dreamer loops
        # around make_env's thunk) sees and restarts through it
        fault = (cfg.get("resilience") or {}).get("fault") or {}
        if str(fault.get("kind") or "").lower() == "env_step":
            env = InjectedEnvFault(env)

        env.action_space.seed(seed)
        env.observation_space.seed(seed)
        if cfg.env.max_episode_steps and cfg.env.max_episode_steps > 0:
            env = gym.wrappers.TimeLimit(env, max_episode_steps=cfg.env.max_episode_steps)
        env = gym.wrappers.RecordEpisodeStatistics(env)
        if (
            cfg.env.capture_video
            and backend != "jax"  # the adapter has no render frames to record
            and rank == 0
            and vector_env_idx == 0
            and run_name is not None
        ):
            if cfg.env.grayscale:
                env = GrayscaleRenderWrapper(env)
            try:
                env = gym.wrappers.RecordVideo(
                    env,
                    os.path.join(run_name, prefix + "_videos" if prefix else "videos"),
                    disable_logger=True,
                )
            except Exception as e:  # video capture is best-effort
                warnings.warn(f"Could not enable video capture: {e}")
        return env

    return thunk


def get_dummy_env(id: str, **kwargs: Any) -> gym.Env:
    """Build a fake env by id (reference env.py:240-255)."""
    if "continuous" in id:
        from sheeprl_tpu.envs.dummy import ContinuousDummyEnv

        return ContinuousDummyEnv(**kwargs)
    if "multidiscrete" in id:
        from sheeprl_tpu.envs.dummy import MultiDiscreteDummyEnv

        return MultiDiscreteDummyEnv(**kwargs)
    if "discrete" in id:
        from sheeprl_tpu.envs.dummy import DiscreteDummyEnv

        return DiscreteDummyEnv(**kwargs)
    raise ValueError(f"Unknown dummy env id: {id}")
