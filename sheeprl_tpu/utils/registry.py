"""Algorithm / evaluation registries.

Same contract as the reference registry (sheeprl/utils/registry.py:11-115): decorators
record (module, entrypoint, decoupled) so the CLI can import and launch by name; the
evaluation registry is validated against the algorithm registry.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

# algo name -> {"module": str, "entrypoint": str, "decoupled": bool}
algorithm_registry: Dict[str, List[Dict[str, Any]]] = {}
# algo name -> {"module": str, "entrypoint": str}
evaluation_registry: Dict[str, List[Dict[str, Any]]] = {}
# algo name -> {"module": str, "entrypoint": str} — get_serve_policy extractors
# (sheeprl_tpu/serve): build a batched, slot-steppable policy from a checkpoint
serve_registry: Dict[str, List[Dict[str, Any]]] = {}


def _register_algorithm(fn: Callable, decoupled: bool = False) -> Callable:
    module = fn.__module__
    algo_name = module.split(".")[-1]
    entrypoint = fn.__name__
    registrations = algorithm_registry.setdefault(algo_name, [])
    if any(r["entrypoint"] == entrypoint and r["module"] == module for r in registrations):
        raise ValueError(f"algorithm {algo_name} already registered from {module}.{entrypoint}")
    registrations.append({"module": module, "entrypoint": entrypoint, "decoupled": decoupled})
    return fn


def _register_evaluation(fn: Callable, algorithms: Sequence[str]) -> Callable:
    module = fn.__module__
    entrypoint = fn.__name__
    if isinstance(algorithms, str):
        algorithms = [algorithms]
    for algo in algorithms:
        registrations = evaluation_registry.setdefault(algo, [])
        registrations.append({"module": module, "entrypoint": entrypoint, "name": algo})
    return fn


def register_algorithm(decoupled: bool = False) -> Callable:
    def wrap(fn: Callable) -> Callable:
        return _register_algorithm(fn, decoupled=decoupled)

    return wrap


def register_evaluation(algorithms: Sequence[str]) -> Callable:
    def wrap(fn: Callable) -> Callable:
        return _register_evaluation(fn, algorithms=algorithms)

    return wrap


def register_serve_policy(algorithms: Sequence[str]) -> Callable:
    """Register a per-family ``get_serve_policy(fabric, cfg, state)`` extractor
    (lives next to the family's ``evaluate`` registration): returns the
    :class:`sheeprl_tpu.serve.ServePolicy` the batching inference server steps."""

    def wrap(fn: Callable) -> Callable:
        module = fn.__module__
        entrypoint = fn.__name__
        algos = [algorithms] if isinstance(algorithms, str) else list(algorithms)
        for algo in algos:
            serve_registry.setdefault(algo, []).append(
                {"module": module, "entrypoint": entrypoint, "name": algo}
            )
        return fn

    return wrap


def get_serve(name: str) -> Optional[Dict[str, Any]]:
    regs = serve_registry.get(name)
    return regs[0] if regs else None


def get_algorithm(name: str) -> Optional[Dict[str, Any]]:
    regs = algorithm_registry.get(name)
    return regs[0] if regs else None


def get_evaluation(name: str) -> Optional[Dict[str, Any]]:
    regs = evaluation_registry.get(name)
    return regs[0] if regs else None
