"""Named-span timing — drives the ``Time/sps_*`` throughput metrics.

Same contract as the reference's timer (sheeprl/utils/timer.py:16-84): a context
manager/decorator with a class-level registry of named accumulating timers; reduced at
log time into `sps_train` / `sps_env_interaction` (the BASELINE north-star metrics,
logged e.g. at sheeprl/algos/ppo/ppo.py:393-408).
"""

from __future__ import annotations

import time
from contextlib import ContextDecorator
from typing import Any, ClassVar, Dict, Optional


class timer(ContextDecorator):
    disabled: ClassVar[bool] = False
    timers: ClassVar[Dict[str, "timer"]] = {}

    def __new__(cls, name: str, **kwargs: Any) -> "timer":
        if name not in cls.timers:
            inst = super().__new__(cls)
            inst._init(name)
            cls.timers[name] = inst
        return cls.timers[name]

    def _init(self, name: str) -> None:
        self.name = name
        self._total = 0.0
        self._count = 0
        self._start: Optional[float] = None
        # reset generation, bumped by reset(): lets non-destructive readers (the
        # telemetry window accounting) distinguish "total shrank because of a
        # reset" from "total grew past my last sample" exactly, not heuristically
        self._resets = 0

    def __init__(self, name: str, **kwargs: Any) -> None:
        # __new__ handles registry; nothing to do (kwargs accepted for reference parity)
        pass

    def __enter__(self) -> "timer":
        if not timer.disabled:
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> bool:
        if not timer.disabled and self._start is not None:
            self._total += time.perf_counter() - self._start
            self._count += 1
            self._start = None
        return False

    def add(self, seconds: float) -> None:
        """Account an externally measured span. The Anakin loops measure ONE
        fused rollout+train program call and split its wall time across two
        phase timers by a measured rollout-only share — a context manager
        cannot express that, so they add the shares directly."""
        if not timer.disabled and seconds > 0:
            self._total += seconds
            self._count += 1

    def compute(self) -> float:
        return self._total

    def reset(self) -> None:
        """Zero the accumulated totals. An in-flight span (entered but not yet
        exited — e.g. a log boundary landing inside ``with timer(...)``) keeps
        its ``_start``, so ``__exit__`` still accounts it into the new window
        instead of silently dropping the whole span."""
        self._total = 0.0
        self._count = 0
        self._resets += 1

    @classmethod
    def to_dict(cls, reset: bool = True) -> Dict[str, float]:
        out = {name: t.compute() for name, t in cls.timers.items() if t._count > 0}
        if reset:
            for t in cls.timers.values():
                t.reset()
        return out
