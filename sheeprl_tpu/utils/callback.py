"""Checkpoint callback (role of sheeprl/utils/callback.py:14-153).

Hooks are invoked through ``fabric.call`` from the training loops. Replay-buffer state
is included when ``buffer.checkpoint`` is set; before writing, the last inserted row of
each buffer is flagged truncated (and restored afterwards) so a resumed buffer never
straddles a live episode — the reference's ``_ckpt_rb`` protocol
(sheeprl/utils/callback.py:91-146).
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List, Optional, Sequence, Union


class CheckpointCallback:
    def __init__(self, keep_last: Optional[int] = None, **_: Any) -> None:
        self.keep_last = keep_last

    def on_checkpoint_coupled(
        self,
        fabric,
        ckpt_path: str,
        state: Dict[str, Any],
        replay_buffer=None,
    ) -> None:
        from sheeprl_tpu.resilience.distributed import checkpoint_manifest
        from sheeprl_tpu.resilience.watchdog import watchdogs_paused

        # the write blocks the loop for as long as the state is big (a large
        # synchronous orbax save can exceed any sane stall timeout) — that is
        # progress, not a hang, so the progress watchdog must not trip on it.
        # checkpoint_manifest (multi-process only) brackets the save with the
        # consistency manifest: begun incomplete before the write, committed
        # only after every mesh rank finished — a crash anywhere inside leaves
        # a set discovery refuses to resolve.
        with watchdogs_paused(), checkpoint_manifest(fabric, ckpt_path):
            if replay_buffer is not None:
                true_dones = self._ckpt_rb(replay_buffer)
                state["rb"] = replay_buffer
            fabric.save(ckpt_path, state)
            if replay_buffer is not None:
                self._experiment_consistent_rb(replay_buffer, true_dones)
                state.pop("rb", None)
            if getattr(fabric, "is_group_zero", fabric.is_global_zero):
                self._delete_old_checkpoints(os.path.dirname(ckpt_path), live=ckpt_path)

    def on_checkpoint_player(self, fabric, ckpt_path: str, state: Dict[str, Any], replay_buffer=None) -> None:
        # decoupled topology: the player holds the buffer, the trainer sent the weights
        self.on_checkpoint_coupled(fabric, ckpt_path, state, replay_buffer)

    def on_checkpoint_trainer(self, fabric, player_channel, state: Dict[str, Any], ckpt_path: str) -> None:
        player_channel.put(("checkpoint", ckpt_path, state))

    # -- truncated-flag protocol ---------------------------------------------------

    def _ckpt_rb(self, rb) -> Union[List, Any]:
        """Mark the most recently written row as truncated; returns the saved flags so
        they can be restored after the write."""
        from sheeprl_tpu.data.buffers import (
            EnvIndependentReplayBuffer,
            EpisodeBuffer,
            ReplayBuffer,
        )

        if isinstance(rb, ReplayBuffer):
            if "dones" not in rb.buffer and "terminated" in rb.buffer:
                state = (rb["terminated"][(rb._pos - 1) % rb.buffer_size, :].copy(),
                         rb["truncated"][(rb._pos - 1) % rb.buffer_size, :].copy())
                rb["terminated"][(rb._pos - 1) % rb.buffer_size, :] = True
                rb["truncated"][(rb._pos - 1) % rb.buffer_size, :] = True
                return state
            state = rb["dones"][(rb._pos - 1) % rb.buffer_size, :].copy()
            rb["dones"][(rb._pos - 1) % rb.buffer_size, :] = True
            return state
        if isinstance(rb, EnvIndependentReplayBuffer):
            return [self._ckpt_rb(b) for b in rb.buffer]
        if isinstance(rb, EpisodeBuffer):
            return None
        return None

    def _experiment_consistent_rb(self, rb, true_dones) -> None:
        from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, ReplayBuffer

        if isinstance(rb, ReplayBuffer):
            if isinstance(true_dones, tuple):
                rb["terminated"][(rb._pos - 1) % rb.buffer_size, :] = true_dones[0]
                rb["truncated"][(rb._pos - 1) % rb.buffer_size, :] = true_dones[1]
            elif true_dones is not None:
                rb["dones"][(rb._pos - 1) % rb.buffer_size, :] = true_dones
        elif isinstance(rb, EnvIndependentReplayBuffer):
            for b, flags in zip(rb.buffer, true_dones):
                self._experiment_consistent_rb(b, flags)

    def _delete_old_checkpoints(self, ckpt_folder: str, live: Optional[str] = None) -> None:
        if not self.keep_last:
            return
        # ``live`` is the checkpoint just written. An async sharded save commits
        # its directory via a background tmp-dir rename; until it lands, the live
        # sidecar has no directory next to it and would be swept as an orphan —
        # corrupting the checkpoint. Excluding the live path (instead of blocking
        # on the in-flight write) keeps async saves actually asynchronous.
        live = os.path.abspath(live) if live else None
        ckpts = sorted(glob.glob(os.path.join(ckpt_folder, "*.ckpt")), key=os.path.getmtime)
        visible = [c for c in ckpts if os.path.abspath(c) != live]
        # the live checkpoint occupies one keep_last slot whether or not its async
        # commit has landed yet (i.e. whether or not the glob saw it)
        budget = self.keep_last - (1 if live else 0)
        for stale in visible[: max(0, len(visible) - max(0, budget))]:
            try:
                if os.path.isdir(stale):  # sharded (orbax) checkpoint directory
                    import shutil

                    shutil.rmtree(stale, ignore_errors=True)
                    if os.path.exists(stale + ".extras.pkl"):
                        os.remove(stale + ".extras.pkl")
                else:
                    os.remove(stale)
                    if os.path.exists(stale + ".sha256"):
                        os.remove(stale + ".sha256")
            except OSError:
                pass
        # orphan integrity sidecars whose pickle checkpoint was swept above
        for sidecar in glob.glob(os.path.join(ckpt_folder, "*.ckpt.sha256")):
            if not os.path.exists(sidecar[: -len(".sha256")]):
                try:
                    os.remove(sidecar)
                except OSError:
                    pass
        # orphan sidecars from a crash between sidecar write and orbax commit
        for sidecar in glob.glob(os.path.join(ckpt_folder, "*.ckpt.extras.pkl")):
            if live is not None and os.path.abspath(sidecar) == live + ".extras.pkl":
                continue  # in-flight async write: directory lands at commit time
            if not os.path.isdir(sidecar[: -len(".extras.pkl")]):
                try:
                    os.remove(sidecar)
                except OSError:
                    pass
        # consistency manifests whose checkpoint set was swept above (multi-
        # process runs; see resilience/distributed.py): a manifest with no
        # remaining ckpt_* artifact for its step is dead weight
        from sheeprl_tpu.resilience.discovery import manifest_path

        remaining = {
            manifest_path(c) for c in glob.glob(os.path.join(ckpt_folder, "*.ckpt"))
        }
        # a displaced `<path>.ckpt.old` set (mid-displacement crash window,
        # see discovery.py) is still resolvable: its manifest must survive too
        remaining |= {
            manifest_path(c[: -len(".old")])
            for c in glob.glob(os.path.join(ckpt_folder, "*.ckpt.old"))
        }
        for manifest in glob.glob(os.path.join(ckpt_folder, "ckpt_*.manifest.json")):
            if manifest not in remaining:
                try:
                    os.remove(manifest)
                except OSError:
                    pass
