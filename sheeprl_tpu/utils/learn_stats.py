"""Device-side training-health statistics for the fused train programs.

Every fused train program (the 9 ``make_train_phase`` factories, the Anakin
fused rollout+train step, the ppo/a2c/ppo_recurrent optimization phases) grows
a ``Learn/*`` scalar block computed INSIDE the jitted program — gradient norms
pre/post clip, clip fraction, update-to-param ratios, param/optimizer-moment
norms, policy entropy, value statistics, TD-error quantiles, and the dreamer
family's KL posterior/prior balance. The helpers here are pure ``jnp`` so the
no-host-callback contract of every registered program survives unchanged
(``sheeprl.py lint --aot`` asserts it): nothing in this module may sync,
print, or touch the host.

The stats ride the programs' outputs as fresh (never donated) scalar buffers;
the loops hand the device dict to ``RunTelemetry.observe_learn`` which keeps a
bounded reservoir of REFERENCES and fetches them in one ``jax.device_get`` at
window cadence — the Podracer rule: learner-side statistics are computed on
device, the host only pulls a handful of scalars per telemetry window.

Key grammar (consumed by ``obs/telemetry.py``, ``obs/diagnose.py``,
``obs/compare.py``): every key starts with ``Learn/``; per-module-group stats
append ``/<group>`` (``Learn/grad_norm/actor``), run-level stats are bare
(``Learn/entropy``). ``obs/telemetry.py`` strips the ``Learn/`` prefix when it
builds the window event's ``learning.stats`` block.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "LEARN_PREFIX",
    "enabled",
    "global_norm",
    "moment_norm",
    "group_stats",
    "value_stats",
    "td_quantiles",
    "entropy_stats",
    "kl_stats",
    "reduce_stacked",
    "learn_keys",
]

LEARN_PREFIX = "Learn/"

_EPS = 1e-12


def enabled(cfg: Any) -> bool:
    """Whether the train-phase factories should COMPILE the Learn/* stats into
    the fused program. Gated on the telemetry config (``metric.telemetry.enabled``
    + ``metric.telemetry.learning``): with telemetry off — the default — the
    programs stay byte-identical to the pre-learning-plane lowering and pay
    zero extra compute (the norms/quantiles are a measurable share of a SMALL
    model's train step on CPU; at accelerator scale they are noise). The
    factories return an empty stats dict on the off path, so callers never
    branch on arity."""
    try:
        tcfg = cfg.metric.get("telemetry") or {}
    except (AttributeError, TypeError):
        return False
    return bool(tcfg.get("enabled", False)) and bool(tcfg.get("learning", True))


def _inexact_leaves(tree: Any) -> list:
    """Float leaves only: optimizer states carry integer step counters whose
    norm is meaningless (and whose dtype would upcast the reduction)."""
    return [
        leaf
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact)
    ]


def maybe(on: bool, build) -> Dict[str, jnp.ndarray]:
    """``build()`` when the learning plane is compiled in, else the empty stats
    dict — the one-line guard every factory wraps its Learn/* block in (``on``
    is a Python bool at trace time, so the off path traces nothing)."""
    return build() if on else {}


def global_norm(tree: Any) -> jnp.ndarray:
    """L2 norm over every float leaf of a pytree (optax.global_norm without the
    integer-leaf hazard)."""
    leaves = _inexact_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves))


def moment_norm(opt_state: Any) -> jnp.ndarray:
    """Global norm of an optimizer state's float leaves (adam mu/nu moments;
    chained transforms contribute whatever float state they carry). A coarse
    but optimizer-agnostic divergence signal: the moments integrate gradient
    history, so a drift here shows up before the params move."""
    return global_norm(opt_state)


def group_stats(
    group: str,
    *,
    grads: Any = None,
    updates: Any = None,
    params: Any = None,
    opt_state: Any = None,
    clip: Optional[float] = None,
) -> Dict[str, jnp.ndarray]:
    """The per-module-group block: grad norm pre/post clip + clip fraction,
    update-to-param ratio, param and optimizer-moment norms. Pass whatever the
    call site has — absent inputs contribute no keys. ``clip`` is the static
    clip_by_global_norm threshold from the config (the post-clip norm is then
    ``min(pre, clip)`` analytically — no second pass over the gradients)."""
    out: Dict[str, jnp.ndarray] = {}
    if grads is not None:
        g = global_norm(grads)
        out[f"{LEARN_PREFIX}grad_norm/{group}"] = g
        if clip is not None and clip > 0:
            out[f"{LEARN_PREFIX}grad_norm_post/{group}"] = jnp.minimum(g, jnp.float32(clip))
            out[f"{LEARN_PREFIX}clip_fraction/{group}"] = (g > clip).astype(jnp.float32)
    if params is not None:
        p = global_norm(params)
        out[f"{LEARN_PREFIX}param_norm/{group}"] = p
        if updates is not None:
            out[f"{LEARN_PREFIX}update_ratio/{group}"] = global_norm(updates) / jnp.maximum(p, _EPS)
    if opt_state is not None:
        out[f"{LEARN_PREFIX}opt_moment_norm/{group}"] = moment_norm(opt_state)
    return out


def value_stats(values: jnp.ndarray, prefix: str = "value") -> Dict[str, jnp.ndarray]:
    """Mean/std/min/max of a value (or Q) estimate batch."""
    v = jnp.asarray(values).astype(jnp.float32)
    return {
        f"{LEARN_PREFIX}{prefix}_mean": v.mean(),
        f"{LEARN_PREFIX}{prefix}_std": v.std(),
        f"{LEARN_PREFIX}{prefix}_min": v.min(),
        f"{LEARN_PREFIX}{prefix}_max": v.max(),
    }


def td_quantiles(td_error: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """p10/p50/p90 of a TD-error (or advantage) batch — the distribution shape
    is the signal (a fat upper tail reads as optimistic bootstrapping, a drift
    of the median as value bias)."""
    td = jnp.asarray(td_error).astype(jnp.float32).reshape(-1)
    q = jnp.quantile(td, jnp.asarray([0.1, 0.5, 0.9], jnp.float32))
    return {
        f"{LEARN_PREFIX}td_error_p10": q[0],
        f"{LEARN_PREFIX}td_error_p50": q[1],
        f"{LEARN_PREFIX}td_error_p90": q[2],
    }


def entropy_stats(entropy: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Mean policy entropy (continuous policies report differential entropy,
    which is legitimately negative — the collapse detector works on deltas,
    not signs)."""
    return {f"{LEARN_PREFIX}entropy": jnp.asarray(entropy).astype(jnp.float32).mean()}


def kl_stats(
    kl: jnp.ndarray,
    post_entropy: Optional[jnp.ndarray] = None,
    prior_entropy: Optional[jnp.ndarray] = None,
) -> Dict[str, jnp.ndarray]:
    """Dreamer-family latent-dynamics health: the (regularized) posterior/prior
    KL plus the posterior/prior entropy balance — ``post / (post + prior)``
    drifting toward 0 reads as posterior collapse (the representation stops
    carrying information), toward 1 as a prior that never learned the
    dynamics."""
    out = {f"{LEARN_PREFIX}kl": jnp.asarray(kl).astype(jnp.float32).mean()}
    if post_entropy is not None and prior_entropy is not None:
        post = jnp.asarray(post_entropy).astype(jnp.float32).mean()
        prior = jnp.asarray(prior_entropy).astype(jnp.float32).mean()
        out[f"{LEARN_PREFIX}post_entropy"] = post
        out[f"{LEARN_PREFIX}prior_entropy"] = prior
        out[f"{LEARN_PREFIX}kl_balance"] = post / jnp.maximum(post + prior, _EPS)
    return out


def reduce_stacked(stats: Mapping[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Reduce a ``lax.scan``-stacked stats dict (leading axes = gradient steps)
    to scalars: mean for every key, plus a ``grad_norm_max/<group>`` companion
    for each pre-clip grad norm (a one-step spike inside a fused multi-step
    round must not be averaged away — it is exactly what the grad-explosion
    detector hunts)."""
    out: Dict[str, jnp.ndarray] = {}
    for key, value in stats.items():
        v = jnp.asarray(value)
        out[key] = v.mean()
        if key.startswith(f"{LEARN_PREFIX}grad_norm/"):
            group = key[len(f"{LEARN_PREFIX}grad_norm/") :]
            out[f"{LEARN_PREFIX}grad_norm_max/{group}"] = v.max()
    return out


def learn_keys(stats: Mapping[str, Any]) -> Dict[str, Any]:
    """The ``Learn/``-prefixed subset of a metrics mapping (the dreamer family
    rides its learn stats on the existing metrics dict; everything else passes
    a pure learn dict). Pure key filtering — never syncs device values."""
    if not isinstance(stats, Mapping):
        return {}
    return {k: v for k, v in stats.items() if isinstance(k, str) and k.startswith(LEARN_PREFIX)}
