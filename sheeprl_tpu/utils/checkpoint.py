"""Checkpoint serialization.

The reference saves a consolidated state dict per checkpoint via ``fabric.save``
(sheeprl/utils/callback.py:31-57). Here a checkpoint is a single file: every jax array
in the state pytree is pulled to host numpy and the whole tree is pickled (optax states,
numpy replay buffers, counters and plain objects all serialize uniformly). Orbax-style
sharded async checkpointing can layer on top for XL models; the file format is an
implementation detail behind save/load.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

# Integrity sidecar: pickle checkpoints get a `<path>.sha256` next to them so
# discovery (resilience/discovery.py) can tell a torn/corrupted file from a
# valid one BEFORE anything unpickles it — hot reload (serve/reload.py) and
# `resume_from=latest` both lean on it. The sidecar is advisory: a checkpoint
# without one validates by the original heuristics (old runs keep resolving).
SHA_SIDECAR_SUFFIX = ".sha256"


# digest cache keyed by (mtime_ns, size): the reload thread re-validates the
# same candidate every poll — hashing a multi-GB checkpoint once is fine,
# every 2 seconds is not. A rewrite changes mtime/size and invalidates.
_sha_cache: Dict[str, tuple] = {}


def sha256_file(path: str) -> str:
    path = os.path.abspath(path)
    st = os.stat(path)
    key = (st.st_mtime_ns, st.st_size)
    cached = _sha_cache.get(path)
    if cached is not None and cached[0] == key:
        return cached[1]
    digest = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            digest.update(chunk)
    value = digest.hexdigest()
    _sha_cache[path] = (key, value)
    return value


def write_sha_sidecar(path: str) -> None:
    """Write ``<path>.sha256`` (atomically) for an already-committed pickle
    checkpoint. Ordering: any STALE sidecar is removed before the checkpoint
    commit (see ``save_checkpoint``), so the crash windows degrade to
    "no sidecar" — never to a mismatching one vetoing a good checkpoint."""
    sidecar = path + SHA_SIDECAR_SUFFIX
    tmp = sidecar + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(sha256_file(path) + "\n")
    os.replace(tmp, sidecar)


def verify_sha_sidecar(path: str) -> Optional[bool]:
    """True/False when ``<path>.sha256`` exists and the digest matches/differs;
    None when there is no sidecar to judge by (advisory contract)."""
    sidecar = path + SHA_SIDECAR_SUFFIX
    if not os.path.isfile(sidecar):
        return None
    try:
        with open(sidecar) as fh:
            expected = fh.read().strip().split()[0]
        return sha256_file(path) == expected
    except (OSError, IndexError):
        return False

# Fault-injection hook (resilience/faults.py): called at the exact points where a
# process kill would leave the crash-window on-disk states the loaders/discovery
# must recover from — after the pickle tmp write but before its commit rename,
# and after the sharded sidecar commit but before the orbax directory commit.
_fault_hook: Optional[Callable[[str, str], None]] = None


def _maybe_fault(stage: str, path: str) -> None:
    if _fault_hook is not None:
        _fault_hook(stage, path)


def _to_host(tree: Any) -> Any:
    def leaf(x: Any) -> Any:
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(leaf, tree)


def save_checkpoint(path: str, state: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    host_state = _to_host(state)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
    _maybe_fault("pickle_commit", path)
    # a stale sidecar (from the checkpoint being overwritten in place) must
    # never outlive its file: drop it BEFORE the commit rename, so a crash in
    # either window leaves "checkpoint without sidecar" (valid by heuristics),
    # never "checkpoint with a mismatching sidecar" (vetoed)
    try:
        os.remove(path + SHA_SIDECAR_SUFFIX)
    except OSError:
        pass
    os.replace(tmp, path)
    try:
        write_sha_sidecar(path)
    except OSError:
        pass  # advisory: an unwritable sidecar must not fail the save


def load_checkpoint(path: str) -> Dict[str, Any]:
    if os.path.isdir(path):  # orbax-backed checkpoint directory (sharded backend)
        return load_checkpoint_sharded(path)
    if not os.path.exists(path) and os.path.isdir(path + ".old"):
        return load_checkpoint_sharded(path)  # falls back to the .old sibling
    with open(path, "rb") as f:
        return pickle.load(f)


# ---------------------------------------------------------------------------------
# Orbax-backed sharded/async checkpointing (the XL/pod-scale option; reference
# semantics stay those of sheeprl/utils/callback.py:31-57 — same state dict, same
# truncated-flag protocol — only the serialization changes). A checkpoint becomes a
# DIRECTORY: every array leaf of the state pytree goes through orbax (sharded,
# optionally async via orbax's background thread), while object leaves the array
# path cannot express (replay buffers, plain python values) plus the tree skeleton
# ride a pickle sidecar. ``load_checkpoint`` auto-detects the format, so
# ``checkpoint.resume_from`` works across both backends.
# ---------------------------------------------------------------------------------

_ARRAY_TYPES = (np.ndarray, jax.Array, np.integer, np.floating, np.bool_)
_async_checkpointer = None
_displaced: list = []  # previous checkpoints moved aside by an in-place overwrite


def _gc_displaced() -> None:
    import shutil

    while _displaced:
        stale = _displaced.pop()
        if os.path.isdir(stale):
            shutil.rmtree(stale, ignore_errors=True)
        elif os.path.exists(stale):
            try:
                os.remove(stale)
            except OSError:
                pass


def _partition_state(state: Any):
    """Flatten ``state`` and split its leaves into orbax-storable arrays and
    pickled objects, keeping a per-leaf spec so load can interleave them back."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays, objects, spec = [], [], []
    for leaf in leaves:
        if isinstance(leaf, _ARRAY_TYPES):
            arrays.append(np.asarray(leaf))
            spec.append("a")
        else:
            # includes python scalars: riding the pickle side keeps their type, so
            # counters stay ints after resume
            objects.append(leaf)
            spec.append("o")
    # sentinel strings (not None: None is an EMPTY SUBTREE to jax, which would drop
    # the leaf from the skeleton's structure and break the load-time unflatten)
    skeleton = jax.tree_util.tree_unflatten(treedef, ["__leaf__"] * len(leaves))
    return arrays, objects, spec, skeleton


def save_checkpoint_sharded(path: str, state: Dict[str, Any], async_save: bool = False) -> None:
    """Write ``state`` as an orbax checkpoint directory at ``path``. Async mode
    hands the array write to orbax's background thread (the previous async write is
    awaited first so at most one is in flight)."""
    import orbax.checkpoint as ocp

    global _async_checkpointer

    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    arrays, objects, spec, skeleton = _partition_state(state)

    if async_save:
        if _async_checkpointer is None:
            _async_checkpointer = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        _async_checkpointer.wait_until_finished()
        _gc_displaced()  # the previous write (whose displaced .old we kept) has landed
        checkpointer = _async_checkpointer
    else:
        if _async_checkpointer is not None:
            # A mixed async-then-sync sequence to the same path must not race the
            # background orbax commit rename; waiting is a no-op when idle.
            _async_checkpointer.wait_until_finished()
            _gc_displaced()
        checkpointer = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    if os.path.exists(path):
        # Overwriting a path in place must be crash-safe: displace the previous
        # checkpoint atomically (rename, not delete) so a crash mid-write still
        # leaves the old state on disk as <path>.old; it is GC'd only after the
        # new write has committed (sync: below; async: at the next wait).
        import shutil

        old = path + ".old"
        shutil.rmtree(old, ignore_errors=True)
        if os.path.exists(old + ".extras.pkl"):
            os.remove(old + ".extras.pkl")
        if os.path.exists(path + ".extras.pkl"):
            os.replace(path + ".extras.pkl", old + ".extras.pkl")
            _displaced.append(old + ".extras.pkl")
        os.replace(path, old)
        _displaced.append(old)
    # Crash-atomic ordering: the sidecar lands BEFORE the orbax commit. Orbax itself
    # writes to a tmp dir and renames on finalize, and load auto-detection keys on
    # the DIRECTORY — so a crash mid-write leaves at worst an orphan sidecar (GC'd
    # by CheckpointCallback), never a directory without its sidecar.
    sidecar = {"skeleton": skeleton, "spec": spec, "objects": objects}
    tmp = path + ".extras.pkl.tmp"
    with open(tmp, "wb") as f:
        pickle.dump(sidecar, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path + ".extras.pkl")
    _maybe_fault("sharded_commit", path)
    checkpointer.save(path, {"leaves": arrays})
    if not async_save:
        _gc_displaced()


def wait_for_checkpoint() -> None:
    """Block until any in-flight async checkpoint write has landed."""
    if _async_checkpointer is not None:
        _async_checkpointer.wait_until_finished()
    _gc_displaced()


def load_checkpoint_sharded(path: str) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if not os.path.isdir(path) and os.path.isdir(path + ".old"):
        # In-place overwrite displaces the live checkpoint to <path>.old before the
        # new write commits; a crash in that window leaves only the .old sibling.
        path = path + ".old"
    checkpointer = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    restored = checkpointer.restore(path)
    arrays = list(restored["leaves"])
    sidecar_path = path + ".extras.pkl"
    if not os.path.exists(sidecar_path) and os.path.exists(path + ".old.extras.pkl"):
        # Crash window mid-displacement: the sidecar was already renamed to
        # <path>.old.extras.pkl but the directory rename never happened, so the
        # dir still at <path> pairs with the .old sidecar.
        sidecar_path = path + ".old.extras.pkl"
    with open(sidecar_path, "rb") as f:
        sidecar = pickle.load(f)
    treedef = jax.tree_util.tree_structure(sidecar["skeleton"])
    arrays_iter, objects_iter = iter(arrays), iter(sidecar["objects"])
    leaves = [next(arrays_iter) if s == "a" else next(objects_iter) for s in sidecar["spec"]]
    return jax.tree_util.tree_unflatten(treedef, leaves)
