"""Checkpoint serialization.

The reference saves a consolidated state dict per checkpoint via ``fabric.save``
(sheeprl/utils/callback.py:31-57). Here a checkpoint is a single file: every jax array
in the state pytree is pulled to host numpy and the whole tree is pickled (optax states,
numpy replay buffers, counters and plain objects all serialize uniformly). Orbax-style
sharded async checkpointing can layer on top for XL models; the file format is an
implementation detail behind save/load.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict

import jax
import numpy as np


def _to_host(tree: Any) -> Any:
    def leaf(x: Any) -> Any:
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree_util.tree_map(leaf, tree)


def save_checkpoint(path: str, state: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    host_state = _to_host(state)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)


def load_checkpoint(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        return pickle.load(f)
