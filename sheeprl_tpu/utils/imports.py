"""Optional-dependency gates (role of sheeprl/utils/imports.py:1-17)."""

from __future__ import annotations

import importlib.util


def _available(module: str) -> bool:
    try:
        return importlib.util.find_spec(module) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_IS_MLFLOW_AVAILABLE = _available("mlflow")
_IS_ATARI_AVAILABLE = _available("ale_py")
_IS_BOX2D_AVAILABLE = _available("Box2D")
_IS_MUJOCO_AVAILABLE = _available("mujoco")
_IS_DMC_AVAILABLE = _available("dm_control")
_IS_CRAFTER_AVAILABLE = _available("crafter")
_IS_DIAMBRA_AVAILABLE = _available("diambra")
_IS_MINEDOJO_AVAILABLE = _available("minedojo")
_IS_MINERL_AVAILABLE = _available("minerl")
_IS_ROBOSUITE_AVAILABLE = _available("robosuite")
_IS_SUPER_MARIO_BROS_AVAILABLE = _available("gym_super_mario_bros")
_IS_CV2_AVAILABLE = _available("cv2")
_IS_TENSORBOARD_AVAILABLE = _available("tensorboardX") or _available("torch.utils.tensorboard")
