"""Run-dir management + TensorBoard logging (role of sheeprl/utils/logger.py:12-91).

Rank-0 creates a versioned run directory ``logs/runs/<root_dir>/<run_name>/version_N``
and shares it to other hosts via the host object channel (the reference broadcasts over
a Gloo group, sheeprl/utils/logger.py:53-89).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Dict, Optional


class TensorBoardLogger:
    """Thin tensorboardX wrapper with the reference logger's name/root_dir/version
    layout (sheeprl/configs/logger/tensorboard.yaml)."""

    def __init__(
        self,
        root_dir: str = "logs/runs",
        name: str = "run",
        version: Optional[str] = None,
        **_: Any,
    ) -> None:
        self.root_dir = root_dir
        self.name = name
        self._version = version
        self._writer = None

    @property
    def version(self) -> str:
        if self._version is None:
            base = Path(self.root_dir) / self.name
            existing = []
            if base.is_dir():
                for d in base.iterdir():
                    if d.name.startswith("version_") and d.name[len("version_") :].isdigit():
                        existing.append(int(d.name[len("version_") :]))
            self._version = f"version_{max(existing) + 1 if existing else 0}"
        return self._version

    @property
    def log_dir(self) -> str:
        return str(Path(self.root_dir) / self.name / self.version)

    @property
    def writer(self):
        if self._writer is None:
            from tensorboardX import SummaryWriter

            os.makedirs(self.log_dir, exist_ok=True)
            self._writer = SummaryWriter(logdir=self.log_dir)
        return self._writer

    def log_metrics(self, metrics: Dict[str, float], step: Optional[int] = None) -> None:
        for k, v in metrics.items():
            try:
                self.writer.add_scalar(k, float(v), global_step=step)
            except (TypeError, ValueError):
                continue

    def log_hyperparams(self, params: Dict[str, Any]) -> None:
        try:
            import json

            self.writer.add_text("hparams", "```\n" + json.dumps(params, indent=2, default=str) + "\n```")
        except Exception:
            pass

    def finalize(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


def get_logger(fabric, cfg, log_dir: Optional[str] = None) -> Optional[TensorBoardLogger]:
    """Rank-0-only logger construction (sheeprl/utils/logger.py:12-36). When the run
    dir has already been allocated (``log_dir``), the logger writes inside it instead
    of allocating its own version directory."""
    if fabric.global_rank != 0 or cfg.metric.log_level == 0:
        return None
    from sheeprl_tpu.config import instantiate

    logger_cfg = dict(cfg.metric.logger)
    if log_dir is not None and "TensorBoardLogger" in str(logger_cfg.get("_target_", "")):
        p = Path(log_dir)
        logger_cfg["root_dir"] = str(p.parent.parent)
        logger_cfg["name"] = p.parent.name
        logger_cfg["version"] = p.name
    return instantiate(logger_cfg)


_run_dir_override: Optional[str] = None


def set_run_dir(path: Optional[str]) -> None:
    """Configure the run-directory base from ``cfg.hydra.run.dir`` (role of the
    reference's hydra/default.yaml run-dir control): when set, every versioned run
    dir is created under it instead of the default ``logs/runs/<root>/<name>``."""
    global _run_dir_override
    _run_dir_override = str(path) if path else None


def run_base_dir(root_dir: str, run_name: str) -> Path:
    """The run's base directory (before versioning), honoring the hydra run-dir
    override — the single source of truth for anything that must land next to the
    run's artifacts (versioned log dirs, profiler traces)."""
    if _run_dir_override:
        return Path(_run_dir_override)
    return Path("logs") / "runs" / root_dir / run_name


def get_log_dir(fabric, root_dir: str, run_name: str, share: bool = True) -> str:
    """Create (rank-0) and share the versioned log dir (sheeprl/utils/logger.py:40-91)."""
    base = run_base_dir(root_dir, run_name)
    if fabric.global_rank == 0:
        existing = []
        if base.is_dir():
            for d in base.iterdir():
                if d.name.startswith("version_") and d.name[len("version_") :].isdigit():
                    existing.append(int(d.name[len("version_") :]))
        log_dir = str(base / f"version_{max(existing) + 1 if existing else 0}")
        os.makedirs(log_dir, exist_ok=True)
    else:
        log_dir = None
    from sheeprl_tpu.parallel import distributed

    # sharing is an inter-PROCESS concern (multi-host SPMD: every process calls this
    # and rank-0's dir wins); a single controller process — however many devices its
    # mesh holds — already knows its dir, and MPMD roles pass share=False because
    # only the player calls get_log_dir at all
    if share and distributed.process_count() > 1:
        log_dir = distributed.host_broadcast_object(log_dir, src=0)
    return log_dir


class MLFlowLogger:
    """MLflow metric/param logger (role of the reference's lightning MLFlowLogger
    option, sheeprl/utils/logger.py:12-36 + configs/logger/mlflow.yaml). Optional
    dependency: constructing it without mlflow installed raises the import-gate
    error; the default TensorBoard path never imports mlflow."""

    def __init__(
        self,
        experiment_name: str = "sheeprl",
        tracking_uri: Optional[str] = None,
        run_name: Optional[str] = None,
        run_id: Optional[str] = None,
        tags: Optional[Dict[str, Any]] = None,
        **_: Any,
    ) -> None:
        from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

        if not _IS_MLFLOW_AVAILABLE:
            raise ModuleNotFoundError("mlflow is not installed: pip install mlflow")
        import mlflow

        self._mlflow = mlflow
        self.tracking_uri = tracking_uri or os.environ.get("MLFLOW_TRACKING_URI")
        if self.tracking_uri:
            mlflow.set_tracking_uri(self.tracking_uri)
        from sheeprl_tpu.utils.mlflow import get_or_create_experiment

        experiment_id = get_or_create_experiment(experiment_name)
        self._run = mlflow.start_run(
            run_id=run_id, experiment_id=experiment_id, run_name=run_name, tags=tags
        )

    @property
    def run_id(self) -> str:
        return self._run.info.run_id

    @property
    def log_dir(self) -> Optional[str]:
        return None

    def log_metrics(self, metrics: Dict[str, float], step: Optional[int] = None) -> None:
        clean = {}
        for k, v in metrics.items():
            try:
                clean[k] = float(v)
            except (TypeError, ValueError):
                continue
        if clean:
            self._mlflow.log_metrics(clean, step=step)

    def log_hyperparams(self, params: Dict[str, Any]) -> None:
        try:
            flat = {}

            def _walk(prefix, node):
                if isinstance(node, dict):
                    for k, v in node.items():
                        _walk(f"{prefix}.{k}" if prefix else str(k), v)
                else:
                    flat[prefix] = node

            _walk("", params)
            # mlflow caps params per batch; log in chunks
            items = list(flat.items())
            for i in range(0, len(items), 90):
                self._mlflow.log_params({k: str(v)[:250] for k, v in items[i : i + 90]})
        except Exception:
            pass

    def finalize(self) -> None:
        try:
            self._mlflow.end_run()
        except Exception:
            pass
