"""Feistel pseudorandom permutation over index ranges (in-jit, O(n), no sort).

Hoisted out of ``algos/ppo/anakin.py`` (PR 7) so every fused program that needs
a bijective in-program index shuffle shares ONE implementation: the PPO epoch
shuffle and the device-resident replay ring's uniform sampler
(``data/device_ring.py``) both ride :func:`prp_permutation`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["prp_permutation"]


def _mix32(x: jax.Array) -> jax.Array:
    """32-bit integer finalizer (splitmix-style avalanche) — the Feistel round
    function of :func:`prp_permutation`."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def prp_permutation(key: jax.Array, n: int, rounds: int = 8) -> jax.Array:
    """Pseudorandom permutation of ``[0, n)`` for power-of-two ``n`` via an
    unbalanced Feistel network: O(n) elementwise integer ops, no sort.

    ``jax.random.permutation`` lowers to a full sort — ~460 ms for 2^19 rows on
    XLA CPU, which made the epoch shuffle HALF of the fused Anakin program's
    train phase. A Feistel cipher over the index bits is a bijection by
    construction (each round swaps halves and XORs one through a keyed hash),
    costs ~2 ms at the same size, and is statistically more than enough for
    minibatch decorrelation (tested uncorrelated with identity; every round key
    derives from ``key``, so the shuffle stays deterministic per seed).
    """
    if n & (n - 1) or n < 2:
        raise ValueError(f"prp_permutation needs a power-of-two size >= 2, got {n}")
    bits = int(n).bit_length() - 1
    half_b = bits // 2
    half_a = bits - half_b
    idx = jnp.arange(n, dtype=jnp.uint32)
    left = idx >> half_b
    right = idx & jnp.uint32((1 << half_b) - 1)
    width_l, width_r = half_a, half_b
    round_keys = jax.random.randint(key, (rounds,), 0, np.iinfo(np.int32).max).astype(jnp.uint32)
    for i in range(rounds):
        f = _mix32(right ^ round_keys[i])
        left, right, width_l, width_r = (
            right,
            left ^ (f & jnp.uint32((1 << width_l) - 1)),
            width_r,
            width_l,
        )
    return ((left << width_r) | right).astype(jnp.int32)
