"""Host-side replay buffers feeding device-sharded pytrees.

Re-provides the reference data layer (sheeprl/data/buffers.py: ReplayBuffer:20,
SequentialReplayBuffer:363, EnvIndependentReplayBuffer:529, EpisodeBuffer:746) with the
same ``(T, B, *)`` dict-of-numpy semantics — circular wrap-around writes, uniform /
contiguous-sequence / whole-episode sampling — but the device boundary is JAX: sampling
produces host numpy blocks that ``sample_tensors`` lands on the accelerator with
``jax.device_put`` (optionally with a ``jax.sharding.Sharding`` so batches arrive
already laid out over the mesh, replacing the reference's torch ``.to(device)`` copies).

Storage is plain numpy or ``MemmapArray`` (disk-backed) per key.
"""

from __future__ import annotations

import logging
import os
import shutil
import uuid
from itertools import compress
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from sheeprl_tpu.utils.memmap import MemmapArray

_VALID_MEMMAP_MODES = ("r+", "w+", "c", "copyonwrite", "readwrite", "write")


def _first(data: Dict[str, np.ndarray]) -> np.ndarray:
    return next(iter(data.values()))


def _validate_add_data(data: Any) -> None:
    if not isinstance(data, dict):
        raise ValueError(f"'data' must be a dictionary of numpy arrays, got {type(data)}")
    ref_key, ref_shape = None, None
    for k, v in data.items():
        if not isinstance(v, np.ndarray):
            raise ValueError(f"'data' values must be numpy arrays; key {k!r} has type {type(v)}")
        if v.ndim < 2:
            raise RuntimeError(
                f"'data' arrays must be [sequence_length, n_envs, ...]; shape of {k!r} is {v.shape}"
            )
        if ref_shape is not None and v.shape[:2] != ref_shape:
            raise RuntimeError(
                "every array in 'data' must agree on the first two dims: "
                f"{ref_key!r} has {ref_shape}, {k!r} has {v.shape[:2]}"
            )
        ref_key, ref_shape = k, v.shape[:2]


def get_tensor(
    array: np.ndarray | MemmapArray,
    dtype: Any = None,
    clone: bool = False,
    device: Any = "cpu",
    from_numpy: bool = False,
):
    """Host numpy → jax array (role of reference buffers.py:1158-1180). ``device`` may
    be a jax.Device, a Sharding, or "cpu"/None for the default device."""
    import jax

    if isinstance(array, MemmapArray):
        array = array.array
    if clone:
        array = np.array(array)
    if dtype is not None:
        array = np.asarray(array, dtype=dtype)
    if device is None or device == "cpu":
        return jax.numpy.asarray(array)
    return jax.device_put(array, device)


class ReplayBuffer:
    """Circular ``(buffer_size, n_envs, *)`` dict-of-numpy buffer (reference
    sheeprl/data/buffers.py:20-360)."""

    batch_axis: int = 1

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: str | os.PathLike | None = None,
        memmap_mode: str = "r+",
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._memmap = memmap
        self._memmap_dir = memmap_dir
        self._memmap_mode = memmap_mode
        self._buf: Dict[str, np.ndarray | MemmapArray] = {}
        if self._memmap:
            if self._memmap_mode not in _VALID_MEMMAP_MODES:
                raise ValueError(f"memmap_mode must be one of {_VALID_MEMMAP_MODES}")
            if self._memmap_dir is None:
                raise ValueError(
                    "The buffer is memory-mapped but 'memmap_dir' is None; set it to a directory."
                )
            self._memmap_dir = Path(self._memmap_dir)
            self._memmap_dir.mkdir(parents=True, exist_ok=True)
        self._pos = 0
        self._full = False
        self._rng: np.random.Generator = np.random.default_rng()

    # -- properties ------------------------------------------------------------------

    @property
    def buffer(self) -> Dict[str, np.ndarray]:
        return self._buf

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> bool:
        return self._full

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> bool:
        return not self._buf

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    # -- serialization ---------------------------------------------------------------

    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        if not self._memmap and not self._full:
            # The capacity beyond the write cursor is uninitialized garbage;
            # pickling it writes buffer_size rows regardless of fill (observed:
            # a 60 GB checkpoint for a 320-step run with the default 5M-capacity
            # Dreamer buffer). Persist only the filled prefix; restore
            # reallocates the full capacity. Memmap buffers already serialize as
            # file references.
            state["_buf"] = {k: v[: self._pos].copy() for k, v in self._buf.items()}
            state["_truncated_to_pos"] = True
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        truncated = state.pop("_truncated_to_pos", False)
        self.__dict__.update(state)
        if truncated:
            head = self._buf
            self._buf = {}
            for k, v in head.items():
                full = np.empty((self._buffer_size, self._n_envs, *v.shape[2:]), dtype=v.dtype)
                full[: self._pos] = v
                self._buf[k] = full

    # -- write path ------------------------------------------------------------------

    def _allocate(self, key: str, value: np.ndarray) -> None:
        shape = (self._buffer_size, self._n_envs, *value.shape[2:])
        if self._memmap:
            self._buf[key] = MemmapArray(
                filename=Path(self._memmap_dir) / f"{key}.memmap",
                dtype=value.dtype,
                shape=shape,
                mode=self._memmap_mode,
            )
        else:
            self._buf[key] = np.empty(shape, dtype=value.dtype)

    def add(self, data: "ReplayBuffer" | Dict[str, np.ndarray], validate_args: bool = False) -> None:
        """Write a ``[steps, n_envs, ...]`` block at the cursor with wrap-around;
        oversize blocks keep only their trailing ``buffer_size`` rows."""
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            _validate_add_data(data)
        data_len = _first(data).shape[0]
        if data_len > self._buffer_size:
            data = {k: v[-self._buffer_size :] for k, v in data.items()}
            data_len = self._buffer_size
        next_pos = (self._pos + data_len) % self._buffer_size
        idxes = (np.arange(self._pos, self._pos + data_len) % self._buffer_size).astype(np.intp)
        if self.empty:
            for k, v in data.items():
                self._allocate(k, v)
        for k, v in data.items():
            self._buf[k][idxes] = v
        if self._pos + data_len >= self._buffer_size:
            self._full = True
        self._pos = next_pos

    # -- read path -------------------------------------------------------------------

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """Uniform sample → ``[n_samples, batch_size, ...]``. With ``sample_next_obs``
        the row at the write head is excluded (its successor is invalid)."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if not self._full and self._pos == 0:
            raise ValueError("No sample has been added to the buffer; call add() first")
        if self._full:
            first_range_end = self._pos - 1 if sample_next_obs else self._pos
            second_range_end = (
                self._buffer_size if first_range_end >= 0 else self._buffer_size + first_range_end
            )
            valid = np.concatenate(
                [np.arange(0, max(first_range_end, 0)), np.arange(self._pos, second_range_end)]
            ).astype(np.intp)
            batch_idxes = valid[self._rng.integers(0, len(valid), size=(batch_size * n_samples,))]
        else:
            max_pos = self._pos - 1 if sample_next_obs else self._pos
            if max_pos == 0:
                raise RuntimeError(
                    "sample_next_obs requires at least two samples in the buffer"
                )
            batch_idxes = self._rng.integers(0, max_pos, size=(batch_size * n_samples,), dtype=np.intp)
        samples = self._get_samples(batch_idxes, sample_next_obs=sample_next_obs, clone=clone)
        return {k: v.reshape(n_samples, batch_size, *v.shape[1:]) for k, v in samples.items()}

    def _get_samples(
        self, batch_idxes: np.ndarray, sample_next_obs: bool = False, clone: bool = False
    ) -> Dict[str, np.ndarray]:
        """One fancy-gather per key into a preallocated output dict. The gather
        always materializes fresh rows (never a view of the ring storage), so
        ``clone`` is satisfied for free — no second copy is ever taken."""
        if self.empty:
            raise RuntimeError("The buffer has not been initialized; add some data first")
        n = len(batch_idxes)
        env_idxes = self._rng.integers(0, self._n_envs, size=(n,), dtype=np.intp)
        flat = batch_idxes * self._n_envs + env_idxes
        if sample_next_obs:
            flat_next = ((batch_idxes + 1) % self._buffer_size) * self._n_envs + env_idxes
        out: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            v2 = np.reshape(np.asarray(v), (-1, *v.shape[2:]))
            dst = np.empty((n, *v2.shape[1:]), dtype=v2.dtype)
            np.take(v2, flat, axis=0, out=dst)
            out[k] = dst
            if sample_next_obs and k in self._obs_keys:
                dst_next = np.empty_like(dst)
                np.take(v2, flat_next, axis=0, out=dst_next)
                out[f"next_{k}"] = dst_next
        return out

    def sample_tensors(
        self,
        batch_size: int,
        clone: bool = False,
        sample_next_obs: bool = False,
        dtype: Any = None,
        device: Any = "cpu",
        from_numpy: bool = False,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        """Sample and land on device (jax arrays; ``device`` may be a Sharding so the
        batch arrives mesh-sharded — the TPU path of reference sample_tensors)."""
        n_samples = kwargs.pop("n_samples", 1)
        samples = self.sample(
            batch_size=batch_size, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs
        )
        return {k: get_tensor(v, dtype=dtype, clone=False, device=device) for k, v in samples.items()}

    def to_tensor(self, dtype: Any = None, clone: bool = False, device: Any = "cpu", from_numpy: bool = False):
        return {k: get_tensor(v, dtype=dtype, clone=clone, device=device) for k, v in self._buf.items()}

    # -- dict access -----------------------------------------------------------------

    def __getitem__(self, key: str) -> np.ndarray | MemmapArray:
        if not isinstance(key, str):
            raise TypeError("'key' must be a string")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized; add some data first")
        return self._buf.get(key)

    def __setitem__(self, key: str, value: np.ndarray | MemmapArray) -> None:
        if not isinstance(value, (np.ndarray, MemmapArray)):
            raise ValueError(f"value must be np.ndarray or MemmapArray, got {type(value)}")
        if value.shape[:2] != (self._buffer_size, self._n_envs):
            raise RuntimeError(
                f"'value' must be [buffer_size, n_envs, ...]; got shape {value.shape}"
            )
        if self._memmap:
            filename = value.filename if isinstance(value, MemmapArray) else Path(self._memmap_dir) / f"{key}.memmap"
            self._buf[key] = MemmapArray.from_array(value, filename=filename, mode=self._memmap_mode)
        else:
            self._buf[key] = np.copy(np.asarray(value))


class SequentialReplayBuffer(ReplayBuffer):
    """Contiguous-sequence sampling → ``[n_samples, sequence_length, batch_size, ...]``
    (reference buffers.py:363-526); each sequence comes from a single env and never
    straddles the write head."""

    batch_axis: int = 2

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        batch_dim = batch_size * n_samples
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if not self._full and self._pos == 0:
            raise ValueError("No sample has been added to the buffer; call add() first")
        if not self._full and self._pos - sequence_length + 1 < 1:
            raise ValueError(
                f"Cannot sample a sequence of length {sequence_length}. Data added so far: {self._pos}"
            )
        if self._full and sequence_length > len(self):
            raise ValueError(
                f"The sequence length ({sequence_length}) is greater than the buffer size ({len(self)})"
            )
        if self._full:
            first_range_end = self._pos - sequence_length + 1
            second_range_end = (
                self._buffer_size if first_range_end >= 0 else self._buffer_size + first_range_end
            )
            valid = np.concatenate(
                [np.arange(0, max(first_range_end, 0)), np.arange(self._pos, second_range_end)]
            ).astype(np.intp)
            start_idxes = valid[self._rng.integers(0, len(valid), size=(batch_dim,))]
        else:
            start_idxes = self._rng.integers(0, self._pos - sequence_length + 1, size=(batch_dim,), dtype=np.intp)
        chunk = np.arange(sequence_length, dtype=np.intp)[None, :]
        idxes = (start_idxes[:, None] + chunk) % self._buffer_size
        return self._get_sequence_samples(
            idxes, batch_size, n_samples, sequence_length, sample_next_obs=sample_next_obs, clone=clone
        )

    def _get_sequence_samples(
        self,
        batch_idxes: np.ndarray,
        batch_size: int,
        n_samples: int,
        sequence_length: int,
        sample_next_obs: bool = False,
        clone: bool = False,
    ) -> Dict[str, np.ndarray]:
        flat_batch_idxes = batch_idxes.reshape(-1)
        n_rows = batch_size * n_samples
        if self._n_envs == 1:
            env_idxes = np.zeros((n_rows * sequence_length,), dtype=np.intp)
        else:
            env_idxes = self._rng.integers(0, self._n_envs, size=(n_rows,), dtype=np.intp)
            env_idxes = np.repeat(env_idxes, sequence_length)
        flat = flat_batch_idxes * self._n_envs + env_idxes
        # the fancy gather materializes fresh rows, so `clone` needs no extra copy
        # (the swapaxes result is a view of the gathered copy, not of the ring)
        out: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            v2 = np.reshape(np.asarray(v), (-1, *v.shape[2:]))
            picked = v2[flat]
            batched = picked.reshape(n_samples, batch_size, sequence_length, *picked.shape[1:])
            out[k] = np.swapaxes(batched, 1, 2)
            if sample_next_obs and k in self._obs_keys:
                picked_next = np.asarray(v)[(flat_batch_idxes + 1) % self._buffer_size, env_idxes]
                batched_next = picked_next.reshape(
                    n_samples, batch_size, sequence_length, *picked_next.shape[1:]
                )
                out[f"next_{k}"] = np.swapaxes(batched_next, 1, 2)
        return out


class EnvIndependentReplayBuffer:
    """One sub-buffer per env with ragged cursors (reference buffers.py:529-743):
    needed when per-env episode alignment matters (Dreamer-V3)."""

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: str | os.PathLike | None = None,
        memmap_mode: str = "r+",
        buffer_cls: Type[ReplayBuffer] = ReplayBuffer,
        **kwargs: Any,
    ):
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        if memmap:
            if memmap_mode not in _VALID_MEMMAP_MODES:
                raise ValueError(f"memmap_mode must be one of {_VALID_MEMMAP_MODES}")
            if memmap_dir is None:
                raise ValueError("The buffer is memory-mapped but 'memmap_dir' is None")
            memmap_dir = Path(memmap_dir)
            memmap_dir.mkdir(parents=True, exist_ok=True)
        self._buf: List[ReplayBuffer] = [
            buffer_cls(
                buffer_size=buffer_size,
                n_envs=1,
                obs_keys=obs_keys,
                memmap=memmap,
                memmap_dir=memmap_dir / f"env_{i}" if memmap else None,
                memmap_mode=memmap_mode,
                **kwargs,
            )
            for i in range(n_envs)
        ]
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._rng: np.random.Generator = np.random.default_rng()
        self._concat_along_axis = buffer_cls.batch_axis

    @property
    def buffer(self) -> Sequence[ReplayBuffer]:
        return tuple(self._buf)

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> Sequence[bool]:
        return tuple(b.full for b in self._buf)

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> Sequence[bool]:
        return tuple(b.empty for b in self._buf)

    @property
    def is_memmap(self) -> Sequence[bool]:
        return tuple(b.is_memmap for b in self._buf)

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)
        for i, b in enumerate(self._buf):
            b.seed(None if seed is None else seed + i)

    def add(
        self,
        data: "ReplayBuffer" | Dict[str, np.ndarray],
        indices: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if indices is None:
            indices = tuple(range(self._n_envs))
        elif len(indices) != _first(data).shape[1]:
            raise ValueError(
                f"The length of 'indices' ({len(indices)}) must equal the second dim of "
                f"'data' ({_first(data).shape[1]})"
            )
        for data_idx, env_idx in enumerate(indices):
            env_data = {k: v[:, data_idx : data_idx + 1] for k, v in data.items()}
            self._buf[env_idx].add(env_data, validate_args=validate_args)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        bs_per_buf = np.bincount(self._rng.integers(0, self._n_envs, (batch_size,)))
        per_buf = [
            b.sample(batch_size=bs, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs)
            for b, bs in zip(self._buf, bs_per_buf)
            if bs > 0
        ]
        # sub-samples are already fresh gathers: a single-env draw needs no copy at
        # all, and multi-env draws concatenate once per key into a preallocated dst
        if len(per_buf) == 1:
            return per_buf[0]
        axis = self._concat_along_axis
        out: Dict[str, np.ndarray] = {}
        for k in per_buf[0]:
            parts = [s[k] for s in per_buf]
            shape = list(parts[0].shape)
            shape[axis] = sum(p.shape[axis] for p in parts)
            dst = np.empty(shape, dtype=parts[0].dtype)
            np.concatenate(parts, axis=axis, out=dst)
            out[k] = dst
        return out

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        dtype: Any = None,
        device: Any = "cpu",
        from_numpy: bool = False,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        samples = self.sample(
            batch_size=batch_size, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs
        )
        return {k: get_tensor(v, dtype=dtype, device=device) for k, v in samples.items()}


class EpisodeBuffer:
    """Whole-episode storage with open-episode accumulation per env, oldest-episode
    eviction and optional ``prioritize_ends`` sampling (reference buffers.py:746-1120)."""

    batch_axis: int = 2

    def __init__(
        self,
        buffer_size: int,
        minimum_episode_length: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        prioritize_ends: bool = False,
        memmap: bool = False,
        memmap_dir: str | os.PathLike | None = None,
        memmap_mode: str = "r+",
    ) -> None:
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if minimum_episode_length <= 0:
            raise ValueError(
                f"The sequence length must be greater than zero, got: {minimum_episode_length}"
            )
        if buffer_size < minimum_episode_length:
            raise ValueError(
                "The sequence length must be lower than the buffer size, "
                f"got: bs = {buffer_size} and sl = {minimum_episode_length}"
            )
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._buffer_size = buffer_size
        self._minimum_episode_length = minimum_episode_length
        self._prioritize_ends = prioritize_ends
        self._open_episodes: List[List[Dict[str, np.ndarray]]] = [[] for _ in range(n_envs)]
        self._cum_lengths: List[int] = []
        self._buf: List[Dict[str, np.ndarray | MemmapArray]] = []
        self._memmap = memmap
        self._memmap_dir = memmap_dir
        self._memmap_mode = memmap_mode
        self._rng: np.random.Generator = np.random.default_rng()
        if self._memmap:
            if self._memmap_mode not in _VALID_MEMMAP_MODES:
                raise ValueError(f"memmap_mode must be one of {_VALID_MEMMAP_MODES}")
            if self._memmap_dir is None:
                raise ValueError("The buffer is memory-mapped but 'memmap_dir' is None")
            self._memmap_dir = Path(self._memmap_dir)
            self._memmap_dir.mkdir(parents=True, exist_ok=True)

    # -- properties ------------------------------------------------------------------

    @property
    def prioritize_ends(self) -> bool:
        return self._prioritize_ends

    @prioritize_ends.setter
    def prioritize_ends(self, value: bool) -> None:
        self._prioritize_ends = value

    @property
    def buffer(self) -> Sequence[Dict[str, np.ndarray | MemmapArray]]:
        return self._buf

    @property
    def obs_keys(self) -> Sequence[str]:
        return self._obs_keys

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def minimum_episode_length(self) -> int:
        return self._minimum_episode_length

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    @property
    def full(self) -> bool:
        return (
            self._cum_lengths[-1] + self._minimum_episode_length > self._buffer_size
            if self._buf
            else False
        )

    def __len__(self) -> int:
        return self._cum_lengths[-1] if self._buf else 0

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    # -- write path ------------------------------------------------------------------

    def add(
        self,
        data: "ReplayBuffer" | Dict[str, np.ndarray],
        env_idxes: Sequence[int] | None = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            _validate_add_data(data)
            if "terminated" not in data and "truncated" not in data:
                raise RuntimeError(
                    f"The episode must contain the `terminated` and the `truncated` keys, got: {data.keys()}"
                )
            if env_idxes is not None and (np.asarray(env_idxes) >= self._n_envs).any():
                raise ValueError(
                    f"The indices of the environment must be integers in [0, {self._n_envs}), given {env_idxes}"
                )
        if env_idxes is None:
            env_idxes = range(self._n_envs)
        for i, env in enumerate(env_idxes):
            env_data = {k: v[:, i] for k, v in data.items()}
            done = np.logical_or(env_data["terminated"], env_data["truncated"])
            episode_ends = done.nonzero()[0].tolist()
            if len(episode_ends) == 0:
                self._open_episodes[env].append(env_data)
                continue
            episode_ends.append(len(done))
            start = 0
            for ep_end_idx in episode_ends:
                stop = ep_end_idx
                episode = {k: env_data[k][start : stop + 1] for k in env_data}
                if len(np.logical_or(episode["terminated"], episode["truncated"])) > 0:
                    self._open_episodes[env].append(episode)
                start = stop + 1
                last = self._open_episodes[env][-1] if self._open_episodes[env] else None
                if last is not None and np.logical_or(last["terminated"][-1], last["truncated"][-1]):
                    self._save_episode(self._open_episodes[env])
                    self._open_episodes[env] = []

    def _save_episode(self, episode_chunks: Sequence[Dict[str, np.ndarray]]) -> None:
        if len(episode_chunks) == 0:
            raise RuntimeError("Invalid episode, an empty sequence is given.")
        episode = {
            k: np.concatenate([chunk[k] for chunk in episode_chunks], axis=0)
            for k in episode_chunks[0]
        }
        ends = np.logical_or(episode["terminated"], episode["truncated"])
        ep_len = ends.shape[0]
        if len(ends.nonzero()[0]) != 1 or not ends[-1]:
            raise RuntimeError("The episode must contain exactly one done at its end")
        if ep_len < self._minimum_episode_length:
            raise RuntimeError(
                f"Episode too short (at least {self._minimum_episode_length} steps), got: {ep_len} steps"
            )
        if ep_len > self._buffer_size:
            raise RuntimeError(
                f"Episode too long (at most {self._buffer_size} steps), got: {ep_len} steps"
            )
        # evict oldest episodes until the new one fits
        if self.full or len(self) + ep_len > self._buffer_size:
            cum = np.asarray(self._cum_lengths)
            mask = (len(self) - cum + ep_len) <= self._buffer_size
            last_to_remove = int(mask.argmax())
            if self._memmap and self._memmap_dir is not None:
                for _ in range(last_to_remove + 1):
                    first_key = next(iter(self._buf[0].keys()))
                    dirname = os.path.dirname(self._buf[0][first_key].filename)
                    self._buf.pop(0)
                    try:
                        shutil.rmtree(dirname)
                    except Exception as e:  # pragma: no cover
                        logging.error(e)
            else:
                self._buf = self._buf[last_to_remove + 1 :]
            cum = cum[last_to_remove + 1 :] - cum[last_to_remove]
            self._cum_lengths = cum.tolist()
        self._cum_lengths.append(len(self) + ep_len)
        if self._memmap:
            episode_dir = Path(self._memmap_dir) / f"episode_{uuid.uuid4()}"
            episode_dir.mkdir(parents=True, exist_ok=True)
            stored: Dict[str, np.ndarray | MemmapArray] = {}
            for k, v in episode.items():
                stored[k] = MemmapArray(
                    filename=str(episode_dir / f"{k}.memmap"),
                    dtype=v.dtype,
                    shape=v.shape,
                    mode=self._memmap_mode,
                )
                stored[k][:] = v
            self._buf.append(stored)
        else:
            self._buf.append(episode)

    # -- read path -------------------------------------------------------------------

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0:
            raise ValueError(f"Batch size must be greater than 0, got: {batch_size}")
        if n_samples <= 0:
            raise ValueError(f"The number of samples must be greater than 0, got: {n_samples}")
        lengths = np.asarray(self._cum_lengths) - np.asarray([0] + self._cum_lengths[:-1])
        if sample_next_obs:
            valid_mask = lengths > sequence_length
        else:
            valid_mask = lengths >= sequence_length
        valid_episodes = list(compress(self._buf, valid_mask))
        if len(valid_episodes) == 0:
            raise RuntimeError(
                "No valid episodes in the buffer; add at least one episode of length >= "
                f"{sequence_length}"
            )
        chunk = np.arange(sequence_length, dtype=np.intp)[None, :]
        nsample_per_eps = np.bincount(
            self._rng.integers(0, len(valid_episodes), (batch_size * n_samples,))
        ).astype(np.intp)
        gathered: Dict[str, List[np.ndarray]] = {k: [] for k in valid_episodes[0]}
        if sample_next_obs:
            gathered.update({f"next_{k}": [] for k in self._obs_keys})
        for i, n in enumerate(nsample_per_eps):
            if n <= 0:
                continue
            ep = valid_episodes[i]
            ep_len = np.logical_or(ep["terminated"], ep["truncated"]).shape[0]
            if sample_next_obs:
                ep_len -= 1
            upper = ep_len - sequence_length + 1
            if self._prioritize_ends:
                upper += sequence_length
            start_idxes = np.minimum(
                self._rng.integers(0, upper, size=(n, 1)), ep_len - sequence_length, dtype=np.intp
            )
            indices = start_idxes + chunk
            for k in valid_episodes[0]:
                arr = np.asarray(ep[k])
                gathered[k].append(
                    arr[indices.reshape(-1)].reshape(n, sequence_length, *arr.shape[1:])
                )
                if sample_next_obs and k in self._obs_keys:
                    gathered[f"next_{k}"].append(arr[indices + 1])
        out: Dict[str, np.ndarray] = {}
        for k, v in gathered.items():
            if v:
                out[k] = np.moveaxis(
                    np.concatenate(v, axis=0).reshape(
                        n_samples, batch_size, sequence_length, *v[0].shape[2:]
                    ),
                    2,
                    1,
                )
                if clone:
                    out[k] = out[k].copy()
        return out

    def sample_tensors(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        dtype: Any = None,
        device: Any = "cpu",
        from_numpy: bool = False,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        samples = self.sample(batch_size, sample_next_obs, n_samples, clone, sequence_length)
        return {k: get_tensor(v, dtype=dtype, device=device) for k, v in samples.items()}
