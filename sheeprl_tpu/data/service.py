"""Standalone experience data-plane service: N actors feed, M learners sample.

Every decoupled topology before this module coupled acting and learning
one-to-one: the player samples its OWN replay buffer and blocks on the learner's
round (``BroadcastChannel`` lockstep alternation), so actor cores idle while the
learner's fused train program runs — PERF_ANALYSIS.md's structural bound once
train programs are fast. MindSpeed RL (arxiv 2507.19017) argues the unit of
production RL is a fleet with a shared distributed dataflow, and the Podracer
architectures (arxiv 2104.06272) fill accelerators by decoupling actor and
learner pools. This module is that dataflow, built on the machinery already in
the tree:

- **Transport** is the jax.distributed coordination-service KV store — the same
  gRPC object plane the decoupled channels (``parallel/distributed.py``) and the
  distributed-resilience control plane (``resilience/distributed.py``) ride.
  Unlike the lockstep channels, ingestion is **append-only and asynchronous**:
  each actor writes sequence-numbered row blocks under its own keyspace, the
  service drains all actor streams at its own pace, and a learner's slow round
  never blocks an actor (until the bounded ``max_inflight`` watermark).
- **Liveness** reuses the PR 6 hooks: every blocking wait here runs in
  ``poll_s`` slices with the resilience layer's ``abort_check`` between slices
  (a declared-dead peer raises ``RankFailureError`` instead of hanging) and a
  hard ``timeout_s`` deadline (``ServiceTimeout``).
- **Learner-side sampling is unchanged**: the service feeds an ordinary replay
  buffer that ``make_replay_sampler`` (``data/prefetch.py``) samples and stages
  exactly as the in-process path does — sharded staging, prefetch pipeline,
  donation downstream all untouched. ``buffer.backend=local`` (the default)
  bypasses this module entirely.

Wire protocol (namespace ``ns``, all keys GC'd by their consumer):

==============================  ==================================================
``{ns}/ing/a{r}/{seq}/c{i}``    chunked pickled ingest message ``i`` of actor r
``{ns}/ing/a{r}/{seq}/n``       chunk count — written LAST, so its presence
                                means the whole message exists
``{ns}/ing/pub/r{r}``           actor r's latest published seq (one dir-get
                                tells the service every stream's frontier)
``{ns}/ing/ack/r{r}``           service's consumed frontier for actor r (the
                                writer's flow-control watermark)
``{ns}/ing/eos/r{r}``           actor r closed its stream (JSON: rows, steps,
                                preempted)
``{ns}/w/{v}/c{i}``, ``.../n``  weight payload version v (immutable once
                                written; versions <= v-2 GC'd by the publisher)
``{ns}/w/latest``               latest committed weight version
``{ns}/done``                   the learner finished (actors may exit)
==============================  ==================================================

Each ingest message carries ``{"rank", "seq", "env_ids", "steps", "rows",
"born", "weight_version"}`` — rank/stream-tagged provenance the service folds
into per-actor counters (and the buffer's env slots, keyed by the actor's env
ids), so a fleet of actors is attributable end-to-end. The last two fields are
the dataflow LINEAGE this plane's observability rides on (howto/observability.md
"Tracing the dataflow"): ``born`` is the wall-clock time the message's oldest
row left the env (ingest latency = drain time − born), and ``weight_version``
is the version the acting actor held when it produced the rows — the learner
derives per-actor weight LAG from it, and the :class:`_AgeBook` turns the
(rows, born) trail into the sampled-row age distribution (seconds and
add-rounds) a uniform replay draw would see.

For single-process unit tests :class:`LocalKV` implements the same surface over
a dict + condition variable; ``tests/test_data/test_service.py`` drives the
writer/service/weight plane against it without ``jax.distributed``.
"""

from __future__ import annotations

import json
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "ActorDataflow",
    "ExperienceService",
    "ExperienceWriter",
    "LearnerDataflow",
    "LocalKV",
    "ServiceError",
    "ServiceTimeout",
    "WeightPublisher",
    "WeightSubscriber",
    "clear_local_service_plane",
    "coordination_kv",
    "install_local_service_plane",
    "service_layout",
    "service_namespace",
    "service_options",
]

_KV_CHUNK = 2 * 1024 * 1024  # stay under gRPC message-size defaults


class ServiceError(RuntimeError):
    """An experience-service operation failed (transport error, closed peer)."""


class ServiceTimeout(ServiceError):
    """A bounded service wait exhausted its deadline — the peer is slow, hung,
    or dead (liveness failures surface separately via ``abort_check``)."""


# ---------------------------------------------------------------------------------
# KV plane: one surface over the coordination-service client and the local fake
# ---------------------------------------------------------------------------------


class CoordinationKV:
    """The jax.distributed coordination-service KV store behind the one surface
    the service machinery speaks. Get methods are non-blocking probes (a missing
    key returns None); the callers own deadlines and abort checks."""

    def __init__(self, client: Any) -> None:
        self._client = client

    @staticmethod
    def _is_missing(exc: BaseException) -> bool:
        # the jaxlib client surfaces status only in the message text; a tiny
        # blocking-get deadline expiring means "not there yet"
        text = str(exc).upper()
        return (
            "DEADLINE" in text or "TIMED OUT" in text or "TIMEOUT" in text or "NOT_FOUND" in text
        )

    def set(self, key: str, value: str) -> None:
        self._client.key_value_set(key, value, allow_overwrite=True)

    def set_bytes(self, key: str, value: bytes) -> None:
        self._client.key_value_set_bytes(key, value)

    def get(self, key: str) -> Optional[str]:
        try:
            return self._client.blocking_key_value_get(key, 50)
        except Exception as exc:
            if self._is_missing(exc):
                return None
            raise

    def get_bytes(self, key: str) -> Optional[bytes]:
        try:
            return self._client.blocking_key_value_get_bytes(key, 50)
        except Exception as exc:
            if self._is_missing(exc):
                return None
            raise

    def dir(self, prefix: str) -> List[Tuple[str, str]]:
        try:
            return list(self._client.key_value_dir_get(prefix))
        except Exception:
            return []  # NOT_FOUND before the first write

    def delete(self, prefix: str) -> None:
        try:
            self._client.key_value_delete(prefix)
        except Exception:
            pass  # GC is best-effort; a dying coordinator ends the run anyway


class LocalKV:
    """In-process KV fake with the same surface (dict + condition variable):
    lets unit tests run writers, the service and the weight plane as threads of
    one process, without a jax.distributed session."""

    def __init__(self) -> None:
        self._data: Dict[str, Any] = {}
        self._cond = threading.Condition()

    def set(self, key: str, value: str) -> None:
        with self._cond:
            self._data[key] = str(value)
            self._cond.notify_all()

    def set_bytes(self, key: str, value: bytes) -> None:
        with self._cond:
            self._data[key] = bytes(value)
            self._cond.notify_all()

    def get(self, key: str) -> Optional[str]:
        with self._cond:
            value = self._data.get(key)
            return None if value is None else str(value)

    def get_bytes(self, key: str) -> Optional[bytes]:
        with self._cond:
            value = self._data.get(key)
            return None if value is None else bytes(value)

    def dir(self, prefix: str) -> List[Tuple[str, str]]:
        with self._cond:
            return [(k, v) for k, v in self._data.items() if k.startswith(prefix)]

    def delete(self, prefix: str) -> None:
        with self._cond:
            for k in [k for k in self._data if k.startswith(prefix)]:
                del self._data[k]


# in-process service-plane override (`sheeprl_tpu/live`): the live flywheel
# runs serve and learner ROLES as threads of one process, so they must share a
# single KV instance and a single namespace. `coordination_kv()` and
# `service_namespace()` consult these before their multi-process defaults —
# which is enough, because `_service_learner` imports both lazily at call time.
_kv_override: Optional[Any] = None
_namespace_override: Optional[str] = None


def install_local_service_plane(
    kv: Optional[Any] = None, namespace: Optional[str] = None
) -> Tuple[Any, str]:
    """Pin every subsequent ``coordination_kv()`` / ``service_namespace()``
    call of this process to one shared in-process plane (a :class:`LocalKV` by
    default, with one freshly-derived namespace). Returns ``(kv, namespace)``;
    undo with :func:`clear_local_service_plane`."""
    global _kv_override, _namespace_override
    _kv_override = kv if kv is not None else LocalKV()
    if namespace is None:
        # derive ONE namespace through the normal nonce path, then pin it so
        # every role of the gang resolves the same keyspace
        _namespace_override = None
        namespace = service_namespace()
    _namespace_override = str(namespace)
    return _kv_override, _namespace_override


def clear_local_service_plane() -> None:
    global _kv_override, _namespace_override
    _kv_override = None
    _namespace_override = None


def coordination_kv() -> Optional[CoordinationKV]:
    """The process's coordination-service KV plane, or None outside a
    jax.distributed session (callers fail with an actionable message — the
    service backend is a multi-process construct by design). An installed
    in-process plane (:func:`install_local_service_plane`) wins."""
    if _kv_override is not None:
        return _kv_override
    from sheeprl_tpu.parallel.distributed import _kv_client

    client = _kv_client()
    return CoordinationKV(client) if client is not None else None


# per-process count of service planes built, namespacing the keyspace so a later
# run in the same jax.distributed session (sequential tests in one interpreter)
# never reads the previous run's stale streams — the BroadcastChannel pattern.
# Stays aligned across processes because every role builds exactly one plane per
# run at the same protocol point (its service construction in the algo's main).
_service_builds = 0


def service_namespace() -> str:
    import os

    if _namespace_override is not None:
        return _namespace_override
    global _service_builds
    nonce = _service_builds
    _service_builds += 1
    attempt = os.environ.get("SHEEPRL_GANG_ATTEMPT", "0")
    return f"sheeprl_xp/i{nonce}/a{attempt}"


def service_options(cfg: Any) -> Dict[str, Any]:
    """The ``buffer.service`` knobs plus the PR 6 channel liveness hooks
    (``resilience.distributed.channel`` timeout/poll + the dead-peer abort
    check), as keyword arguments for the classes below."""
    from sheeprl_tpu.resilience.distributed import channel_abort_check

    scfg = (cfg.buffer.get("service") or {}) if cfg.buffer is not None else {}
    ccfg = (((cfg.get("resilience") or {}).get("distributed") or {}).get("channel")) or {}
    return {
        "max_inflight": int(scfg.get("max_inflight") or 8),
        "flush_every": int(scfg.get("flush_every") or 1),
        "poll_s": float(scfg.get("poll") or 0.05),
        "timeout_s": float(ccfg.get("timeout") or 1800.0),
        "abort_check": channel_abort_check,
        # actors refresh weights from the plane by default; false freezes them on
        # their init weights — the deliberate stale-weight injection the
        # weight_staleness detector smoke rides (howto/observability.md)
        "poll_weights": bool(scfg.get("poll_weights", True)),
    }


def service_layout(cfg: Any) -> Dict[str, Any]:
    """The service topology derived from config + the live process count:
    ranks ``0..actors-1`` act, ranks ``actors..nprocs-1`` learn. Raises with an
    actionable message when the config cannot form a service plane."""
    from sheeprl_tpu.parallel import distributed

    nprocs = distributed.process_count()
    actors = int((cfg.buffer.get("service") or {}).get("actors") or 1)
    if nprocs < 2:
        raise ValueError(
            "buffer.backend=service needs a multi-process run (the service decouples "
            "actor PROCESSES from learner processes): launch a gang with "
            "resilience.distributed.gang.processes=<actors+learners> or bring up "
            "jax.distributed externally; buffer.backend=local is the in-process path"
        )
    if not (1 <= actors <= nprocs - 1):
        raise ValueError(
            f"buffer.service.actors={actors} leaves no learner rank in a "
            f"{nprocs}-process run (need 1 <= actors <= {nprocs - 1})"
        )
    return {
        "nprocs": nprocs,
        "actors": actors,
        "learners": nprocs - actors,
        "actor_ranks": tuple(range(actors)),
        "learner_ranks": tuple(range(actors, nprocs)),
        "leader": actors,  # the learner rank hosting the service/buffer
    }


def _bounded_wait(
    predicate: Callable[[], Optional[Any]],
    *,
    timeout_s: float,
    poll_s: float,
    abort_check: Optional[Callable[[], None]],
    what: str,
) -> Any:
    """Poll ``predicate`` until it returns non-None, with the PR 6 liveness
    contract: ``abort_check`` between slices (raises on a declared-dead peer),
    ``ServiceTimeout`` when the hard deadline expires."""
    deadline = time.monotonic() + timeout_s
    while True:
        if abort_check is not None:
            abort_check()
        value = predicate()
        if value is not None:
            return value
        if time.monotonic() >= deadline:
            raise ServiceTimeout(
                f"experience service wait for {what} timed out after {timeout_s:.0f}s "
                "— the peer is slow, hung, or dead"
            )
        time.sleep(poll_s)


# ---------------------------------------------------------------------------------
# Actor side: append-only ingestion writer
# ---------------------------------------------------------------------------------


class ExperienceWriter:
    """One actor's append-only ingestion stream.

    ``add(rows, env_ids)`` accumulates ``[1, E, ...]`` step blocks host-side and
    every ``flush_every`` adds ships them as ONE chunked message (pickled
    ``{"rank", "seq", "env_ids", "steps", "rows"}`` — rows stacked on the time
    axis, images staying uint8 across the wire). Flow control: the service acks
    its consumed frontier per actor; a writer more than ``max_inflight``
    messages ahead blocks (bounded, abort-checked) — acting can outrun a learner
    hiccup by the watermark but never flood the KV store. ``close()`` publishes
    the end-of-stream marker."""

    def __init__(
        self,
        kv: Any,
        ns: str,
        rank: int,
        *,
        max_inflight: int = 8,
        flush_every: int = 1,
        poll_s: float = 0.05,
        timeout_s: float = 1800.0,
        abort_check: Optional[Callable[[], None]] = None,
    ) -> None:
        if max_inflight < 1:
            raise ValueError(f"'max_inflight' must be >= 1, got {max_inflight}")
        if flush_every < 1:
            raise ValueError(f"'flush_every' must be >= 1, got {flush_every}")
        self.kv = kv
        self.ns = ns
        self.rank = int(rank)
        self.max_inflight = int(max_inflight)
        self.flush_every = int(flush_every)
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self.abort_check = abort_check
        self._seq = 0
        self._pending: List[Tuple[Dict[str, np.ndarray], Optional[Sequence[int]], float]] = []
        self._closed = False
        # the weight version this actor currently ACTS with — the loop updates it
        # after every successful refresh, and every shipped message carries it, so
        # the learner can account per-actor weight lag (dataflow lineage)
        self.weight_version = 0
        # consumer-side counters for telemetry (rows = env transitions shipped)
        self._tele_rows = 0
        self._tele_messages = 0
        self._tele_bytes = 0
        self._tele_block_seconds = 0.0

    # -- internals ---------------------------------------------------------------

    def _acked(self) -> int:
        value = self.kv.get(f"{self.ns}/ing/ack/r{self.rank}")
        return int(value) if value else 0

    def _wait_for_credit(self) -> None:
        if self._seq - self._acked() < self.max_inflight:
            return
        t0 = time.perf_counter()
        _bounded_wait(
            lambda: True if self._seq - self._acked() < self.max_inflight else None,
            timeout_s=self.timeout_s,
            poll_s=self.poll_s,
            abort_check=self.abort_check,
            what=f"ingest credit (actor {self.rank}, {self.max_inflight} in flight)",
        )
        self._tele_block_seconds += time.perf_counter() - t0

    def _put_message(self, payload: bytes) -> None:
        tag = f"{self.ns}/ing/a{self.rank}/{self._seq}"
        n = max(1, -(-len(payload) // _KV_CHUNK))
        for i in range(n):
            self.kv.set_bytes(f"{tag}/c{i}", payload[i * _KV_CHUNK : (i + 1) * _KV_CHUNK])
        self.kv.set(f"{tag}/n", str(n))
        # the frontier key commits the message: one dir-get over {ns}/ing/pub/
        # tells the service every actor's latest complete seq
        self.kv.set(f"{self.ns}/ing/pub/r{self.rank}", str(self._seq))
        self._seq += 1
        self._tele_messages += 1
        self._tele_bytes += len(payload)

    # -- actor-loop API ----------------------------------------------------------

    @property
    def seq(self) -> int:
        return self._seq

    def add(
        self,
        rows: Mapping[str, np.ndarray],
        env_ids: Optional[Sequence[int]] = None,
        steps: Optional[int] = None,
    ) -> None:
        """Queue one ``[1, E, ...]`` step block (``env_ids``: the service-buffer
        env slots these columns belong to; None = this actor's full span) and
        flush when ``flush_every`` blocks are pending."""
        if self._closed:
            raise ServiceError("add() on a closed ExperienceWriter")
        # COPY, not view: with flush_every > 1 the pending blocks outlive the
        # caller's iteration, and vector envs reuse their observation storage —
        # an aliased view would stack flush_every copies of the LAST step
        block = {k: np.array(v) for k, v in rows.items()}
        n_rows = int(next(iter(block.values())).shape[0] * next(iter(block.values())).shape[1])
        self._tele_rows += n_rows
        # birth stamp: when the rows left the env, not when the message ships —
        # with flush_every > 1 the oldest pending block sets the message's age
        self._pending.append((block, tuple(env_ids) if env_ids is not None else None, time.time()))
        if len(self._pending) >= self.flush_every:
            self.flush(steps=steps)

    def flush(self, steps: Optional[int] = None) -> None:
        if not self._pending:
            return
        self._wait_for_credit()
        # one message per (env_ids) group, preserving order: full-span rows ship
        # together (stacked on the time axis), partial adds (dreamer's SAME_STEP
        # reset rows) ship as their own messages so env alignment survives
        groups: List[Tuple[Optional[Tuple[int, ...]], List[Dict[str, np.ndarray]], float]] = []
        for block, ids, born in self._pending:
            if groups and groups[-1][0] == ids:
                groups[-1][1].append(block)
            else:
                groups.append((ids, [block], born))
        self._pending = []
        for ids, blocks, born in groups:
            rows = (
                blocks[0]
                if len(blocks) == 1
                else {k: np.concatenate([b[k] for b in blocks], axis=0) for k in blocks[0]}
            )
            payload = pickle.dumps(
                {
                    "rank": self.rank,
                    "seq": self._seq,
                    "env_ids": ids,
                    "steps": int(steps) if steps is not None else None,
                    "rows": rows,
                    "born": born,
                    "weight_version": int(self.weight_version),
                }
            )
            self._put_message(payload)

    def close(self, preempted: bool = False) -> None:
        """Flush pending rows and publish the end-of-stream marker."""
        if self._closed:
            return
        try:
            self.flush()
        finally:
            self._closed = True
            self.kv.set(
                f"{self.ns}/ing/eos/r{self.rank}",
                json.dumps(
                    {"rows": self._tele_rows, "messages": self._seq, "preempted": bool(preempted)}
                ),
            )

    def wait_done(self, timeout_s: Optional[float] = None) -> bool:
        """Block (bounded, abort-checked) until the learner publishes the run's
        ``done`` marker — actors exit together with the learner, so a gang's
        teardown grace window never SIGTERMs a learner still draining. Returns
        False on timeout instead of raising: a missing done marker at exit is a
        warning, not a failure (heartbeats catch a DEAD learner much earlier)."""
        try:
            _bounded_wait(
                lambda: self.kv.get(f"{self.ns}/done"),
                timeout_s=float(timeout_s if timeout_s is not None else self.timeout_s),
                poll_s=self.poll_s,
                abort_check=self.abort_check,
                what="the learner's done marker",
            )
            return True
        except ServiceTimeout:
            return False

    def telemetry_snapshot(self) -> Dict[str, Any]:
        return {
            "rows": self._tele_rows,
            "messages": self._tele_messages,
            "bytes": self._tele_bytes,
            "flow_block_seconds": round(self._tele_block_seconds, 4),
            "inflight": self._seq - self._acked(),
            "weight_version": int(self.weight_version),
        }


# ---------------------------------------------------------------------------------
# Learner side: the service draining actor streams into a replay buffer
# ---------------------------------------------------------------------------------


def _weighted_percentiles(entries: Sequence[Tuple[int, float]]) -> Optional[Dict[str, float]]:
    """{p50, p99, mean, max} of a row-weighted value sample: ``entries`` are
    (rows, value) pairs, each value counting ``rows`` times — the exact
    distribution a uniform draw over those rows would see, without expanding
    the sample row-by-row."""
    pairs = sorted((float(v), int(n)) for n, v in entries if n > 0)
    total = sum(n for _, n in pairs)
    if total <= 0:
        return None
    out: Dict[str, float] = {}
    targets = {"p50": 0.5 * total, "p99": 0.99 * total}
    seen = 0
    acc = 0.0
    for value, n in pairs:
        acc += value * n
        seen += n
        for name, target in list(targets.items()):
            if seen >= target:
                out[name] = round(value, 4)
                del targets[name]
    out["mean"] = round(acc / total, 4)
    out["max"] = round(pairs[-1][0], 4)
    return out


class _AgeBook:
    """Capacity-bounded trail of what the replay buffer currently holds, kept by
    the ingest thread: one entry per ingested message ``(rows, born, round)``
    where ``round`` is the message's global add-round index. Entries beyond the
    buffer's row capacity are evicted from the left — the same FIFO the ring
    buffer overwrites in — so :meth:`age_snapshot` is the age distribution of
    the rows a uniform sample draws from, in seconds (wall clock since the rows
    left the env) and in add-rounds (how many ingest messages ago)."""

    def __init__(self, capacity_rows: Optional[int]) -> None:
        from collections import deque

        # None = unknown capacity: fall back to a generous entry cap so the
        # book cannot grow without bound on exotic buffers. A deque: eviction
        # runs on the ingest-drain path (which contends with the sampler lock),
        # so the FIFO must be O(1) per message even at the entry cap. The lock
        # covers writer (ingest thread) vs snapshot reader (the learner's
        # telemetry window emit) — an unguarded deque iteration would raise
        # "mutated during iteration" under load and freeze the gauges.
        self.capacity_rows = int(capacity_rows) if capacity_rows else None
        self._entries: "deque[Tuple[int, float, int]]" = deque()
        self._lock = threading.Lock()
        self._rows = 0
        self._round = 0

    def record(self, rows: int, born: Optional[float]) -> None:
        with self._lock:
            self._round += 1
            if born is None:
                return  # a pre-lineage writer: age unknown, never guessed
            self._entries.append((int(rows), float(born), self._round))
            self._rows += int(rows)
            cap = self.capacity_rows
            while (cap is not None and self._rows > cap) or len(self._entries) > 65536:
                evicted = self._entries.popleft()
                self._rows -= evicted[0]

    def age_snapshot(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        with self._lock:
            if not self._entries:
                return None
            entries = list(self._entries)
            current_round = self._round
        now = time.time() if now is None else float(now)
        seconds = _weighted_percentiles([(n, max(now - born, 0.0)) for n, born, _ in entries])
        rounds = _weighted_percentiles(
            [(n, float(current_round - rnd)) for n, _, rnd in entries]
        )
        return {"seconds": seconds, "rounds": rounds, "add_rounds": current_round}


class ExperienceService:
    """Drains every actor's ingestion stream into a replay buffer.

    Runs an ingest thread (start/stop) that polls the publication frontier,
    fetches complete messages in actor order, and ``rb.add``s their rows under
    ``lock`` — the same mutex the learner's replay sampler gathers under, so a
    sampled block is never a torn read of a half-written row (the
    ``data/prefetch.py`` contract). Consumed messages are acked (the writers'
    flow-control credit) and deleted (KV GC).

    ``rb`` is any buffer with the ``add(rows, env_ids?, validate_args=...)``
    surface (``EnvIndependentReplayBuffer`` for per-actor env slots, plain
    ``ReplayBuffer`` for a single flat span). Counters are per-actor
    (provenance) and aggregate; ``queue_depth`` is the published-minus-consumed
    backlog across actors — the "is the learner keeping up" gauge the
    ``fleet_ingest`` bench records."""

    def __init__(
        self,
        rb: Any,
        kv: Any,
        ns: str,
        actor_ranks: Sequence[int],
        *,
        lock: Optional[threading.Lock] = None,
        poll_s: float = 0.05,
        env_ids_of: Optional[Callable[[int], Sequence[int]]] = None,
        validate_args: bool = False,
    ) -> None:
        self.rb = rb
        self.kv = kv
        self.ns = ns
        self.actor_ranks = tuple(int(r) for r in actor_ranks)
        self.lock = lock or threading.Lock()
        self.poll_s = float(poll_s)
        self._env_ids_of = env_ids_of
        self._validate_args = bool(validate_args)
        self._consumed: Dict[int, int] = {r: 0 for r in self.actor_ranks}
        self._eos: Dict[int, Dict[str, Any]] = {}
        self._rows: Dict[int, int] = {r: 0 for r in self.actor_ranks}
        self._messages = 0
        self._bytes = 0
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._depth_sum = 0.0
        self._depth_polls = 0
        self._depth_max = 0
        self._started_at: Optional[float] = None
        # dataflow lineage (howto/observability.md "Tracing the dataflow"):
        # sampled-row ages over the buffer's retained span, per-message ingest
        # latency (drain − born, bounded reservoir), and each actor's last
        # reported acting weight version (the learner-side lag source)
        try:
            capacity = int(rb.buffer_size) * int(rb.n_envs)
        except (AttributeError, TypeError, ValueError):
            capacity = None
        self._ages = _AgeBook(capacity)
        self._ingest_latency_s: List[Tuple[int, float]] = []  # (rows, seconds)
        self._actor_weight_version: Dict[int, int] = {}

    # -- draining ----------------------------------------------------------------

    def _frontier(self) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for key, value in self.kv.dir(f"{self.ns}/ing/pub/"):
            name = key.rsplit("/", 1)[-1]
            if name.startswith("r"):
                try:
                    out[int(name[1:])] = int(value)
                except (TypeError, ValueError):
                    continue
        return out

    def _fetch(self, rank: int, seq: int) -> Optional[Dict[str, Any]]:
        tag = f"{self.ns}/ing/a{rank}/{seq}"
        n_raw = self.kv.get(f"{tag}/n")
        if n_raw is None:
            return None
        chunks = []
        for i in range(int(n_raw)):
            chunk = self.kv.get_bytes(f"{tag}/c{i}")
            if chunk is None:  # the frontier said complete; transient KV lag
                return None
            chunks.append(chunk)
        payload = pickle.loads(b"".join(chunks))
        self._bytes += sum(len(c) for c in chunks)
        self.kv.delete(tag + "/")
        return payload

    def drain_once(self) -> int:
        """One drain pass over every actor stream; returns rows ingested. Called
        by the ingest thread (or directly in tests/synchronous callers)."""
        frontier = self._frontier()
        ingested = 0
        depth = sum(
            max(frontier.get(r, -1) + 1 - self._consumed[r], 0) for r in self.actor_ranks
        )
        self._depth_sum += depth
        self._depth_polls += 1
        self._depth_max = max(self._depth_max, depth)
        for rank in self.actor_ranks:
            latest = frontier.get(rank, -1)
            while self._consumed[rank] <= latest:
                message = self._fetch(rank, self._consumed[rank])
                if message is None:
                    break
                rows = message["rows"]
                env_ids = message.get("env_ids")
                if env_ids is None and self._env_ids_of is not None:
                    env_ids = self._env_ids_of(rank)
                with self.lock:
                    if env_ids is not None:
                        self.rb.add(dict(rows), list(env_ids), validate_args=self._validate_args)
                    else:
                        self.rb.add(dict(rows), validate_args=self._validate_args)
                first = next(iter(rows.values()))
                n_rows = int(
                    first.shape[0] * (len(env_ids) if env_ids is not None else first.shape[1])
                )
                self._rows[rank] += n_rows
                ingested += n_rows
                self._messages += 1
                born = message.get("born")
                self._ages.record(n_rows, born)
                if born is not None:
                    self._ingest_latency_s.append((n_rows, max(time.time() - float(born), 0.0)))
                    if len(self._ingest_latency_s) > 4096:
                        del self._ingest_latency_s[:2048]
                if message.get("weight_version") is not None:
                    self._actor_weight_version[rank] = int(message["weight_version"])
                self._consumed[rank] += 1
                self.kv.set(f"{self.ns}/ing/ack/r{rank}", str(self._consumed[rank]))
        # end-of-stream markers (poll AFTER draining so eos with a drained
        # backlog really means "everything this actor ever sent is in the buffer")
        for key, value in self.kv.dir(f"{self.ns}/ing/eos/"):
            name = key.rsplit("/", 1)[-1]
            if name.startswith("r"):
                try:
                    self._eos[int(name[1:])] = json.loads(value)
                except (TypeError, ValueError):
                    self._eos[int(name[1:])] = {}
        return ingested

    def _ingest_loop(self) -> None:
        try:
            while not self._stop.is_set():
                if self.drain_once() == 0:
                    self._stop.wait(self.poll_s)
        except BaseException as exc:  # surface on the learner thread
            self._error = exc

    # -- lifecycle / learner API -------------------------------------------------

    def start(self) -> "ExperienceService":
        if self._thread is None:
            self._started_at = time.perf_counter()
            self._thread = threading.Thread(
                target=self._ingest_loop, name="experience-ingest", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        self.raise_pending()

    def raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise ServiceError("experience ingest thread failed") from err

    def mark_done(self) -> None:
        """Publish the run's done marker (the actors' exit gate)."""
        self.kv.set(f"{self.ns}/done", "1")

    @property
    def rows_total(self) -> int:
        return sum(self._rows.values())

    def rows_of(self, rank: int) -> int:
        return self._rows.get(int(rank), 0)

    def eos_all(self) -> bool:
        """Every actor published end-of-stream AND its backlog is fully drained."""
        if set(self._eos) != set(self.actor_ranks):
            return False
        frontier = self._frontier()
        return all(self._consumed[r] > frontier.get(r, -1) for r in self.actor_ranks)

    def eos_preempted(self) -> bool:
        return any(bool(e.get("preempted")) for e in self._eos.values())

    def row_ages(self) -> Optional[Dict[str, Any]]:
        """Sampled-row age distribution (seconds and add-rounds) over what the
        buffer currently retains; None before the first lineage-stamped row."""
        return self._ages.age_snapshot()

    def ingest_latency(self) -> Optional[Dict[str, float]]:
        """Row-weighted env→buffer latency percentiles in SECONDS (born stamp →
        drain) over a bounded recent reservoir."""
        return _weighted_percentiles(list(self._ingest_latency_s))

    def actor_weight_versions(self) -> Dict[int, int]:
        """Each actor's last reported acting weight version (from the ingest
        messages) — the learner computes per-actor lag against the publisher."""
        return dict(self._actor_weight_version)

    def telemetry_snapshot(self) -> Dict[str, Any]:
        elapsed = (
            time.perf_counter() - self._started_at if self._started_at is not None else None
        )
        return {
            "rows": self.rows_total,
            "rows_per_actor": {str(r): self._rows[r] for r in self.actor_ranks},
            "messages": self._messages,
            "bytes": self._bytes,
            "rows_per_sec": (
                round(self.rows_total / elapsed, 2) if elapsed and elapsed > 0 else None
            ),
            "queue_depth_mean": (
                round(self._depth_sum / self._depth_polls, 3) if self._depth_polls else 0.0
            ),
            "queue_depth_max": self._depth_max,
            "eos": sorted(self._eos),
        }


# ---------------------------------------------------------------------------------
# Weight plane: learner publishes, actors poll
# ---------------------------------------------------------------------------------


class WeightPublisher:
    """Version-keyed weight publication. Payloads are immutable once written
    (``{ns}/w/{v}/c{i}`` + ``n``), the ``latest`` pointer commits a version, and
    versions ``<= v-2`` are GC'd — a reader holding ``latest`` therefore always
    fetches complete chunks (a very late reader whose version was GC'd simply
    re-polls ``latest``). Non-blocking for the learner."""

    def __init__(self, kv: Any, ns: str) -> None:
        self.kv = kv
        self.ns = ns
        self.version = 0
        self._tele_bytes = 0

    def publish(self, tree: Any, final: bool = False) -> int:
        self.version += 1
        payload = pickle.dumps({"version": self.version, "final": bool(final), "tree": tree})
        tag = f"{self.ns}/w/{self.version}"
        n = max(1, -(-len(payload) // _KV_CHUNK))
        for i in range(n):
            self.kv.set_bytes(f"{tag}/c{i}", payload[i * _KV_CHUNK : (i + 1) * _KV_CHUNK])
        self.kv.set(f"{tag}/n", str(n))
        self.kv.set(f"{self.ns}/w/latest", str(self.version))
        if self.version > 2:
            self.kv.delete(f"{self.ns}/w/{self.version - 2}/")
        self._tele_bytes += len(payload)
        return self.version

    def telemetry_snapshot(self) -> Dict[str, Any]:
        return {"version": self.version, "bytes": self._tele_bytes}


class WeightSubscriber:
    """Actor-side weight reader: ``poll()`` is non-blocking (None when nothing
    newer than the held version exists), ``wait(min_version)`` blocks bounded
    for the first publication (abort-checked, so a dead learner breaks the wait
    instead of hanging the actor)."""

    def __init__(
        self,
        kv: Any,
        ns: str,
        *,
        poll_s: float = 0.05,
        timeout_s: float = 1800.0,
        abort_check: Optional[Callable[[], None]] = None,
    ) -> None:
        self.kv = kv
        self.ns = ns
        self.version = 0
        # newest version OBSERVED on the plane (>= self.version): held vs latest
        # is this actor's weight lag, honest even when the actor never fetches
        self.latest = 0
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self.abort_check = abort_check

    def _fetch(self, version: int) -> Optional[Dict[str, Any]]:
        tag = f"{self.ns}/w/{version}"
        n_raw = self.kv.get(f"{tag}/n")
        if n_raw is None:
            return None
        chunks = []
        for i in range(int(n_raw)):
            chunk = self.kv.get_bytes(f"{tag}/c{i}")
            if chunk is None:
                return None  # GC raced a very late read: re-poll latest
            chunks.append(chunk)
        payload = pickle.loads(b"".join(chunks))
        return payload if payload.get("version") == version else None

    def peek_latest(self) -> int:
        """Read (and remember) the newest published version WITHOUT fetching a
        payload — the lag probe for actors that are not refreshing this tick."""
        latest_raw = self.kv.get(f"{self.ns}/w/latest")
        if latest_raw is not None:
            self.latest = max(self.latest, int(latest_raw))
        return self.latest

    def poll(self) -> Optional[Dict[str, Any]]:
        latest = self.peek_latest()
        if latest <= self.version:
            return None
        payload = self._fetch(latest)
        if payload is None:
            return None
        self.version = latest
        return payload

    def telemetry_snapshot(self) -> Dict[str, Any]:
        return {
            "version": int(self.version),
            "latest": int(self.latest),
            "lag": max(int(self.latest) - int(self.version), 0),
        }

    def wait(self, min_version: int = 1, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        def pred() -> Optional[Dict[str, Any]]:
            payload = self.poll()
            if self.version >= min_version:
                return payload if payload is not None else {"version": self.version}
            return None

        return _bounded_wait(
            pred,
            timeout_s=float(timeout_s if timeout_s is not None else self.timeout_s),
            poll_s=self.poll_s,
            abort_check=self.abort_check,
            what=f"weight version >= {min_version}",
        )


# ---------------------------------------------------------------------------------
# Dataflow observability providers: what RunTelemetry.attach_dataflow consumes.
# One snapshot per telemetry window — the `dataflow` block on window/summary
# events and the Service/* gauges (obs/telemetry.py) read straight from these,
# no second bookkeeping path.
# ---------------------------------------------------------------------------------


class ActorDataflow:
    """The actor role's dataflow view: its ingestion counters (writer) and its
    weight staleness (held vs newest published — ``peek_latest`` keeps the lag
    honest even for an actor that never refreshes)."""

    role = "actor"

    def __init__(self, writer: ExperienceWriter, subscriber: WeightSubscriber) -> None:
        self._writer = writer
        self._subscriber = subscriber

    def dataflow_snapshot(self) -> Dict[str, Any]:
        try:
            self._subscriber.peek_latest()
        except Exception:
            pass  # a dying coordinator must not take the telemetry window down
        w = self._writer.telemetry_snapshot()
        s = self._subscriber.telemetry_snapshot()
        return {
            "role": "actor",
            "weight_version": s["version"],
            "weight_latest": s["latest"],
            "weight_lag": s["lag"],
            "rows": w["rows"],
            "messages": w["messages"],
            "inflight": w["inflight"],
            "flow_block_seconds": w["flow_block_seconds"],
        }


class LearnerDataflow:
    """The learner role's dataflow view: ingest latency + sampled-row ages from
    the service's lineage trail, queue depth, and per-actor weight lag against
    the publisher's current version."""

    role = "learner"

    def __init__(self, service: ExperienceService, publisher: WeightPublisher) -> None:
        self._service = service
        self._publisher = publisher

    def dataflow_snapshot(self) -> Dict[str, Any]:
        snap = self._service.telemetry_snapshot()
        current = int(self._publisher.version)
        versions = self._service.actor_weight_versions()
        lags = {str(r): max(current - v, 0) for r, v in sorted(versions.items())}
        latency = self._service.ingest_latency()
        return {
            "role": "learner",
            "weight_version": current,
            "weight_lag": (
                {
                    "per_actor": lags,
                    "max": max(lags.values()),
                    "mean": round(sum(lags.values()) / len(lags), 3),
                }
                if lags
                else None
            ),
            "row_age": self._service.row_ages(),
            "ingest_latency_ms": (
                {k: round(v * 1000.0, 3) for k, v in latency.items()} if latency else None
            ),
            "queue_depth": snap["queue_depth_mean"],
            "queue_depth_max": snap["queue_depth_max"],
            "rows": snap["rows"],
            "rows_per_actor": snap["rows_per_actor"],
            "rows_per_sec": snap["rows_per_sec"],
        }
