from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
    get_tensor,
)
from sheeprl_tpu.data.prefetch import (
    ReplaySamplePrefetcher,
    SyncReplaySampler,
    make_replay_sampler,
)

__all__ = [
    "EnvIndependentReplayBuffer",
    "EpisodeBuffer",
    "ReplayBuffer",
    "ReplaySamplePrefetcher",
    "SequentialReplayBuffer",
    "SyncReplaySampler",
    "get_tensor",
    "make_replay_sampler",
]
