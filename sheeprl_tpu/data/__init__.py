from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
    get_tensor,
)
from sheeprl_tpu.data.device_ring import (
    DeviceRingSampler,
    buffer_to_ring,
    ring_capacity,
    ring_init,
    ring_sample,
    ring_to_buffer,
    ring_write,
)
from sheeprl_tpu.data.prefetch import (
    ReplaySamplePrefetcher,
    SyncReplaySampler,
    make_replay_sampler,
)
from sheeprl_tpu.data.service import (
    ExperienceService,
    ExperienceWriter,
    WeightPublisher,
    WeightSubscriber,
    service_layout,
    service_options,
)

__all__ = [
    "DeviceRingSampler",
    "EnvIndependentReplayBuffer",
    "EpisodeBuffer",
    "ExperienceService",
    "ExperienceWriter",
    "ReplayBuffer",
    "ReplaySamplePrefetcher",
    "SequentialReplayBuffer",
    "SyncReplaySampler",
    "WeightPublisher",
    "WeightSubscriber",
    "buffer_to_ring",
    "get_tensor",
    "make_replay_sampler",
    "ring_capacity",
    "ring_init",
    "ring_sample",
    "ring_to_buffer",
    "ring_write",
    "service_layout",
    "service_options",
]
