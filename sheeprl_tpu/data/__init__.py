from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
    get_tensor,
)
from sheeprl_tpu.data.prefetch import (
    ReplaySamplePrefetcher,
    SyncReplaySampler,
    make_replay_sampler,
)
from sheeprl_tpu.data.service import (
    ExperienceService,
    ExperienceWriter,
    WeightPublisher,
    WeightSubscriber,
    service_layout,
    service_options,
)

__all__ = [
    "EnvIndependentReplayBuffer",
    "EpisodeBuffer",
    "ExperienceService",
    "ExperienceWriter",
    "ReplayBuffer",
    "ReplaySamplePrefetcher",
    "SequentialReplayBuffer",
    "SyncReplaySampler",
    "WeightPublisher",
    "WeightSubscriber",
    "get_tensor",
    "make_replay_sampler",
    "service_layout",
    "service_options",
]
