from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
    get_tensor,
)

__all__ = [
    "EnvIndependentReplayBuffer",
    "EpisodeBuffer",
    "ReplayBuffer",
    "SequentialReplayBuffer",
    "get_tensor",
]
