"""Device-resident replay ring: the on-mesh experience plane (``buffer.backend=device``).

Every host-replay off-policy loop pays one host→device round trip per
environment step (write) plus one per train round (sample + ``device_put``) —
the structural bound PERF_ANALYSIS.md identifies once train programs are fast,
and the boundary the Podracer architectures (arxiv 2104.06272) and MindSpeed RL
(arxiv 2507.19017) both erase by keeping the RL stages device-resident. This
module puts the replay buffer itself ON the mesh:

- the ring is a plain donated pytree ``{"data": {key: [capacity, n_envs, ...]},
  "pos": int32, "fill": int32}``, sharded ``P(None, "data")`` over the env axis
  on multi-device fabrics (same env-axis split as the Anakin rollout state);
- :func:`ring_write` is a pure in-program wraparound write — mod-``capacity``
  scatter at the carried cursor, cursor + fill count carried in the pytree — so
  a fused rollout can append its ``[T, E, ...]`` trajectory without the host;
- :func:`ring_sample` draws a ``[n_samples, batch, ...]`` block uniformly over
  the valid region using the Feistel :func:`~sheeprl_tpu.utils.prp.prp_permutation`
  (``utils/prp.py``): ONE O(slots) bijective index shuffle per call, so a
  full ring is sampled uniformly *without replacement* — no sort, no rejection
  loop, nothing that cannot live inside a jit.

The host-facing :class:`DeviceRingSampler` exposes the exact
``make_replay_sampler`` surface (``add`` / ``sample`` / ``lock`` / ``buffer`` /
``telemetry_snapshot`` / ``close``) over the ring, with a host
:class:`~sheeprl_tpu.data.buffers.ReplayBuffer` as the durability twin:
``sync_to_host()`` snapshots the ring into it at checkpoint cadence (cursor and
fill included, so ``rb._pos``/``rb._full`` round-trip), and
``restore_from_host()`` is the resume path — one ``device_put`` of the
snapshot back onto the mesh. ``local`` and ``service`` remain the
checkpoint-durable compatibility backends; the ring is the fused-topology hot
path (``algos/sac/anakin.py``).

Shape contract: ``capacity * n_envs`` (the slot count) must be a power of two —
that is what makes the Feistel shuffle a bijection. The capacity helper
:func:`ring_capacity` rounds a requested transition budget UP to the nearest
compliant row count, so ``buffer.size`` keeps its usual "at least this many
transitions" meaning.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Mapping, Optional, Tuple

import numpy as np

from sheeprl_tpu.utils.prp import prp_permutation

__all__ = [
    "DeviceRingSampler",
    "ring_capacity",
    "ring_init",
    "ring_sample",
    "ring_to_buffer",
    "ring_write",
    "buffer_to_ring",
]


def _next_pow2(n: int) -> int:
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def ring_capacity(size: int, n_envs: int) -> int:
    """Rows per env for a total transition budget of ``size``: at least
    ``ceil(size / n_envs)``, rounded up so ``capacity * n_envs`` is a power of
    two (the :func:`ring_sample` bijection constraint). ``n_envs`` itself must
    be a power of two — with any other env count no row count can make the slot
    count compliant."""
    if n_envs < 1 or (n_envs & (n_envs - 1)):
        raise ValueError(
            f"buffer.backend=device needs a power-of-two env count, got {n_envs}; "
            "the Feistel sampler permutes capacity*n_envs slots and a bijection "
            "needs a power-of-two domain (see howto/device_replay.md)"
        )
    rows = -(-int(size) // int(n_envs))  # ceil
    return max(_next_pow2(rows * n_envs) // n_envs, 1)


def ring_init(
    capacity: int,
    n_envs: int,
    row_specs: Mapping[str, Tuple[Tuple[int, ...], Any]],
    sharding: Any = None,
) -> Dict[str, Any]:
    """Allocate an empty ring: ``row_specs`` maps key -> (per-env trailing
    shape, dtype). ``sharding`` (a ``P(None, "data")`` NamedSharding) lands the
    storage env-sharded over the mesh at init — the donated carry then stays
    put for the life of the run."""
    import jax
    import jax.numpy as jnp

    slots = int(capacity) * int(n_envs)
    if slots < 2 or (slots & (slots - 1)):
        raise ValueError(
            f"device ring needs a power-of-two slot count (capacity*n_envs), got "
            f"{capacity}*{n_envs}={slots}; use ring_capacity() to round the budget up"
        )
    data = {
        k: jnp.zeros((int(capacity), int(n_envs), *shape), dtype=dtype)
        for k, (shape, dtype) in row_specs.items()
    }
    if sharding is not None:
        data = jax.device_put(data, sharding)
    return {"data": data, "pos": jnp.int32(0), "fill": jnp.int32(0)}


def ring_write(ring: Dict[str, Any], rows: Mapping[str, Any]) -> Dict[str, Any]:
    """Pure wraparound append of a ``[T, n_envs, ...]`` block at the carried
    cursor (jit-safe; ``T`` is static from the block shape). Oversize blocks
    keep their trailing ``capacity`` rows — the same overwrite semantics as the
    host :class:`~sheeprl_tpu.data.buffers.ReplayBuffer.add`."""
    import jax.numpy as jnp

    data = ring["data"]
    first = next(iter(rows.values()))
    steps = int(first.shape[0])
    capacity = int(next(iter(data.values())).shape[0])
    if steps > capacity:
        rows = {k: v[-capacity:] for k, v in rows.items()}
        steps = capacity
    idx = (ring["pos"] + jnp.arange(steps, dtype=jnp.int32)) % capacity
    new_data = {k: data[k].at[idx].set(rows[k].astype(data[k].dtype)) for k in data}
    return {
        "data": new_data,
        "pos": (ring["pos"] + steps) % capacity,
        "fill": jnp.minimum(ring["fill"] + steps, capacity),
    }


def ring_sample(
    ring: Dict[str, Any], key: Any, batch_size: int, n_samples: int = 1
) -> Dict[str, Any]:
    """Uniform ``[n_samples, batch_size, ...]`` draw over the valid region.

    One Feistel permutation of ALL ``capacity * n_envs`` slots per call, of
    which the first ``n_samples * batch_size`` entries are taken and folded
    into the filled region by a modulo. On a full ring the fold is the
    identity, so the draw is exactly uniform **without replacement** (a
    bijection of the slot space); during the fill ramp each filled slot is hit
    with multiplicity within ±1 of uniform. Draws larger than the slot count
    wrap around the permutation (with-replacement across wraps)."""
    import jax.numpy as jnp

    data = ring["data"]
    ref = next(iter(data.values()))
    capacity, n_envs = int(ref.shape[0]), int(ref.shape[1])
    slots = capacity * n_envs
    n = int(n_samples) * int(batch_size)
    if n <= 0:
        raise ValueError(f"n_samples*batch_size must be > 0, got {n}")
    perm = prp_permutation(key, slots)
    flat_idx = perm[jnp.arange(n) % slots]
    # valid slots are the first fill*n_envs of the row-major flat layout: before
    # the first wrap pos == fill (prefix rows), after it fill == capacity (all)
    valid = jnp.maximum(ring["fill"], 1) * n_envs
    flat_idx = (flat_idx % valid).astype(jnp.int32)
    out: Dict[str, Any] = {}
    for k, v in data.items():
        flat = v.reshape(slots, *v.shape[2:])
        taken = jnp.take(flat, flat_idx, axis=0)
        out[k] = taken.reshape(int(n_samples), int(batch_size), *v.shape[2:])
    return out


def ring_to_buffer(ring: Dict[str, Any], rb: Optional[Any] = None) -> Any:
    """Snapshot the ring into a host :class:`ReplayBuffer` (ONE device→host pull
    per key) with the write cursor and fill state mapped onto ``rb._pos`` /
    ``rb._full`` — the checkpoint-durability bridge: the snapshot pickles
    through the existing ``_ckpt_rb`` protocol exactly like a host-replay run."""
    from sheeprl_tpu.data.buffers import ReplayBuffer

    data = {k: np.asarray(v) for k, v in ring["data"].items()}
    ref = next(iter(data.values()))
    capacity, n_envs = int(ref.shape[0]), int(ref.shape[1])
    if rb is None:
        rb = ReplayBuffer(capacity, n_envs, obs_keys=("observations",), memmap=False)
    fill = int(ring["fill"])
    rb._buf = {k: v.copy() for k, v in data.items()}
    rb._pos = int(ring["pos"])
    rb._full = fill >= capacity
    return rb


def buffer_to_ring(rb: Any, sharding: Any = None) -> Dict[str, Any]:
    """Resume path: ``device_put`` a host :class:`ReplayBuffer` snapshot back
    onto the mesh as a ring, cursor and fill intact."""
    import jax
    import jax.numpy as jnp

    data = {k: np.asarray(v) for k, v in rb.buffer.items()}
    if sharding is not None:
        data = jax.device_put(data, sharding)
    else:
        data = {k: jnp.asarray(v) for k, v in data.items()}
    capacity = int(rb.buffer_size)
    fill = capacity if rb.full else int(rb._pos)
    return {"data": data, "pos": jnp.int32(int(rb._pos) % capacity), "fill": jnp.int32(fill)}


class DeviceRingSampler:
    """``buffer.backend=device`` behind the ``make_replay_sampler`` surface.

    The replay storage is the device ring; the wrapped host
    :class:`ReplayBuffer` is only the durability twin (checkpoint snapshot /
    resume restore). ``add``/``sample`` run as small jitted device programs —
    useful for tests and non-fused loops; the fused ``sac_anakin`` topology
    bypasses them entirely by carrying ``self.ring`` through its own donated
    program and rebinding it (:attr:`ring` is plain mutable state).
    """

    is_async = False

    def __init__(
        self,
        rb: Any,
        sample_kwargs: Optional[Mapping[str, Any]] = None,
        sharding: Any = None,
        lock: Optional[threading.Lock] = None,
        seed: int = 0,
        **_: Any,
    ) -> None:
        import jax

        self._rb = rb
        self._sample_kwargs = dict(sample_kwargs or {})
        self._sample_kwargs.pop("n_samples", None)
        if self._sample_kwargs.pop("sample_next_obs", False):
            raise ValueError(
                "buffer.backend=device stores next_observations explicitly; "
                "sample_next_obs=True is a host-replay feature (buffer.sample_next_obs=False)"
            )
        self._batch_size = int(self._sample_kwargs.pop("batch_size"))
        self._sharding = sharding
        self.lock = lock or threading.Lock()
        self.ring: Optional[Dict[str, Any]] = None
        self._key = jax.random.PRNGKey(seed)
        self._write = jax.jit(ring_write, donate_argnums=(0,))
        self._sample = jax.jit(ring_sample, static_argnames=("batch_size", "n_samples"))
        self._tele_wait_seconds = 0.0
        self._tele_sample_calls = 0
        self._tele_units = 0
        self._tele_rows_written = 0
        if not rb.empty:
            # a restored (resume_from) buffer re-lands on the mesh immediately
            self.ring = buffer_to_ring(rb, sharding=sharding)

    # -- sampler surface ---------------------------------------------------------------

    @property
    def buffer(self) -> Any:
        return self._rb

    def add(self, data: Mapping[str, Any], *args: Any, **kwargs: Any) -> None:
        rows = {k: np.asarray(v) for k, v in data.items()}
        if self.ring is None:
            first = next(iter(rows.values()))
            n_envs = int(first.shape[1])
            specs = {k: (tuple(v.shape[2:]), v.dtype) for k, v in rows.items()}
            self.ring = ring_init(self._rb.buffer_size, n_envs, specs, sharding=self._sharding)
        self.note_writes(int(next(iter(rows.values())).shape[0]))
        self.ring = self._write(self.ring, rows)

    def note_writes(self, steps: int) -> None:
        """Account ``steps`` ring rows written. ``add`` self-accounts; the fused
        topologies that bypass it (``sac_anakin`` carries the ring through its
        own donated program and rebinds :attr:`ring`) call this once per
        iteration so the overwrite gauge stays honest — pure host bookkeeping,
        no device sync."""
        self._tele_rows_written += max(int(steps), 0)

    def sample(self, n_samples: int) -> Dict[str, Any]:
        import jax
        import time

        if self.ring is None:
            raise RuntimeError("No sample has been added to the device ring; call add() first")
        t0 = time.perf_counter()
        self._key, sample_key = jax.random.split(self._key)
        block = self._sample(
            self.ring, sample_key, batch_size=self._batch_size, n_samples=int(n_samples)
        )
        self._tele_wait_seconds += time.perf_counter() - t0
        self._tele_sample_calls += 1
        self._tele_units += int(n_samples)
        return block

    def telemetry_snapshot(self) -> Dict[str, Any]:
        """Same schema as the host samplers' — the sync-path semantics apply
        (the consumer blocks for the full sample dispatch) — plus the ring
        storage gauges: ``ring_fill``/``ring_capacity`` (occupancy in rows) and
        the cumulative ``ring_overwritten`` slot count (rows written past
        capacity × envs — experience lost to wraparound). Reading ``fill``
        costs one device sync; this runs at telemetry-window cadence, not on
        the hot path."""
        snap = {
            "is_async": False,
            "wait_seconds": self._tele_wait_seconds,
            "sample_calls": self._tele_sample_calls,
            "units": self._tele_units,
            "occupancy_sum": 0.0,
            "staleness_sum": 0.0,
            "empty_waits": 0,
            "pipeline_len": 0,
            "depth": 0,
            "ring_fill": 0,
            "ring_capacity": 0,
            "ring_overwritten": 0,
        }
        if self.ring is not None:
            ref = next(iter(self.ring["data"].values()))
            capacity, n_envs = int(ref.shape[0]), int(ref.shape[1])
            snap["ring_fill"] = int(self.ring["fill"])
            snap["ring_capacity"] = capacity
            snap["ring_overwritten"] = max(self._tele_rows_written - capacity, 0) * n_envs
        return snap

    def close(self) -> None:
        pass

    def __enter__(self) -> "DeviceRingSampler":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- durability bridge -------------------------------------------------------------

    def sync_to_host(self) -> Any:
        """Snapshot the live ring into the wrapped host buffer (checkpoint
        cadence); returns the buffer for the checkpoint callback."""
        if self.ring is not None:
            ring_to_buffer(self.ring, self._rb)
        return self._rb

    def restore_from_host(self) -> None:
        """Re-land the host snapshot on the mesh (resume path)."""
        if not self._rb.empty:
            self.ring = buffer_to_ring(self._rb, sharding=self._sharding)
