"""Async replay-sample prefetch pipeline (host→device dataflow overlap).

Every off-policy loop samples its next ``[G, ...]`` replay block from the host-side
numpy buffer and stages it on the accelerator. Done inline, that gather + `device_put`
is serialized with both env stepping and device compute — exactly the first-order
overlap lever the Podracer architectures (arxiv 2104.06272) and MindSpeed RL
(arxiv 2507.19017) identify for accelerator-resident RL. This module moves it onto a
background thread:

- :class:`ReplaySamplePrefetcher` keeps a pipeline of single-gradient-step **units**
  (``n_samples=1`` sample blocks) staged — sampled, host-cast by ``transform`` and
  landed on the device/mesh via ``sharding`` — so the next train round's block is
  already device-resident when the current train round retires. ``sample(G)`` pops
  ``G`` units and concatenates them (device-side when staged sharded). The pipeline
  length adapts to the units consumed per add-round (capped at ``_MAX_PIPELINE``) so
  a loop that pops more than ``depth`` units per round — in one call or several —
  never serializes on the worker, while a one-off burst can't park a huge pipeline.
  During long no-train stretches the pipeline shrinks to one hot unit (one refresh
  gather per ``depth + 1`` buffer writes) instead of churning blocks nobody pops.
- :class:`SyncReplaySampler` is the ``prefetch.enabled=false`` fallback: the EXACT
  inline code path the loops used before (one ``rb.sample(n_samples=G)`` call, host
  cast, one ``device_put``).

Bounded-staleness contract
--------------------------
``add()`` counts *add-rounds*. Every unit records the add-round at which its sample
command was issued; ``add()`` evicts (and, for one hot unit, schedules the
replacement of) any staged unit whose issue round lags the buffer by more than
``depth`` add-rounds. Because the worker samples **at or after** the issue round and
rounds only advance in ``add()``, every block returned by ``sample()`` was sampled
from a buffer state **at most ``depth`` add-rounds behind** the live buffer.
``last_sampled_rounds`` exposes the actual per-unit sample rounds for tests.

Determinism
-----------
Sample commands are issued ONLY by the loop thread (in ``sample()`` and the eviction
path of ``add()``) and executed in FIFO order by the single worker, so the buffer's
RNG is consumed in a reproducible order for a fixed sequence of ``add``/``sample``
calls. Note the prefetcher draws per-unit (``n_samples=1`` × G) while the sync path
draws one ``n_samples=G`` block, so the two paths consume the RNG differently: they
are distributionally identical but not index-identical on a live run. On a frozen
buffer the prefetcher is bit-identical to the same per-unit calls run inline (see
tests/test_data/test_prefetch.py).

Thread safety: ``add()`` mutates the buffer and the worker gathers from it under the
shared ``lock``, so a unit is never a torn read of a half-written row. Hold the same
``lock`` around anything else that must see a quiescent buffer — the loops take it
around replay-buffer checkpoint serialization so the pickled RNG/storage state is
not a torn mid-sample read. Worker exceptions re-raise in the loop thread from
``sample()``/``add()``/``close()``. The worker holds no reference to the sampler
object itself, so an abandoned pipeline (a loop that crashed past ``close()``) is
shut down by ``__del__`` as soon as the sampler is garbage collected.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Mapping, Optional, Sequence

import numpy as np

__all__ = ["ReplaySamplePrefetcher", "SyncReplaySampler", "make_replay_sampler"]

_SENTINEL = object()

# hard cap on the adaptive pipeline length: beyond this the worker keeps up by
# producing during the round anyway, and staged blocks are device memory
_MAX_PIPELINE = 16


def _stage(block: Dict[str, np.ndarray], sharding: Any) -> Dict[str, Any]:
    if sharding is None:
        return block
    import jax

    return jax.device_put(block, sharding)


def _concat_units(units: list, sharding: Any) -> Dict[str, Any]:
    if len(units) == 1:
        return units[0]
    if sharding is None:
        return {k: np.concatenate([u[k] for u in units], axis=0) for k in units[0]}
    import jax.numpy as jnp

    # device-side concat of identically-sharded [1, ...] units: the leading axis is
    # unsharded in every spec the loops pass, so GSPMD keeps the unit sharding
    return {k: jnp.concatenate([u[k] for u in units], axis=0) for k in units[0]}


def _uint8_transform(uint8_keys: Sequence[str]) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """The loops' shared host cast: image keys stay uint8 across the host→device
    boundary (4× less transfer; the jitted program normalizes on device), everything
    else lands float32. A key matches by exact name or a ``next_<name>`` twin."""
    keys = tuple(uint8_keys)

    def cast(s: Dict[str, Any]) -> Dict[str, Any]:
        return {
            k: (
                np.asarray(v)
                if any(k == u or k.endswith(f"_{u}") for u in keys)
                else np.asarray(v, dtype=np.float32)
            )
            for k, v in s.items()
        }

    return cast


class SyncReplaySampler:
    """``buffer.prefetch.enabled=false``: the exact pre-prefetch inline path.

    One ``rb.sample(n_samples=G)`` call on the loop thread, host ``transform``, one
    ``device_put`` when a ``sharding`` is given — byte-for-byte the code the
    off-policy loops ran before the pipeline existed.
    """

    is_async = False

    def __init__(
        self,
        rb: Any,
        sample_kwargs: Optional[Mapping[str, Any]] = None,
        transform: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]] = None,
        sharding: Any = None,
        lock: Optional[threading.Lock] = None,
        **_: Any,
    ) -> None:
        self._rb = rb
        self._sample_kwargs = dict(sample_kwargs or {})
        self._transform = transform
        self._sharding = sharding
        # everything runs on the loop thread; the lock exists so call sites can be
        # written uniformly against either sampler (e.g. checkpoint serialization)
        self.lock = lock or threading.Lock()
        # telemetry counters (same schema as the prefetcher's): with the sync path
        # the consumer is blocked for the WHOLE gather+cast+stage, so that full
        # duration is the honest "wait" — it is exactly what the async pipeline
        # overlaps away, which makes the on/off A/B legible from telemetry alone
        self._tele_wait_seconds = 0.0
        self._tele_sample_calls = 0
        self._tele_units = 0

    @property
    def buffer(self) -> Any:
        return self._rb

    def add(self, data: Any, *args: Any, **kwargs: Any) -> None:
        self._rb.add(data, *args, **kwargs)

    def sample(self, n_samples: int) -> Dict[str, Any]:
        t0 = time.perf_counter()
        block = self._rb.sample(n_samples=n_samples, **self._sample_kwargs)
        if self._transform is not None:
            block = self._transform(block)
        staged = _stage(block, self._sharding)
        self._tele_wait_seconds += time.perf_counter() - t0
        self._tele_sample_calls += 1
        self._tele_units += int(n_samples)
        return staged

    def telemetry_snapshot(self) -> Dict[str, Any]:
        """Cumulative consumer-side counters (see ReplaySamplePrefetcher's)."""
        return {
            "is_async": False,
            "wait_seconds": self._tele_wait_seconds,
            "sample_calls": self._tele_sample_calls,
            "units": self._tele_units,
            "occupancy_sum": 0.0,
            "staleness_sum": 0.0,
            "empty_waits": 0,
            "pipeline_len": 0,
            "depth": 0,
        }

    def close(self) -> None:
        pass

    def __enter__(self) -> "SyncReplaySampler":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def _worker_loop(commands, ready, lock, state, rb, sample_kwargs, transform, sharding) -> None:
    """Worker body. Deliberately a free function over plain collaborators — holding
    no reference to the sampler object — so a sampler abandoned without close()
    becomes garbage-collectable and its __del__ can stop this thread."""
    try:
        while True:
            cmd = commands.get()
            if cmd is _SENTINEL:
                return
            with lock:
                sampled_round = state["round"]
                unit = rb.sample(n_samples=1, **sample_kwargs)
            if transform is not None:
                unit = transform(unit)
            unit = _stage(unit, sharding)
            ready.put((unit, sampled_round))
    except BaseException as e:  # propagate to the loop thread
        state["error"] = e
        ready.put(_SENTINEL)  # wake a blocked sample()


class ReplaySamplePrefetcher:
    """Background-thread replay sampling + sharded device staging, depth-buffered.

    See the module docstring for the pipeline, staleness and determinism contracts.

    Args:
        rb: any buffer exposing ``add(data, ...)`` and
            ``sample(n_samples=..., **sample_kwargs)``.
        sample_kwargs: fixed kwargs of every unit sample (batch_size,
            sequence_length, sample_next_obs, ...). ``n_samples`` is always 1.
        transform: host-side cast applied to each unit dict before staging.
        sharding: ``jax.sharding.Sharding`` / device for staging; None keeps units
            host-side (the decoupled data plane ships host blocks).
        depth: minimum staged units kept ahead (2 = double buffering, ...), and the
            staleness bound in add-rounds; the pipeline grows to the per-round
            consumption when that exceeds ``depth``.
        lock: optional externally shared mutex serializing buffer writes against
            worker gathers (pass one lock to several prefetchers over one buffer).
    """

    is_async = True

    def __init__(
        self,
        rb: Any,
        sample_kwargs: Optional[Mapping[str, Any]] = None,
        transform: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]] = None,
        sharding: Any = None,
        depth: int = 2,
        lock: Optional[threading.Lock] = None,
        name: str = "replay-prefetch",
    ) -> None:
        if depth < 1:
            raise ValueError(f"'depth' must be >= 1, got {depth}")
        self._rb = rb
        self._sample_kwargs = dict(sample_kwargs or {})
        self._sample_kwargs.pop("n_samples", None)
        self._sharding = sharding
        self.depth = int(depth)
        self.lock = lock or threading.Lock()
        self._commands: "queue.Queue[Any]" = queue.Queue()
        self._ready: "queue.Queue[Any]" = queue.Queue()
        self._issue_rounds: deque = deque()  # issue round per in-flight/staged unit, FIFO
        # pipeline length follows the units consumed per add-round (droq pops G then
        # 1 more between two adds; SAC pops G=4), capped so a one-off burst (a
        # pretrain round popping 100) can't park a hundred staged blocks
        self._consumed_since_add = 0
        self._pending_discards = 0
        # shared with the worker (which must not reference `self`): the add-round
        # clock and the worker's pending exception
        self._state: Dict[str, Any] = {"round": 0, "error": None}
        self._closed = False
        self.last_sampled_rounds: list = []
        # telemetry counters, loop-thread only (read via telemetry_snapshot):
        # wait_seconds = time sample() spent blocked before its units were popped
        # (a starved pipeline shows up here), occupancy_sum = ready-queue depth
        # summed per sample() call, staleness_sum = add-rounds of lag summed per
        # popped unit (bounded by `depth` per the staleness contract)
        self._tele_wait_seconds = 0.0
        self._tele_sample_calls = 0
        self._tele_units = 0
        self._tele_occupancy_sum = 0.0
        self._tele_staleness_sum = 0.0
        self._tele_empty_waits = 0
        self._thread = threading.Thread(
            target=_worker_loop,
            args=(
                self._commands,
                self._ready,
                self.lock,
                self._state,
                rb,
                self._sample_kwargs,
                transform,
                sharding,
            ),
            daemon=True,
            name=name,
        )
        self._thread.start()

    # -- internals --------------------------------------------------------------------

    def _raise_pending(self) -> None:
        if self._state["error"] is not None:
            err, self._state["error"] = self._state["error"], None
            self._closed = True
            raise RuntimeError("replay prefetch worker failed") from err

    def _issue(self) -> None:
        self._issue_rounds.append(self._state["round"])
        self._commands.put(("produce", self._state["round"]))

    def _pop_ready(self):
        while True:
            self._raise_pending()
            try:
                item = self._ready.get(timeout=1.0)
            except queue.Empty:
                continue
            if item is _SENTINEL:
                self._raise_pending()
                raise RuntimeError("replay prefetch worker exited unexpectedly")
            return item

    # -- loop-thread API --------------------------------------------------------------

    @property
    def buffer(self) -> Any:
        return self._rb

    @property
    def add_round(self) -> int:
        """Add-rounds seen so far — the reference clock of the staleness contract."""
        return self._state["round"]

    def add(self, data: Any, *args: Any, **kwargs: Any) -> None:
        """Write to the buffer (one add-round) and evict units staged too long ago.

        Eviction keeps the staleness invariant: after this returns, every
        in-flight/staged unit was issued at most ``depth`` add-rounds ago, so any
        block later popped by ``sample()`` lags the buffer by at most ``depth``
        add-rounds (the worker samples at or after the issue round).
        """
        self._raise_pending()
        with self.lock:
            self._rb.add(data, *args, **kwargs)
            self._state["round"] += 1
        self._consumed_since_add = 0
        while self._issue_rounds and self._state["round"] - self._issue_rounds[0] > self.depth:
            self._issue_rounds.popleft()
            self._pending_discards += 1
            # during a no-train stretch (consumption paused, writes landing) keep ONE
            # hot unit staged instead of refreshing a full pipeline nobody pops —
            # sample() restores the pipeline as soon as training resumes
            if not self._issue_rounds:
                self._issue()
        # free parked memory early: drop discarded units the worker has already
        # produced (they sit at the head of the ready stream, in FIFO command order)
        while self._pending_discards:
            try:
                item = self._ready.get_nowait()
            except queue.Empty:
                break
            if item is _SENTINEL:
                self._ready.put(_SENTINEL)  # let _raise_pending surface the error
                break
            self._pending_discards -= 1

    def sample(self, n_samples: int) -> Dict[str, Any]:
        """Pop ``n_samples`` staged units as one ``[G, ...]`` block and refill.

        Blocks only for units the worker has not finished yet (first call, or a
        jump in ``n_samples``); the steady-state block is already staged.
        """
        if n_samples <= 0:
            raise ValueError(f"'n_samples' must be > 0, got {n_samples}")
        self._raise_pending()
        if self._closed:
            raise RuntimeError("sample() on a closed ReplaySamplePrefetcher")
        t0 = time.perf_counter()
        occupancy = self._ready.qsize()
        self._tele_occupancy_sum += occupancy
        if occupancy == 0:
            # hard-starvation event: the consumer arrived and NOTHING was staged
            # (the diagnosis engine's prefetch_starvation detector reads this —
            # wait_seconds alone cannot tell many tiny waits from full stalls)
            self._tele_empty_waits += 1
        # top up the logical stream so n_samples fresh units exist beyond discards
        while len(self._issue_rounds) < n_samples:
            self._issue()
        # stale units evicted by add() sit at the stream head, in FIFO order
        for _ in range(self._pending_discards):
            self._pop_ready()
        self._pending_discards = 0
        units, rounds = [], []
        for _ in range(n_samples):
            unit, sampled_round = self._pop_ready()
            units.append(unit)
            rounds.append(sampled_round)
            self._issue_rounds.popleft()
        self.last_sampled_rounds = rounds
        live_round = self._state["round"]
        self._tele_wait_seconds += time.perf_counter() - t0
        self._tele_sample_calls += 1
        self._tele_units += n_samples
        self._tele_staleness_sum += sum(live_round - r for r in rounds)
        # refill the pipeline for the next round, sized to the units consumed since
        # the last buffer write (covers multi-call rounds like droq's G + 1), capped
        # so a one-off burst doesn't provision a pipeline nobody will drain
        self._consumed_since_add += n_samples
        target = max(self.depth, min(self._consumed_since_add, _MAX_PIPELINE))
        while len(self._issue_rounds) < target:
            self._issue()
        return _concat_units(units, self._sharding)

    def telemetry_snapshot(self) -> Dict[str, Any]:
        """Cumulative consumer-side pipeline counters, diffed per telemetry
        window by ``RunTelemetry`` into ``Time/prefetch_wait`` /
        ``Buffer/pipeline_occupancy`` / ``Buffer/pipeline_staleness``. Loop-thread
        only (like ``sample``/``add``); ``qsize`` is the usual approximation."""
        return {
            "is_async": True,
            "wait_seconds": self._tele_wait_seconds,
            "sample_calls": self._tele_sample_calls,
            "units": self._tele_units,
            "occupancy_sum": self._tele_occupancy_sum,
            "staleness_sum": self._tele_staleness_sum,
            "empty_waits": self._tele_empty_waits,
            "pipeline_len": len(self._issue_rounds),
            "depth": self.depth,
        }

    def close(self) -> None:
        """Shut the worker down and surface any pending worker exception."""
        if self._closed:
            return
        self._closed = True
        self._commands.put(_SENTINEL)
        self._thread.join(timeout=60.0)
        self._raise_pending()

    def __enter__(self) -> "ReplaySamplePrefetcher":
        return self

    def __exit__(self, *exc: Any) -> None:
        # don't mask an in-flight exception with a worker teardown error
        try:
            self.close()
        except Exception:
            if not exc or exc[0] is None:
                raise

    def __del__(self) -> None:  # abandoned pipeline: stop the (self-reference-free) worker
        try:
            if not self._closed:
                self._closed = True
                self._commands.put(_SENTINEL)
        except Exception:
            pass


def make_replay_sampler(
    rb: Any,
    prefetch_cfg: Optional[Mapping[str, Any]] = None,
    *,
    sample_kwargs: Optional[Mapping[str, Any]] = None,
    transform: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]] = None,
    uint8_keys: Optional[Sequence[str]] = None,
    sharding: Any = None,
    lock: Optional[threading.Lock] = None,
    name: str = "replay-prefetch",
    backend: str = "local",
    seed: int = 0,
):
    """Build the hot-path replay sampler from the ``buffer.prefetch`` config group:
    a :class:`ReplaySamplePrefetcher` when ``enabled`` (the default), else the
    :class:`SyncReplaySampler` that restores the exact inline code path.

    ``backend="device"`` routes to the device-resident replay ring instead
    (:class:`~sheeprl_tpu.data.device_ring.DeviceRingSampler`, same surface):
    storage lives ON the mesh, ``rb`` becomes the checkpoint-durability twin,
    and the ``prefetch`` group is ignored (there is no host sample path to
    pipeline). ``local`` keeps the host samplers byte-for-byte unchanged.

    ``uint8_keys`` is a shorthand for the loops' standard cast (those keys — and
    their ``next_`` twins — stay uint8, the rest goes float32); pass ``transform``
    instead for anything custom. Without either, samples pass through unchanged.
    """
    if backend == "device":
        from sheeprl_tpu.data.device_ring import DeviceRingSampler

        if transform is not None or uint8_keys:
            raise ValueError("buffer.backend=device does not support host-side sample transforms")
        return DeviceRingSampler(rb, sample_kwargs, sharding=sharding, lock=lock, seed=seed)
    if transform is None and uint8_keys is not None:
        transform = _uint8_transform(uint8_keys)
    enabled = bool(prefetch_cfg.get("enabled", False)) if prefetch_cfg else False
    if not enabled:
        return SyncReplaySampler(rb, sample_kwargs, transform=transform, sharding=sharding, lock=lock)
    depth = int(prefetch_cfg.get("depth", 2))  # depth<1 rejected by the constructor
    return ReplaySamplePrefetcher(
        rb, sample_kwargs, transform=transform, sharding=sharding, depth=depth, lock=lock, name=name
    )
