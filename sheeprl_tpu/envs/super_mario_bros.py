"""Super Mario Bros adapter (capability parity with reference
sheeprl/envs/super_mario_bros.py:22-74; gym-super-mario-bros is optional).

Wraps the nes-py env in a joypad action set and converts the gym-0.x done flag to
terminated/truncated using the in-game timer.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_SUPER_MARIO_BROS_AVAILABLE

if not _IS_SUPER_MARIO_BROS_AVAILABLE:
    raise ModuleNotFoundError(
        "gym-super-mario-bros is not installed: pip install gym-super-mario-bros==7.4.0"
    )

from typing import Any, Dict, Optional

import gym_super_mario_bros as gsmb
import gymnasium as gym
import numpy as np
from gym_super_mario_bros.actions import COMPLEX_MOVEMENT, RIGHT_ONLY, SIMPLE_MOVEMENT
from nes_py.wrappers import JoypadSpace

ACTION_SPACE_MAP = {"simple": SIMPLE_MOVEMENT, "right_only": RIGHT_ONLY, "complex": COMPLEX_MOVEMENT}


class _JoypadSeedableReset(JoypadSpace):
    """nes-py's JoypadSpace drops reset kwargs; forward them (reference
    super_mario_bros.py:22-24)."""

    def reset(self, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        return self.env.reset(seed=seed, options=options)


class SuperMarioBrosWrapper(gym.Env):
    def __init__(self, id: str, action_space: str = "simple", render_mode: str = "rgb_array"):
        env = gsmb.make(id)
        self._env = _JoypadSeedableReset(env, ACTION_SPACE_MAP[action_space])
        self.render_mode = render_mode
        inner = env.observation_space
        self.observation_space = gym.spaces.Dict(
            {"rgb": gym.spaces.Box(inner.low, inner.high, inner.shape, inner.dtype)}
        )
        self.action_space = gym.spaces.Discrete(self._env.action_space.n)

    def step(self, action):
        if isinstance(action, np.ndarray):
            action = action.squeeze().item()
        obs, reward, done, info = self._env.step(action)
        # ``info["time"]`` is the in-game countdown clock: an episode is a time-limit
        # truncation only when the clock actually EXPIRED. (The reference wrapper
        # treats any nonzero clock as truncation — sheeprl/envs/super_mario_bros.py —
        # which mislabels deaths as truncated and skews value bootstrapping;
        # ADVICE round-2 flagged it, fixed here rather than preserved.)
        is_timelimit = info.get("time", 1) == 0
        return {"rgb": obs.copy()}, reward, done and not is_timelimit, done and is_timelimit, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs = self._env.reset(seed=seed, options=options)
        return {"rgb": obs.copy()}, {}

    def render(self):
        frame = self._env.render(mode=self.render_mode)
        if self.render_mode == "rgb_array" and frame is not None:
            return frame.copy()
        return None

    def close(self) -> None:
        self._env.close()
