"""Batched gridworld family in pure JAX (the JaxARC direction, PAPERS.md).

A family of NxN navigation tasks over a static wall layout: the agent starts at
a random free cell, a goal sits at another random free cell, actions are
up/right/down/left, reaching the goal terminates with reward 1, every other
step costs ``step_penalty``. Layouts are precomputed boolean masks (pure data),
so a whole family member is one ``jnp.where`` pipeline — vmap over thousands of
instances is free.

Observation is MLP-friendly: one-hot agent position concat one-hot goal
position (``2 * N * N`` floats).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.base import ActionSpec, EnvSpec, JaxEnv

# dr, dc per action: 0=up 1=right 2=down 3=left
_MOVES = np.array([[-1, 0], [0, 1], [1, 0], [0, -1]], np.int32)


def _four_rooms_walls(size: int) -> np.ndarray:
    """Classic four-rooms layout: a cross of walls with one door per arm."""
    walls = np.zeros((size, size), bool)
    mid = size // 2
    walls[mid, :] = True
    walls[:, mid] = True
    q1, q3 = mid // 2, mid + 1 + (size - mid - 1) // 2
    for r, c in ((mid, q1), (mid, q3), (q1, mid), (q3, mid)):
        walls[r, c] = False
    return walls


_LAYOUTS = {
    "empty": lambda size: np.zeros((size, size), bool),
    "four_rooms": _four_rooms_walls,
}


class GridWorld(JaxEnv):
    """One member of the gridworld family (``layout`` in {empty, four_rooms},
    ``size`` >= 5). State is ``(agent_rc, goal_rc)`` int32 pairs."""

    def __init__(self, size: int = 8, layout: str = "empty", step_penalty: float = 0.01):
        if layout not in _LAYOUTS:
            raise ValueError(f"unknown gridworld layout {layout!r}; choose from {sorted(_LAYOUTS)}")
        if size < 5:
            raise ValueError(f"gridworld size must be >= 5, got {size}")
        self.size = int(size)
        self.layout = layout
        self.step_penalty = float(step_penalty)
        walls = _LAYOUTS[layout](self.size)
        self._walls = jnp.asarray(walls)
        free = np.argwhere(~walls).astype(np.int32)
        self._free_cells = jnp.asarray(free)  # [F, 2] sampling table of free cells
        self.spec = EnvSpec(
            obs_shape=(2 * self.size * self.size,),
            action=ActionSpec(kind="discrete", num_actions=4),
            obs_low=0.0,
            obs_high=1.0,
        )

    def _obs(self, state: Tuple[jax.Array, jax.Array]) -> jax.Array:
        agent, goal = state
        n = self.size * self.size
        agent_idx = agent[0] * self.size + agent[1]
        goal_idx = goal[0] * self.size + goal[1]
        one_hot = jnp.zeros((2 * n,), jnp.float32)
        return one_hot.at[agent_idx].set(1.0).at[n + goal_idx].set(1.0)

    def reset(self, key: jax.Array) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array]:
        ka, kg = jax.random.split(key)
        num_free = self._free_cells.shape[0]
        agent = self._free_cells[jax.random.randint(ka, (), 0, num_free)]
        # goal re-drawn from the cells != agent by shifting the draw past it
        draw = jax.random.randint(kg, (), 0, num_free - 1)
        agent_pos = jnp.argmax(jnp.all(self._free_cells == agent, axis=1))
        goal = self._free_cells[jnp.where(draw >= agent_pos, draw + 1, draw)]
        state = (agent, goal)
        return state, self._obs(state)

    def step(
        self, state: Tuple[jax.Array, jax.Array], action: jax.Array
    ) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
        agent, goal = state
        move = jnp.asarray(_MOVES)[action]
        target = jnp.clip(agent + move, 0, self.size - 1)
        blocked = self._walls[target[0], target[1]]
        new_agent = jnp.where(blocked, agent, target)
        done = jnp.all(new_agent == goal)
        reward = jnp.where(done, 1.0, -self.step_penalty).astype(jnp.float32)
        new_state = (new_agent, goal)
        return new_state, self._obs(new_state), reward, done, {}
