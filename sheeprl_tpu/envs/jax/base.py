"""The ``JaxEnv`` protocol: environments as pure functions over pytrees.

An on-device environment is two pure functions plus a static spec:

- ``reset(key) -> (state, obs)`` — build a fresh episode state from a PRNG key;
- ``step(state, action) -> (state, obs, reward, done, info)`` — advance one
  step. ``info`` is a dict of fixed-shape arrays (it must be scan-able), with
  the keys produced by the :class:`~sheeprl_tpu.envs.jax.wrappers.AutoReset`
  wrapper contract documented in ``howto/jax_envs.md``.

``state`` is an arbitrary pytree; both functions must be jit/vmap/scan-safe
(no Python control flow on traced values, no host callbacks). Batching over a
``num_envs`` leading axis is the wrapper's job
(:class:`~sheeprl_tpu.envs.jax.wrappers.VmapEnv`), not the environment's:
every env here is written single-instance.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


@dataclass(frozen=True)
class ActionSpec:
    """Static action-space descriptor.

    ``kind='discrete'``: ``num_actions`` categorical actions, taken as an int32
    scalar. ``kind='continuous'``: a float vector of ``shape`` bounded by
    ``low``/``high`` (broadcastable scalars kept static for jit closure).
    """

    kind: str  # "discrete" | "continuous"
    num_actions: int = 0
    shape: Tuple[int, ...] = ()
    low: float = -1.0
    high: float = 1.0

    def __post_init__(self) -> None:
        if self.kind not in ("discrete", "continuous"):
            raise ValueError(f"unknown action kind {self.kind!r}")

    @property
    def actions_dim(self) -> Tuple[int, ...]:
        """The per-head action dims in the agents' convention (one categorical
        head of ``num_actions`` logits, or one continuous head of ``shape``)."""
        if self.kind == "discrete":
            return (int(self.num_actions),)
        return tuple(int(s) for s in self.shape)

    def to_gym_space(self):
        """The equivalent gymnasium space (adapter + agent-building path)."""
        import gymnasium as gym

        if self.kind == "discrete":
            return gym.spaces.Discrete(int(self.num_actions))
        return gym.spaces.Box(self.low, self.high, self.shape, np.float32)


@dataclass(frozen=True)
class EnvSpec:
    """Static environment descriptor: observation shape/dtype + action spec."""

    obs_shape: Tuple[int, ...]
    action: ActionSpec
    obs_dtype: Any = np.float32
    # bounds are informational (the adapter's observation_space); pure-plane
    # consumers never clip observations
    obs_low: float = -np.inf
    obs_high: float = np.inf
    # populated by wrappers/envs that truncate episodes at a step budget; the
    # Anakin rollout uses it to decide statically whether to pay the
    # truncation-bootstrap value pass
    max_episode_steps: Optional[int] = None

    def to_gym_obs_space(self):
        import gymnasium as gym

        return gym.spaces.Box(self.obs_low, self.obs_high, self.obs_shape, self.obs_dtype)


class JaxEnv:
    """Base class for on-device environments (duck-typed protocol: anything with
    ``spec``/``reset``/``step`` of the right signatures works)."""

    spec: EnvSpec

    def reset(self, key: jax.Array) -> Tuple[Any, jax.Array]:
        raise NotImplementedError

    def step(
        self, state: Any, action: jax.Array
    ) -> Tuple[Any, jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError
