"""On-device environment plane: pure-JAX vectorized environments.

The host plane (``sheeprl_tpu/envs/`` + ``utils/env.py``) steps Python/gymnasium
envs and pays a host<->device handoff per vector step. This plane puts the
environment *inside* JAX — ``reset``/``step`` are pure functions over pytrees —
so the Anakin topology (``algos/ppo/anakin.py``) can fuse rollout + train into
one jitted program over the mesh with zero host transfers in steady state
(Podracer, arxiv 2104.06272).

Select it with ``env.backend=jax`` (see ``howto/jax_envs.md``):

- the Anakin loops (``ppo_anakin``/``a2c_anakin``) consume the pure plane
  directly via :func:`make_jax_env`;
- every host-env loop keeps working through :class:`JaxToGymEnv`, the
  gymnasium adapter ``utils/env.py`` swaps in behind the ``make_env`` factory.
"""

from sheeprl_tpu.envs.jax.base import ActionSpec, EnvSpec, JaxEnv
from sheeprl_tpu.envs.jax.classic import CartPole, Pendulum
from sheeprl_tpu.envs.jax.factory import JAX_ENV_IDS, JaxToGymEnv, make_jax_env, resolve_jax_env
from sheeprl_tpu.envs.jax.gridworld import GridWorld
from sheeprl_tpu.envs.jax.wrappers import AutoReset, AutoResetState, VmapEnv

__all__ = [
    "ActionSpec",
    "AutoReset",
    "AutoResetState",
    "CartPole",
    "EnvSpec",
    "GridWorld",
    "JAX_ENV_IDS",
    "JaxEnv",
    "JaxToGymEnv",
    "Pendulum",
    "VmapEnv",
    "make_jax_env",
    "resolve_jax_env",
]
