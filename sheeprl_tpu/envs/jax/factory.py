"""Factories for the on-device env plane + the gymnasium compatibility adapter.

Two consumers, one id namespace (:data:`JAX_ENV_IDS`):

- :func:`make_jax_env` — the pure plane: resolve ``cfg.env.id``, apply the
  :class:`AutoReset` contract and vmap-batch over ``num_envs``. This is what
  the Anakin topology fuses into its jitted program.
- :class:`JaxToGymEnv` — a ``gym.Env`` stepping the same pure functions on the
  host CPU backend, so ``env.backend=jax`` slots behind the existing
  ``make_env`` factory and every host-env loop/wrapper/test keeps working.

Gridworld ids take an optional size suffix: ``gridworld_four_rooms-16`` is the
16x16 four-rooms member.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.envs.jax.base import JaxEnv
from sheeprl_tpu.envs.jax.classic import CartPole, Pendulum
from sheeprl_tpu.envs.jax.gridworld import GridWorld
from sheeprl_tpu.envs.jax.wrappers import AutoReset, VmapEnv

# id -> (constructor, default max_episode_steps — gymnasium's registered
# TimeLimit for the classics, a 4*N*N step budget for gridworlds)
JAX_ENV_IDS = ("CartPole-v1", "Pendulum-v1", "gridworld_empty", "gridworld_four_rooms")


def resolve_jax_env(env_id: str) -> Tuple[JaxEnv, Optional[int]]:
    """Build the bare single-instance env for ``env_id`` and return it with the
    id's default episode step budget."""
    if env_id == "CartPole-v1":
        return CartPole(), 500
    if env_id == "Pendulum-v1":
        return Pendulum(), 200
    if env_id.startswith("gridworld_"):
        base, _, size_suffix = env_id.partition("-")
        layout = base[len("gridworld_"):]
        size = int(size_suffix) if size_suffix else 8
        return GridWorld(size=size, layout=layout), 4 * size * size
    raise ValueError(
        f"unknown jax env id {env_id!r}; the on-device plane provides {JAX_ENV_IDS} "
        "(see howto/jax_envs.md to add one)"
    )


def make_jax_env(cfg: Any, num_envs: int) -> VmapEnv:
    """The pure plane entry point: ``cfg.env.id`` resolved, AutoReset applied
    (``cfg.env.max_episode_steps`` overrides the id default; <= 0 disables
    truncation entirely), batched over ``num_envs``."""
    env, default_limit = resolve_jax_env(str(cfg.env.id))
    limit = cfg.env.get("max_episode_steps", None)
    limit = default_limit if limit is None else (int(limit) if int(limit) > 0 else None)
    return VmapEnv(AutoReset(env, max_episode_steps=limit), num_envs)


class JaxToGymEnv(gym.Env):
    """gymnasium adapter over a pure :class:`JaxEnv` (``env.backend=jax`` behind
    ``make_env``). Steps run through jitted functions pinned to the host CPU
    backend — the host plane's loops treat this exactly like any other gym env,
    including TimeLimit/RecordEpisodeStatistics stacking on top."""

    metadata = {"render_modes": []}
    render_mode = None

    def __init__(
        self,
        id: str,
        seed: int = 0,
        max_episode_steps: Optional[int] = None,
        apply_default_time_limit: bool = True,
    ):
        self._env, default_limit = resolve_jax_env(id)
        self.id = id
        if max_episode_steps is None and apply_default_time_limit:
            max_episode_steps = default_limit
        self._max_episode_steps = max_episode_steps
        self.observation_space = self._env.spec.to_gym_obs_space()
        self.action_space = self._env.spec.action.to_gym_space()
        # pin the step/reset programs to the host CPU backend by committing the
        # PRNG chain there: committed inputs drive jit placement, and the env
        # state stays committed across steps (jit's deprecated backend= kwarg
        # is avoided — the ActPlacement device-split reasoning applies: a
        # per-step dispatch to an accelerator dwarfs a classic-control step)
        self._cpu = jax.local_devices(backend="cpu")[0]
        self._reset_fn = jax.jit(self._env.reset)
        self._step_fn = jax.jit(self._env.step)
        self._key = jax.device_put(jax.random.PRNGKey(seed), self._cpu)
        self._state: Any = None
        self._elapsed = 0
        # gym.Env duck compatibility without inheriting (gym.Env is pure protocol)
        self.spec = gym.envs.registration.EnvSpec(id=f"jax/{id}")

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict] = None):
        if seed is not None:
            self._key = jax.device_put(jax.random.PRNGKey(seed), self._cpu)
        self._key, reset_key = jax.random.split(self._key)
        self._state, obs = self._reset_fn(reset_key)
        self._elapsed = 0
        return np.asarray(obs), {}

    def step(self, action):
        if self._env.spec.action.kind == "discrete":
            action = np.int32(action)
        else:
            action = np.asarray(action, np.float32)
        self._state, obs, reward, done, _ = self._step_fn(self._state, action)
        self._elapsed += 1
        terminated = bool(done)
        truncated = bool(
            self._max_episode_steps is not None
            and self._elapsed >= self._max_episode_steps
            and not terminated
        )
        return np.asarray(obs), float(reward), terminated, truncated, {}

    def render(self):
        return None

    def close(self):
        pass

    @property
    def unwrapped(self):
        return self
