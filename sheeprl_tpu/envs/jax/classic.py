"""Classic-control environments in pure JAX, numerically matching gymnasium.

Dynamics, reward, termination and reset distributions are transcribed from
gymnasium's ``CartPoleEnv`` / ``PendulumEnv`` (classic_control module) so the
step-semantics parity suite (``tests/test_envs/test_jax_parity.py``) can drive
both implementations over the same action sequence and assert obs/reward/
termination agreement within float tolerance.

State is the raw physics vector; PRNG randomness only enters at ``reset``.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.jax.base import ActionSpec, EnvSpec, JaxEnv


class CartPole(JaxEnv):
    """gymnasium ``CartPole-v1``: euler-integrated cart-pole, 2 discrete actions,
    reward 1 per step (terminal step included), termination on |x| > 2.4 or
    |theta| > ~12 deg. The v1 500-step truncation is the AutoReset wrapper's job
    (``max_episode_steps``), exactly like gymnasium's TimeLimit."""

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    TOTAL_MASS = MASSPOLE + MASSCART
    LENGTH = 0.5  # half the pole's length
    POLEMASS_LENGTH = MASSPOLE * LENGTH
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_THRESHOLD = 12 * 2 * np.pi / 360
    X_THRESHOLD = 2.4

    spec = EnvSpec(
        obs_shape=(4,),
        action=ActionSpec(kind="discrete", num_actions=2),
        # gymnasium advertises the threshold-derived bounds; parity is on values,
        # bounds are informational only
        obs_low=-np.inf,
        obs_high=np.inf,
    )

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        state = jax.random.uniform(key, (4,), jnp.float32, -0.05, 0.05)
        return state, state

    def step(
        self, state: jax.Array, action: jax.Array
    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
        x, x_dot, theta, theta_dot = state[0], state[1], state[2], state[3]
        force = jnp.where(action == 1, self.FORCE_MAG, -self.FORCE_MAG).astype(jnp.float32)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        temp = (force + self.POLEMASS_LENGTH * theta_dot**2 * sintheta) / self.TOTAL_MASS
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costheta**2 / self.TOTAL_MASS)
        )
        xacc = temp - self.POLEMASS_LENGTH * thetaacc * costheta / self.TOTAL_MASS
        # euler integration, gymnasium's kinematics_integrator="euler" order
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        new_state = jnp.stack([x, x_dot, theta, theta_dot]).astype(jnp.float32)
        done = (
            (jnp.abs(x) > self.X_THRESHOLD) | (jnp.abs(theta) > self.THETA_THRESHOLD)
        )
        reward = jnp.float32(1.0)
        return new_state, new_state, reward, done, {}


class Pendulum(JaxEnv):
    """gymnasium ``Pendulum-v1``: torque-controlled pendulum swing-up, continuous
    action in [-2, 2], never terminates (truncation-only episodes — gymnasium's
    200-step TimeLimit maps to the AutoReset ``max_episode_steps``)."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0

    spec = EnvSpec(
        obs_shape=(3,),
        action=ActionSpec(kind="continuous", num_actions=0, shape=(1,), low=-2.0, high=2.0),
        obs_low=-8.0,
        obs_high=8.0,
    )

    @staticmethod
    def _obs(state: jax.Array) -> jax.Array:
        th, thdot = state[0], state[1]
        return jnp.stack([jnp.cos(th), jnp.sin(th), thdot]).astype(jnp.float32)

    def reset(self, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        # gymnasium: th ~ U(-pi, pi), thdot ~ U(-1, 1)
        high = jnp.array([np.pi, 1.0], jnp.float32)
        state = jax.random.uniform(key, (2,), jnp.float32, -1.0, 1.0) * high
        return state, self._obs(state)

    def step(
        self, state: jax.Array, action: jax.Array
    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
        th, thdot = state[0], state[1]
        u = jnp.clip(action.reshape(()), -self.MAX_TORQUE, self.MAX_TORQUE)
        angle_norm = ((th + jnp.pi) % (2 * jnp.pi)) - jnp.pi
        costs = angle_norm**2 + 0.1 * thdot**2 + 0.001 * u**2
        newthdot = thdot + (
            3.0 * self.G / (2.0 * self.L) * jnp.sin(th) + 3.0 / (self.M * self.L**2) * u
        ) * self.DT
        newthdot = jnp.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        newth = th + newthdot * self.DT
        new_state = jnp.stack([newth, newthdot]).astype(jnp.float32)
        reward = (-costs).astype(jnp.float32)
        return new_state, self._obs(new_state), reward, jnp.bool_(False), {}
