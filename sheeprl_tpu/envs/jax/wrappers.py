"""Pure-JAX env wrappers: the autoreset contract and vmap batching.

``AutoReset`` reproduces the host plane's SAME_STEP autoreset semantics
(gym.vector.AutoresetMode.SAME_STEP, see ``algos/ppo/ppo.py``): the step that
ends an episode returns the *fresh reset observation* as the next observation,
the terminal observation rides in ``info["terminal_observation"]``, and
truncation (step-budget exhaustion) is reported separately from termination so
the rollout can bootstrap truncated episodes exactly like the host loops.
Episode return/length accumulate in carried state and surface in ``info`` on
the done step — the role of ``RecordEpisodeStatistics``.

``VmapEnv`` lifts a single-instance env to a ``num_envs`` leading axis with
``jax.vmap``; composition order is ``VmapEnv(AutoReset(env))`` so every
instance resets independently inside one fused program.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.envs.jax.base import EnvSpec, JaxEnv


class AutoResetState(NamedTuple):
    inner: Any  # wrapped env's state
    key: jax.Array  # PRNG chain for in-step resets
    episode_return: jax.Array  # float32 running return of the CURRENT episode
    episode_length: jax.Array  # int32 running length of the CURRENT episode


class AutoReset(JaxEnv):
    """done -> fresh reset inside ``step`` (branchless: the reset is computed
    every step and selected by the done mask — classic-control/gridworld resets
    are a handful of ops, so this stays cheaper than any ``lax.cond`` under
    vmap, where both branches execute anyway)."""

    def __init__(self, env: JaxEnv, max_episode_steps: int | None = None):
        self.env = env
        self.max_episode_steps = int(max_episode_steps) if max_episode_steps else None
        self.spec = EnvSpec(
            obs_shape=env.spec.obs_shape,
            action=env.spec.action,
            obs_dtype=env.spec.obs_dtype,
            obs_low=env.spec.obs_low,
            obs_high=env.spec.obs_high,
            max_episode_steps=self.max_episode_steps,
        )

    def reset(self, key: jax.Array) -> Tuple[AutoResetState, jax.Array]:
        key, reset_key = jax.random.split(key)
        inner, obs = self.env.reset(reset_key)
        state = AutoResetState(
            inner=inner,
            key=key,
            episode_return=jnp.float32(0.0),
            episode_length=jnp.int32(0),
        )
        return state, obs

    def step(
        self, state: AutoResetState, action: jax.Array
    ) -> Tuple[AutoResetState, jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
        inner, obs, reward, terminated, _ = self.env.step(state.inner, action)
        episode_return = state.episode_return + reward
        episode_length = state.episode_length + 1
        if self.max_episode_steps is not None:
            truncated = (episode_length >= self.max_episode_steps) & ~terminated
        else:
            truncated = jnp.bool_(False)
        done = terminated | truncated

        key, reset_key = jax.random.split(state.key)
        reset_inner, reset_obs = self.env.reset(reset_key)
        new_inner = jax.tree_util.tree_map(
            lambda r, s: jnp.where(done, r, s), reset_inner, inner
        )
        new_obs = jnp.where(done, reset_obs, obs)
        new_state = AutoResetState(
            inner=new_inner,
            key=key,
            episode_return=jnp.where(done, 0.0, episode_return).astype(jnp.float32),
            episode_length=jnp.where(done, 0, episode_length).astype(jnp.int32),
        )
        info = {
            # the pre-reset observation of THIS step (the host plane's
            # infos["final_obs"]); valid only where done
            "terminal_observation": obs,
            "terminated": terminated,
            "truncated": truncated,
            # episode stats of the episode that ENDED this step; valid where done
            "episode_return": episode_return,
            "episode_length": episode_length,
        }
        return new_state, new_obs, reward, done, info


class VmapEnv(JaxEnv):
    """Batch a single-instance env over a ``num_envs`` leading axis. ``reset``
    takes ONE key and fans it out; ``step`` maps state/action elementwise."""

    def __init__(self, env: JaxEnv, num_envs: int):
        if num_envs < 1:
            raise ValueError(f"num_envs must be >= 1, got {num_envs}")
        self.env = env
        self.num_envs = int(num_envs)
        self.spec = env.spec
        self._reset = jax.vmap(env.reset)
        self._step = jax.vmap(env.step)

    def reset(self, key: jax.Array) -> Tuple[Any, jax.Array]:
        return self._reset(jax.random.split(key, self.num_envs))

    def step(
        self, state: Any, action: jax.Array
    ) -> Tuple[Any, jax.Array, jax.Array, jax.Array, Dict[str, jax.Array]]:
        return self._step(state, action)
