"""MineDojo adapter (capability parity with reference sheeprl/envs/minedojo.py:56-307;
minedojo is optional).

Re-expresses MineDojo's 8-slot functional action space as a 3-head MultiDiscrete
(movement-camera-functional macro, craft target, equip/place/destroy target),
flattens the inventory/equipment into per-item vectors, and exposes the action masks
the Dreamer-V3 MinedojoActor consumes (``mask_action_type`` / ``mask_equip_place`` /
``mask_destroy`` / ``mask_craft_smelt``).
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINEDOJO_AVAILABLE

if not _IS_MINEDOJO_AVAILABLE:
    raise ModuleNotFoundError("minedojo is not installed: pip install minedojo")

import copy
from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import minedojo
import minedojo.tasks
import numpy as np
from minedojo.sim import ALL_CRAFT_SMELT_ITEMS, ALL_ITEMS

N_ALL_ITEMS = len(ALL_ITEMS)
ITEM_ID_TO_NAME = dict(enumerate(ALL_ITEMS))
ITEM_NAME_TO_ID = {name: i for i, name in enumerate(ALL_ITEMS)}

# 19 macro actions over MineDojo's raw 8-slot action vector
# (slot 0 fwd/back, 1 left/right, 2 jump/sneak/sprint, 3 pitch, 4 yaw,
#  5 functional, 6 craft arg, 7 inventory arg); 12 is the camera no-op bin.
_MACROS = [
    [0, 0, 0, 12, 12, 0, 0, 0],  # no-op
    [1, 0, 0, 12, 12, 0, 0, 0],  # forward
    [2, 0, 0, 12, 12, 0, 0, 0],  # back
    [0, 1, 0, 12, 12, 0, 0, 0],  # left
    [0, 2, 0, 12, 12, 0, 0, 0],  # right
    [1, 0, 1, 12, 12, 0, 0, 0],  # jump + forward
    [1, 0, 2, 12, 12, 0, 0, 0],  # sneak + forward
    [1, 0, 3, 12, 12, 0, 0, 0],  # sprint + forward
    [0, 0, 0, 11, 12, 0, 0, 0],  # pitch -15
    [0, 0, 0, 13, 12, 0, 0, 0],  # pitch +15
    [0, 0, 0, 12, 11, 0, 0, 0],  # yaw -15
    [0, 0, 0, 12, 13, 0, 0, 0],  # yaw +15
    [0, 0, 0, 12, 12, 1, 0, 0],  # use
    [0, 0, 0, 12, 12, 2, 0, 0],  # drop
    [0, 0, 0, 12, 12, 3, 0, 0],  # attack
    [0, 0, 0, 12, 12, 4, 0, 0],  # craft
    [0, 0, 0, 12, 12, 5, 0, 0],  # equip
    [0, 0, 0, 12, 12, 6, 0, 0],  # place
    [0, 0, 0, 12, 12, 7, 0, 0],  # destroy
]
ACTION_MAP = {i: np.asarray(m) for i, m in enumerate(_MACROS)}


def _item_key(name: str) -> str:
    return "_".join(name.split(" "))


class MineDojoWrapper(gym.Env):
    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: Optional[int] = 30,
        sticky_jump: Optional[int] = 10,
        **kwargs: Any,
    ):
        self._pitch_limits = pitch_limits
        self._pos = kwargs.get("start_position", None)
        self._break_speed_multiplier = kwargs.pop("break_speed_multiplier", 100)
        # a >1 break-speed already shortens digging; sticky attack would overshoot
        self._sticky_attack = 0 if self._break_speed_multiplier > 1 else (sticky_attack or 0)
        self._sticky_jump = sticky_jump or 0
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        if self._pos is not None and not (pitch_limits[0] <= self._pos["pitch"] <= pitch_limits[1]):
            raise ValueError(
                f"The initial position must respect the pitch limits {pitch_limits}, given {self._pos['pitch']}"
            )

        # minedojo.make mutates the global task-spec table; snapshot + restore so
        # repeated construction stays deterministic (reference minedojo.py:43,115)
        task_specs = copy.deepcopy(minedojo.tasks.ALL_TASKS_SPECS)
        self._env = minedojo.make(
            task_id=id,
            image_size=(height, width),
            world_seed=seed,
            fast_reset=True,
            break_speed_multiplier=self._break_speed_multiplier,
            **kwargs,
        )
        minedojo.tasks.ALL_TASKS_SPECS = copy.deepcopy(task_specs)

        self._inventory: Dict[str, list] = {}
        self._inventory_names: Optional[np.ndarray] = None
        self._inventory_max = np.zeros(N_ALL_ITEMS)
        self.action_space = gym.spaces.MultiDiscrete(
            np.array([len(ACTION_MAP), len(ALL_CRAFT_SMELT_ITEMS), N_ALL_ITEMS])
        )
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(0, 255, self._env.observation_space["rgb"].shape, np.uint8),
                "inventory": gym.spaces.Box(0.0, np.inf, (N_ALL_ITEMS,), np.float32),
                "inventory_max": gym.spaces.Box(0.0, np.inf, (N_ALL_ITEMS,), np.float32),
                "inventory_delta": gym.spaces.Box(-np.inf, np.inf, (N_ALL_ITEMS,), np.float32),
                "equipment": gym.spaces.Box(0.0, 1.0, (N_ALL_ITEMS,), np.int32),
                "life_stats": gym.spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
                "mask_action_type": gym.spaces.Box(0, 1, (len(ACTION_MAP),), bool),
                "mask_equip_place": gym.spaces.Box(0, 1, (N_ALL_ITEMS,), bool),
                "mask_destroy": gym.spaces.Box(0, 1, (N_ALL_ITEMS,), bool),
                "mask_craft_smelt": gym.spaces.Box(0, 1, (len(ALL_CRAFT_SMELT_ITEMS),), bool),
            }
        )
        self.render_mode = "rgb_array"
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    def _convert_inventory(self, inventory: Dict[str, Any]) -> np.ndarray:
        counts = np.zeros(N_ALL_ITEMS)
        self._inventory = {}
        self._inventory_names = np.array([_item_key(n) for n in inventory["name"].tolist()])
        for slot, (name, quantity) in enumerate(zip(inventory["name"], inventory["quantity"])):
            item = _item_key(name)
            self._inventory.setdefault(item, []).append(slot)
            counts[ITEM_NAME_TO_ID[item]] += 1 if item == "air" else quantity
        self._inventory_max = np.maximum(counts, self._inventory_max)
        return counts

    def _convert_inventory_delta(self, delta: Dict[str, Any]) -> np.ndarray:
        out = np.zeros(N_ALL_ITEMS)
        for names_key, qty_key, sign in (
            ("inc_name_by_craft", "inc_quantity_by_craft", 1),
            ("dec_name_by_craft", "dec_quantity_by_craft", -1),
            ("inc_name_by_other", "inc_quantity_by_other", 1),
            ("dec_name_by_other", "dec_quantity_by_other", -1),
        ):
            for name, qty in zip(delta[names_key], delta[qty_key]):
                out[ITEM_NAME_TO_ID[_item_key(name)]] += sign * qty
        return out

    def _convert_equipment(self, equipment: Dict[str, Any]) -> np.ndarray:
        out = np.zeros(N_ALL_ITEMS, dtype=np.int32)
        out[ITEM_NAME_TO_ID[_item_key(equipment["name"][0])]] = 1
        return out

    def _convert_masks(self, masks: Dict[str, Any]) -> Dict[str, np.ndarray]:
        equip_mask = np.zeros(N_ALL_ITEMS, dtype=bool)
        destroy_mask = np.zeros(N_ALL_ITEMS, dtype=bool)
        for item, can_equip, can_destroy in zip(self._inventory_names, masks["equip"], masks["destroy"]):
            idx = ITEM_NAME_TO_ID[item]
            equip_mask[idx] = can_equip
            destroy_mask[idx] = can_destroy
        # functional-action availability: equip/place need an equipable item, destroy
        # a destroyable one; the 12 movement/camera macros are always legal
        masks["action_type"][5:7] *= bool(np.any(equip_mask))
        masks["action_type"][7] *= bool(np.any(destroy_mask))
        return {
            "mask_action_type": np.concatenate((np.ones(12, dtype=bool), masks["action_type"][1:])),
            "mask_equip_place": equip_mask,
            "mask_destroy": destroy_mask,
            "mask_craft_smelt": masks["craft_smelt"],
        }

    def _convert_action(self, action: np.ndarray) -> np.ndarray:
        out = ACTION_MAP[int(action[0])].copy()
        if self._sticky_attack:
            if out[5] == 3:
                self._sticky_attack_counter = self._sticky_attack - 1
            elif self._sticky_attack_counter > 0 and out[5] == 0:
                out[5] = 3
                self._sticky_attack_counter -= 1
            else:
                self._sticky_attack_counter = 0
        if self._sticky_jump:
            if out[2] == 1:
                self._sticky_jump_counter = self._sticky_jump - 1
            elif self._sticky_jump_counter > 0 and out[0] == 0:
                out[2] = 1
                # keep moving while the sticky jump plays out
                if out[0] == out[1] == 0:
                    out[0] = 1
                self._sticky_jump_counter -= 1
            elif out[2] != 1:
                self._sticky_jump_counter = 0
        out[6] = int(action[1]) if out[5] == 4 else 0
        if out[5] in (5, 6, 7):
            out[7] = self._inventory[ITEM_ID_TO_NAME[int(action[2])]][0]
        else:
            out[7] = 0
        return out

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            "rgb": obs["rgb"].copy(),
            "inventory": self._convert_inventory(obs["inventory"]),
            "inventory_max": self._inventory_max,
            "inventory_delta": self._convert_inventory_delta(obs["delta_inv"]),
            "equipment": self._convert_equipment(obs["equipment"]),
            "life_stats": np.concatenate(
                (obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["oxygen"])
            ),
            **self._convert_masks(obs["masks"]),
        }

    def _update_pos(self, obs: Dict[str, Any]) -> None:
        loc = obs["location_stats"]
        self._pos = {
            "x": float(loc["pos"][0]),
            "y": float(loc["pos"][1]),
            "z": float(loc["pos"][2]),
            "pitch": float(loc["pitch"].item()),
            "yaw": float(loc["yaw"].item()),
        }

    def _life_info(self, obs: Dict[str, Any]) -> Dict[str, float]:
        return {
            "life": float(obs["life_stats"]["life"].item()),
            "oxygen": float(obs["life_stats"]["oxygen"].item()),
            "food": float(obs["life_stats"]["food"].item()),
        }

    def step(self, action: np.ndarray):
        raw_action = action
        action = self._convert_action(action)
        # clamp the camera so the pitch never leaves the limits
        next_pitch = self._pos["pitch"] + (action[3] - 12) * 15
        if not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            action[3] = 12
        obs, reward, done, info = self._env.step(action)
        is_timelimit = info.get("TimeLimit.truncated", False)
        self._update_pos(obs)
        info.update(
            {
                "life_stats": self._life_info(obs),
                "location_stats": copy.deepcopy(self._pos),
                "action": raw_action.tolist(),
                "biomeid": float(obs["location_stats"]["biome_id"].item()),
            }
        )
        return self._convert_obs(obs), reward, done and not is_timelimit, done and is_timelimit, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs = self._env.reset()
        self._update_pos(obs)
        self._sticky_jump_counter = 0
        self._sticky_attack_counter = 0
        self._inventory_max = np.zeros(N_ALL_ITEMS)
        return self._convert_obs(obs), {
            "life_stats": self._life_info(obs),
            "location_stats": copy.deepcopy(self._pos),
            "biomeid": float(obs["location_stats"]["biome_id"].item()),
        }

    def render(self):
        prev = self._env.unwrapped._prev_obs
        return None if prev is None else prev["rgb"]

    def close(self) -> None:
        self._env.close()
