"""Deterministic fake environments for tests/CI (role of sheeprl/envs/dummy.py:8-90):
dict observations with an ``rgb`` image and a ``state`` vector, zero rewards, fixed
episode length."""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

import gymnasium as gym
import numpy as np


class BaseDummyEnv(gym.Env):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        step_latency_ms: float = 0.0,
    ):
        # step_latency_ms > 0 paces each step like a real emulator frame
        # (Atari ~5-20 ms): the fleet_ingest bench uses it so multi-actor
        # ingestion scaling measures the DATA PLANE, not single-core contention
        self._step_latency_s = float(step_latency_ms) / 1000.0
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(0, 255, shape=image_size, dtype=np.uint8),
                "state": gym.spaces.Box(-20, 20, shape=vector_shape, dtype=np.float32),
            }
        )
        self.reward_range = (-np.inf, np.inf)
        self.render_mode = "rgb_array"
        self._current_step = 0
        self._n_steps = n_steps

    def get_obs(self) -> Dict[str, np.ndarray]:
        return {
            "rgb": np.zeros(self.observation_space["rgb"].shape, dtype=np.uint8),
            "state": np.zeros(self.observation_space["state"].shape, dtype=np.float32),
        }

    def step(self, action):
        if self._step_latency_s > 0:
            time.sleep(self._step_latency_s)
        done = self._current_step == self._n_steps
        self._current_step += 1
        return self.get_obs(), 0.0, done, False, {}

    def reset(self, seed=None, options=None):
        self._current_step = 0
        return self.get_obs(), {}

    def render(self):
        rgb = self.get_obs()["rgb"]
        return np.transpose(rgb, (1, 2, 0))

    def close(self):
        pass


class ContinuousDummyEnv(BaseDummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        action_dim: int = 2,
        step_latency_ms: float = 0.0,
    ):
        self.action_space = gym.spaces.Box(-1.0, 1.0, shape=(action_dim,))
        super().__init__(
            image_size=image_size, n_steps=n_steps, vector_shape=vector_shape,
            step_latency_ms=step_latency_ms,
        )


class DiscreteDummyEnv(BaseDummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 4,
        vector_shape: Tuple[int, ...] = (10,),
        action_dim: int = 2,
        step_latency_ms: float = 0.0,
    ):
        self.action_space = gym.spaces.Discrete(action_dim)
        super().__init__(
            image_size=image_size, n_steps=n_steps, vector_shape=vector_shape,
            step_latency_ms=step_latency_ms,
        )


class MultiDiscreteDummyEnv(BaseDummyEnv):
    def __init__(
        self,
        image_size: Tuple[int, int, int] = (3, 64, 64),
        n_steps: int = 128,
        vector_shape: Tuple[int, ...] = (10,),
        action_dims: List[int] = [2, 2],
        step_latency_ms: float = 0.0,
    ):
        self.action_space = gym.spaces.MultiDiscrete(action_dims)
        super().__init__(
            image_size=image_size, n_steps=n_steps, vector_shape=vector_shape,
            step_latency_ms=step_latency_ms,
        )
