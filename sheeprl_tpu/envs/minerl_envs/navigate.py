"""Custom MineRL Navigate task spec (capability parity with reference
sheeprl/envs/minerl_envs/navigate.py:18-139): reach a diamond block ~64 m away
guided by a compass; optional dense distance shaping and extreme-hills variant.
The Malmo time limit is disabled — truncation is owned by the framework's
TimeLimit wrapper so terminated/truncated stay distinguishable.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("minerl is not installed: pip install minerl==0.4.4")

from typing import List

import minerl.herobraine.hero.handlers as handlers
from minerl.herobraine.hero.handler import Handler

from sheeprl_tpu.envs.minerl_envs.backend import CustomSimpleEmbodimentEnvSpec


class CustomNavigate(CustomSimpleEmbodimentEnvSpec):
    def __init__(self, dense: bool, extreme: bool, *args, **kwargs):
        suffix = ("Extreme" if extreme else "") + ("Dense" if dense else "")
        self.dense, self.extreme = dense, extreme
        kwargs.pop("max_episode_steps", None)
        super().__init__(f"CustomMineRLNavigate{suffix}-v0", *args, max_episode_steps=None, **kwargs)

    def is_from_folder(self, folder: str) -> bool:
        return folder == ("navigateextreme" if self.extreme else "navigate")

    def create_observables(self) -> List[Handler]:
        return super().create_observables() + [
            handlers.CompassObservation(angle=True, distance=False),
            handlers.FlatInventoryObservation(["dirt"]),
        ]

    def create_actionables(self) -> List[Handler]:
        return super().create_actionables() + [
            handlers.PlaceBlock(["none", "dirt"], _other="none", _default="none")
        ]

    def create_rewardables(self) -> List[Handler]:
        rewards: List[Handler] = [
            handlers.RewardForTouchingBlockType(
                [{"type": "diamond_block", "behaviour": "onceOnly", "reward": 100.0}]
            )
        ]
        if self.dense:
            rewards.append(handlers.RewardForDistanceTraveledToCompassTarget(reward_per_block=1.0))
        return rewards

    def create_agent_start(self) -> List[Handler]:
        return super().create_agent_start() + [
            handlers.SimpleInventoryAgentStart([dict(type="compass", quantity="1")])
        ]

    def create_agent_handlers(self) -> List[Handler]:
        return [handlers.AgentQuitFromTouchingBlockType(["diamond_block"])]

    def create_server_world_generators(self) -> List[Handler]:
        if self.extreme:
            return [handlers.BiomeGenerator(biome=3, force_reset=True)]
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_quit_producers(self) -> List[Handler]:
        return [handlers.ServerQuitWhenAnyAgentFinishes()]

    def create_server_decorators(self) -> List[Handler]:
        return [
            handlers.NavigationDecorator(
                max_randomized_radius=64,
                min_randomized_radius=64,
                block="diamond_block",
                placement="surface",
                max_radius=8,
                min_radius=0,
                max_randomized_distance=8,
                min_randomized_distance=0,
                randomize_compass_location=True,
            )
        ]

    def create_server_initial_conditions(self) -> List[Handler]:
        return [
            handlers.TimeInitialCondition(allow_passage_of_time=False, start_time=6000),
            handlers.WeatherInitialCondition("clear"),
            handlers.SpawningInitialCondition("false"),
        ]

    def get_docstring(self) -> str:
        return (
            "Navigate to a diamond block ~64 m from spawn using a compass observation; "
            "+100 on reaching it" + (", plus per-tick distance shaping" if self.dense else "")
        )

    def determine_success_from_rewards(self, rewards: list) -> bool:
        return sum(rewards) >= (160.0 if self.dense else 100.0)
