"""Custom MineRL Obtain task specs (capability parity with reference
sheeprl/envs/minerl_envs/obtain.py:23-326): the ObtainDiamond / ObtainIronPickaxe
item-hierarchy tasks with GUI-free craft/smelt actions and milestone rewards.
The Malmo time limit is disabled — truncation is owned by the framework's
TimeLimit wrapper so terminated/truncated stay distinguishable.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("minerl is not installed: pip install minerl==0.4.4")

from typing import Dict, List, Union

from minerl.herobraine.hero import handlers
from minerl.herobraine.hero.handler import Handler

from sheeprl_tpu.envs.minerl_envs.backend import CustomSimpleEmbodimentEnvSpec

_NONE = "none"
_OTHER = "other"

_INVENTORY_ITEMS = [
    "dirt", "coal", "torch", "log", "planks", "stick", "crafting_table",
    "wooden_axe", "wooden_pickaxe", "stone", "cobblestone", "furnace",
    "stone_axe", "stone_pickaxe", "iron_ore", "iron_ingot", "iron_axe", "iron_pickaxe",
]
_EQUIP_ITEMS = [
    "air", "wooden_axe", "wooden_pickaxe", "stone_axe", "stone_pickaxe",
    "iron_axe", "iron_pickaxe",
]
# milestone rewards along the diamond item hierarchy (reference obtain.py:183-196)
_MILESTONES = [
    ("log", 1), ("planks", 2), ("stick", 4), ("crafting_table", 4),
    ("wooden_pickaxe", 8), ("cobblestone", 16), ("furnace", 32),
    ("stone_pickaxe", 32), ("iron_ore", 64), ("iron_ingot", 128),
    ("iron_pickaxe", 256),
]


def _camel(word: str) -> str:
    return "".join(part.capitalize() for part in word.split("_"))


class CustomObtain(CustomSimpleEmbodimentEnvSpec):
    def __init__(
        self,
        target_item: str,
        dense: bool,
        reward_schedule: List[Dict[str, Union[str, int, float]]],
        *args,
        max_episode_steps=None,
        **kwargs,
    ):
        self.target_item = target_item
        self.dense = dense
        self.reward_schedule = reward_schedule
        name = f"CustomMineRLObtain{_camel(target_item)}{'Dense' if dense else ''}-v0"
        super().__init__(*args, name=name, max_episode_steps=max_episode_steps, **kwargs)

    def create_observables(self) -> List[Handler]:
        return super().create_observables() + [
            handlers.FlatInventoryObservation(_INVENTORY_ITEMS),
            handlers.EquippedItemObservation(
                items=_EQUIP_ITEMS + [_OTHER], _default="air", _other=_OTHER
            ),
        ]

    def create_actionables(self) -> List[Handler]:
        return super().create_actionables() + [
            handlers.PlaceBlock(
                [_NONE, "dirt", "stone", "cobblestone", "crafting_table", "furnace", "torch"],
                _other=_NONE,
                _default=_NONE,
            ),
            handlers.EquipAction([_NONE] + _EQUIP_ITEMS, _other=_NONE, _default=_NONE),
            handlers.CraftAction(
                [_NONE, "torch", "stick", "planks", "crafting_table"], _other=_NONE, _default=_NONE
            ),
            handlers.CraftNearbyAction(
                [_NONE] + [i for i in _EQUIP_ITEMS if i != "air"] + ["furnace"],
                _other=_NONE,
                _default=_NONE,
            ),
            handlers.SmeltItemNearby([_NONE, "iron_ingot", "coal"], _other=_NONE, _default=_NONE),
        ]

    def create_rewardables(self) -> List[Handler]:
        reward_handler = (
            handlers.RewardForCollectingItems if self.dense else handlers.RewardForCollectingItemsOnce
        )
        return [reward_handler(self.reward_schedule or {self.target_item: 1})]

    def create_agent_handlers(self) -> List[Handler]:
        return [handlers.AgentQuitFromPossessingItem([dict(type="diamond", amount=1)])]

    def create_server_world_generators(self) -> List[Handler]:
        return [handlers.DefaultWorldGenerator(force_reset=True)]

    def create_server_quit_producers(self) -> List[Handler]:
        return [handlers.ServerQuitWhenAnyAgentFinishes()]

    def create_server_decorators(self) -> List[Handler]:
        return []

    def create_server_initial_conditions(self) -> List[Handler]:
        return [
            handlers.TimeInitialCondition(start_time=6000, allow_passage_of_time=True),
            handlers.SpawningInitialCondition(allow_spawning=True),
        ]

    def is_from_folder(self, folder: str) -> bool:
        return folder == f"o_{self.target_item}"

    def get_docstring(self) -> str:
        return f"Obtain {self.target_item} through the item hierarchy; milestone rewards."

    def determine_success_from_rewards(self, rewards: list) -> bool:
        reward_values = [s["reward"] for s in self.reward_schedule]
        max_missing = round(len(self.reward_schedule) * 0.1)
        return len(set(rewards).intersection(reward_values)) >= len(reward_values) - max_missing


def _schedule(extra: List[Dict] = ()) -> List[Dict]:
    return [dict(type=t, amount=1, reward=r) for t, r in _MILESTONES] + list(extra)


class CustomObtainDiamond(CustomObtain):
    def __init__(self, dense: bool, *args, **kwargs):
        kwargs.pop("max_episode_steps", None)
        super().__init__(
            *args,
            target_item="diamond",
            dense=dense,
            reward_schedule=_schedule([dict(type="diamond", amount=1, reward=1024)]),
            max_episode_steps=None,
            **kwargs,
        )

    def is_from_folder(self, folder: str) -> bool:
        return folder == "o_dia"


class CustomObtainIronPickaxe(CustomObtain):
    def __init__(self, dense: bool, *args, **kwargs):
        kwargs.pop("max_episode_steps", None)
        super().__init__(
            *args,
            target_item="iron_pickaxe",
            dense=dense,
            reward_schedule=_schedule(),
            max_episode_steps=None,
            **kwargs,
        )

    def create_agent_handlers(self) -> List[Handler]:
        return [handlers.AgentQuitFromCraftingItem([dict(type="iron_pickaxe", amount=1)])]

    def is_from_folder(self, folder: str) -> bool:
        return folder == "o_iron"
