"""Base env-spec for the custom MineRL tasks (capability parity with reference
sheeprl/envs/minerl_envs/backend.py:19-61; minerl==0.4.4 is optional).

Provides the simple-embodiment observation/action surface (POV camera, location and
life stats, 8 keyboard actions + camera) plus a Malmo break-speed multiplier so the
obtain tasks are tractable without sticky attack.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("minerl is not installed: pip install minerl==0.4.4")

from abc import ABC
from typing import List

from minerl.herobraine.env_spec import EnvSpec
from minerl.herobraine.hero import handler, handlers
from minerl.herobraine.hero.handlers.translation import TranslationHandler
from minerl.herobraine.hero.mc import INVERSE_KEYMAP

SIMPLE_KEYBOARD_ACTION = ["forward", "back", "left", "right", "jump", "sneak", "sprint", "attack"]


class BreakSpeedMultiplier(handler.Handler):
    """Malmo agent-start handler scaling block-breaking speed (the diamond_env
    trick; reference backend.py:53-61)."""

    def __init__(self, multiplier: float = 1.0):
        self.multiplier = multiplier

    def to_string(self):
        return f"break_speed({self.multiplier})"

    def xml_template(self):
        return "<BreakSpeedMultiplier>{{multiplier}}</BreakSpeedMultiplier>"


class CustomSimpleEmbodimentEnvSpec(EnvSpec, ABC):
    """Shared base of the custom navigate/obtain specs."""

    def __init__(self, name, *args, resolution=(64, 64), break_speed: int = 100, **kwargs):
        self.resolution = resolution
        self.break_speed = break_speed
        super().__init__(name, *args, **kwargs)

    def create_agent_start(self) -> List[handler.Handler]:
        return [BreakSpeedMultiplier(self.break_speed)]

    def create_observables(self) -> List[TranslationHandler]:
        return [
            handlers.POVObservation(self.resolution),
            handlers.ObservationFromCurrentLocation(),
            handlers.ObservationFromLifeStats(),
        ]

    def create_actionables(self) -> List[TranslationHandler]:
        return [
            handlers.KeybasedCommandAction(k, v)
            for k, v in INVERSE_KEYMAP.items()
            if k in SIMPLE_KEYBOARD_ACTION
        ] + [handlers.CameraAction()]

    def create_monitors(self) -> List[TranslationHandler]:
        return []
