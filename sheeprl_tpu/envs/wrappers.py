"""Generic environment wrappers.

Same capability set as the reference's wrapper suite (sheeprl/envs/wrappers.py:13-342):
velocity masking, action repeat, crash-restart with a fail-window budget, dilated frame
stacking, reward/actions-as-observation, grayscale render. Written against the
gymnasium 1.x API (the reference targets 0.x).
"""

from __future__ import annotations

import copy
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import gymnasium as gym
import numpy as np


class MaskVelocityWrapper(gym.ObservationWrapper):
    """Zero out velocity entries to make the MDP partially observable."""

    velocity_indices: Dict[str, np.ndarray] = {
        "CartPole-v0": np.array([1, 3]),
        "CartPole-v1": np.array([1, 3]),
        "MountainCar-v0": np.array([1]),
        "MountainCarContinuous-v0": np.array([1]),
        "Pendulum-v1": np.array([2]),
        "LunarLander-v2": np.array([2, 3, 5]),
        "LunarLander-v3": np.array([2, 3, 5]),
        "LunarLanderContinuous-v2": np.array([2, 3, 5]),
        "LunarLanderContinuous-v3": np.array([2, 3, 5]),
    }

    def __init__(self, env: gym.Env):
        super().__init__(env)
        assert env.unwrapped.spec is not None
        env_id: str = env.unwrapped.spec.id
        self.mask = np.ones_like(env.observation_space.sample())
        try:
            self.mask[self.velocity_indices[env_id]] = 0.0
        except KeyError as e:
            raise NotImplementedError(f"Velocity masking not implemented for {env_id}") from e

    def observation(self, observation: np.ndarray) -> np.ndarray:
        return observation * self.mask


class ActionRepeat(gym.Wrapper):
    """Repeat each action ``amount`` times, accumulating reward, stopping on done."""

    def __init__(self, env: gym.Env, amount: int = 1):
        super().__init__(env)
        if amount <= 0:
            raise ValueError("`amount` should be a positive integer")
        self._amount = amount

    @property
    def action_repeat(self) -> int:
        return self._amount

    def step(self, action):
        terminated = truncated = False
        total_reward = 0.0
        obs, info = None, {}
        for _ in range(self._amount):
            obs, reward, terminated, truncated, info = self.env.step(action)
            total_reward += float(reward)
            if terminated or truncated:
                break
        return obs, total_reward, terminated, truncated, info


class InjectedEnvFault(gym.Wrapper):
    """One-shot ``env.step`` exception driven by ``resilience.fault=env_step``
    (sheeprl_tpu/resilience/faults.py): under :class:`RestartOnException` it
    exercises the crash-restart path, elsewhere an ordinary run crash. The armed
    flag is process-global, so it reaches sync (in-process) vector envs; async
    vector-env subprocesses never observe it."""

    def step(self, action):
        from sheeprl_tpu.resilience.faults import InjectedFaultError, consume_env_fault

        if consume_env_fault():
            raise InjectedFaultError(
                "resilience.fault=env_step: injected exception in env.step"
            )
        return self.env.step(action)


class RestartOnException(gym.Wrapper):
    """Rebuild a crashed env in place, with at most ``maxfails`` failures per
    ``window`` seconds (reference sheeprl/envs/wrappers.py:74-124). Dreamer-V3 wraps
    every env in this for long-running fault tolerance."""

    def __init__(
        self,
        env_fn: Callable[[], gym.Env],
        exceptions: Union[type, Tuple[type, ...], List[type]] = (Exception,),
        window: float = 300,
        maxfails: int = 2,
        wait: float = 20,
    ):
        if not isinstance(exceptions, (tuple, list)):
            exceptions = [exceptions]
        self._env_fn = env_fn
        self._exceptions = tuple(exceptions)
        self._window = window
        self._maxfails = maxfails
        self._wait = wait
        self._last = time.time()
        self._fails = 0
        super().__init__(env_fn())

    def _register_fail(self, err: Exception, where: str) -> None:
        if time.time() > self._last + self._window:
            self._last = time.time()
            self._fails = 1
        else:
            self._fails += 1
        if self._fails > self._maxfails:
            raise RuntimeError(f"The env crashed too many times: {self._fails}") from err
        gym.logger.warn(f"{where} - Restarting env after crash with {type(err).__name__}: {err}")
        time.sleep(self._wait)

    def step(self, action):
        try:
            return self.env.step(action)
        except self._exceptions as e:
            self._register_fail(e, "STEP")
            self.env = self._env_fn()
            new_obs, info = self.env.reset()
            info.update({"restart_on_exception": True})
            return new_obs, 0.0, False, False, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        try:
            return self.env.reset(seed=seed, options=options)
        except self._exceptions as e:
            self._register_fail(e, "RESET")
            self.env = self._env_fn()
            new_obs, info = self.env.reset(seed=seed, options=options)
            info.update({"restart_on_exception": True})
            return new_obs, info


class FrameStack(gym.Wrapper):
    """Stack the last ``num_stack`` image frames (optionally dilated) of each cnn key
    along a new leading axis: (num_stack, C, H, W)."""

    def __init__(self, env: gym.Env, num_stack: int, cnn_keys: Sequence[str], dilation: int = 1) -> None:
        super().__init__(env)
        if num_stack <= 0:
            raise ValueError(f"Invalid value for num_stack, expected a value greater than zero, got {num_stack}")
        if not isinstance(env.observation_space, gym.spaces.Dict):
            raise RuntimeError(
                f"Expected an observation space of type gym.spaces.Dict, got: {type(env.observation_space)}"
            )
        self._num_stack = num_stack
        self._dilation = dilation
        self._cnn_keys = []
        self.observation_space = copy.deepcopy(env.observation_space)
        for k, v in env.observation_space.spaces.items():
            if cnn_keys and k in cnn_keys and len(v.shape) == 3:
                self._cnn_keys.append(k)
                self.observation_space[k] = gym.spaces.Box(
                    np.repeat(v.low[None, ...], num_stack, axis=0),
                    np.repeat(v.high[None, ...], num_stack, axis=0),
                    (num_stack, *v.shape),
                    v.dtype,
                )
        if not self._cnn_keys:
            raise RuntimeError("Specify at least one valid cnn key to be stacked")
        self._frames = {k: deque(maxlen=num_stack * dilation) for k in self._cnn_keys}

    def _get_obs(self, key: str) -> np.ndarray:
        frames = list(self._frames[key])[self._dilation - 1 :: self._dilation]
        assert len(frames) == self._num_stack
        return np.stack(frames, axis=0)

    def step(self, action):
        obs, reward, terminated, truncated, infos = self.env.step(action)
        for k in self._cnn_keys:
            self._frames[k].append(obs[k])
            obs[k] = self._get_obs(k)
        return obs, reward, terminated, truncated, infos

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, infos = self.env.reset(seed=seed, options=options)
        for k in self._cnn_keys:
            self._frames[k].clear()
            for _ in range(self._num_stack * self._dilation):
                self._frames[k].append(obs[k])
            obs[k] = self._get_obs(k)
        return obs, infos


class RewardAsObservationWrapper(gym.Wrapper):
    """Expose the last reward as a (1,)-shaped observation under the ``reward`` key."""

    def __init__(self, env: gym.Env) -> None:
        super().__init__(env)
        reward_range = getattr(self.env, "reward_range", None) or (-np.inf, np.inf)
        reward_space = gym.spaces.Box(*reward_range, (1,), np.float32)
        if isinstance(self.env.observation_space, gym.spaces.Dict):
            self.observation_space = gym.spaces.Dict(
                {"reward": reward_space, **dict(self.env.observation_space.items())}
            )
        else:
            self.observation_space = gym.spaces.Dict(
                {"obs": self.env.observation_space, "reward": reward_space}
            )

    def _convert_obs(self, obs: Any, reward: Union[float, np.ndarray]) -> Dict[str, Any]:
        reward_obs = np.asarray(reward, dtype=np.float32).reshape(-1)
        if isinstance(obs, dict):
            obs["reward"] = reward_obs
            return obs
        return {"obs": obs, "reward": reward_obs}

    def step(self, action):
        obs, reward, terminated, truncated, infos = self.env.step(action)
        return self._convert_obs(obs, copy.deepcopy(reward)), reward, terminated, truncated, infos

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, infos = self.env.reset(seed=seed, options=options)
        return self._convert_obs(obs, 0), infos


class GrayscaleRenderWrapper(gym.Wrapper):
    """Expand grayscale render frames to 3 channels so video encoders accept them."""

    def render(self):
        frame = super().render()
        if isinstance(frame, np.ndarray):
            if frame.ndim == 2:
                frame = frame[..., np.newaxis]
            if frame.ndim == 3 and frame.shape[-1] == 1:
                frame = frame.repeat(3, axis=-1)
        return frame


class ActionsAsObservationWrapper(gym.Wrapper):
    """Expose the last ``num_stack`` (dilated) actions, one-hot for (multi)discrete
    spaces, under the ``action_stack`` observation key."""

    def __init__(self, env: gym.Env, num_stack: int, noop: Union[float, int, List[int]], dilation: int = 1):
        super().__init__(env)
        if num_stack < 1:
            raise ValueError(
                f"The number of actions to stack must be greater or equal than 1, got: {num_stack}"
            )
        if dilation < 1:
            raise ValueError(f"The actions stack dilation argument must be greater than zero, got: {dilation}")
        if not isinstance(noop, (int, float, list)):
            raise ValueError(f"The noop action must be an integer or float or list, got: {noop} ({type(noop)})")
        if not isinstance(env.observation_space, gym.spaces.Dict):
            raise ValueError(
                "ActionsAsObservationWrapper requires a Dict observation space; apply it "
                "after the dict-obs coercion (make_env does this automatically)"
            )
        self._num_stack = num_stack
        self._dilation = dilation
        self._actions: deque = deque(maxlen=num_stack * dilation)
        self._is_continuous = isinstance(self.env.action_space, gym.spaces.Box)
        self._is_multidiscrete = isinstance(self.env.action_space, gym.spaces.MultiDiscrete)
        self.observation_space = copy.deepcopy(self.env.observation_space)
        if self._is_continuous:
            self._action_shape = self.env.action_space.shape[0]
            low = np.resize(self.env.action_space.low, self._action_shape * num_stack)
            high = np.resize(self.env.action_space.high, self._action_shape * num_stack)
        elif self._is_multidiscrete:
            low, high = 0, 1
            self._action_shape = int(sum(self.env.action_space.nvec))
        else:
            low, high = 0, 1
            self._action_shape = int(self.env.action_space.n)
        self.observation_space["action_stack"] = gym.spaces.Box(
            low=low, high=high, shape=(self._action_shape * num_stack,), dtype=np.float32
        )
        if self._is_continuous:
            if isinstance(noop, list):
                raise ValueError(f"The noop actions must be a float for continuous action spaces, got: {noop}")
            self.noop = np.full((self._action_shape,), noop, dtype=np.float32)
        elif self._is_multidiscrete:
            if not isinstance(noop, list):
                raise ValueError(f"The noop actions must be a list for multi-discrete action spaces, got: {noop}")
            if len(self.env.action_space.nvec) != len(noop):
                raise RuntimeError(
                    "The number of noop actions must equal the number of actions of the environment. "
                    f"Got env_action_space = {self.env.action_space.nvec} and noop = {noop}"
                )
            pieces = []
            for noop_act, n in zip(noop, self.env.action_space.nvec):
                piece = np.zeros((int(n),), dtype=np.float32)
                piece[int(noop_act)] = 1.0
                pieces.append(piece)
            self.noop = np.concatenate(pieces, axis=-1)
        else:
            if isinstance(noop, (list, float)):
                raise ValueError(f"The noop actions must be an integer for discrete action spaces, got: {noop}")
            self.noop = np.zeros((self._action_shape,), dtype=np.float32)
            self.noop[int(noop)] = 1.0

    def _one_hot(self, action: Any) -> np.ndarray:
        if self._is_continuous:
            return np.asarray(action, dtype=np.float32).reshape(-1)
        if self._is_multidiscrete:
            pieces = []
            for act, n in zip(action, self.env.action_space.nvec):
                piece = np.zeros((int(n),), dtype=np.float32)
                piece[int(act)] = 1.0
                pieces.append(piece)
            return np.concatenate(pieces, axis=-1)
        one_hot = np.zeros((self._action_shape,), dtype=np.float32)
        one_hot[int(np.asarray(action).reshape(()))] = 1.0
        return one_hot

    def _get_actions_stack(self) -> np.ndarray:
        stack = list(self._actions)[self._dilation - 1 :: self._dilation]
        return np.concatenate(stack, axis=-1).astype(np.float32)

    def step(self, action):
        self._actions.append(self._one_hot(action))
        obs, reward, terminated, truncated, info = super().step(action)
        obs["action_stack"] = self._get_actions_stack()
        return obs, reward, terminated, truncated, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, info = super().reset(seed=seed, options=options)
        self._actions.clear()
        for _ in range(self._num_stack * self._dilation):
            self._actions.append(self.noop)
        obs["action_stack"] = self._get_actions_stack()
        return obs, info
