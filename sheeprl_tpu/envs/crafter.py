"""Crafter adapter (capability parity with reference sheeprl/envs/crafter.py:17-66;
crafter is optional — the module import is gated).

Crafter is the BASELINE north-star XL workload: 64x64 rgb obs, 17 discrete actions,
gym-0.x step API converted to terminated/truncated via the ``discount`` info field.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_CRAFTER_AVAILABLE

if not _IS_CRAFTER_AVAILABLE:
    raise ModuleNotFoundError("crafter is not installed: pip install crafter")

from typing import Any, Dict, Optional, Tuple, Union

import crafter
import gymnasium as gym
import numpy as np
from gymnasium import spaces


class CrafterWrapper(gym.Env):
    def __init__(self, id: str, screen_size: Union[int, Tuple[int, int]], seed: Optional[int] = None) -> None:
        if id not in ("crafter_reward", "crafter_nonreward"):
            raise ValueError(f"id must be crafter_reward or crafter_nonreward, got {id!r}")
        size = (screen_size, screen_size) if isinstance(screen_size, int) else tuple(screen_size)
        self._env = crafter.Env(size=size, seed=seed, reward=(id == "crafter_reward"))
        inner = self._env.observation_space
        self.observation_space = spaces.Dict(
            {"rgb": spaces.Box(inner.low, inner.high, inner.shape, inner.dtype)}
        )
        self.action_space = spaces.Discrete(self._env.action_space.n)
        self.reward_range = self._env.reward_range or (-np.inf, np.inf)
        self.observation_space.seed(seed)
        self.action_space.seed(seed)
        self.render_mode = "rgb_array"
        self.metadata = {"render_fps": 30}

    def step(self, action: Any):
        obs, reward, done, info = self._env.step(action)
        # crafter signals a true terminal with discount==0; otherwise the episode hit
        # its internal time limit (reference crafter.py:52-53)
        terminated = done and info["discount"] == 0
        truncated = done and info["discount"] != 0
        return {"rgb": obs}, reward, terminated, truncated, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        self._env._seed = seed
        obs = self._env.reset()
        return {"rgb": obs}, {}

    def render(self):
        return self._env.render()

    def close(self) -> None:
        return
