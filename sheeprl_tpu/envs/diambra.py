"""DIAMBRA Arena adapter (capability parity with reference
sheeprl/envs/diambra.py:22-145; the diambra SDK is optional).

Normalizes the arena's Dict observation (Discrete/MultiDiscrete entries become int32
Boxes so the whole dict flows through the pixel/vector pipeline) and forces the
settings the framework owns (frame shape, single player, flatten).
"""

from __future__ import annotations

import warnings

from sheeprl_tpu.utils.imports import _IS_DIAMBRA_AVAILABLE

if not _IS_DIAMBRA_AVAILABLE:
    raise ModuleNotFoundError("diambra is not installed: pip install diambra diambra-arena")

from typing import Any, Dict, Optional, Tuple, Union

import diambra
import diambra.arena
import gymnasium as gym
import numpy as np
from diambra.arena import EnvironmentSettings, WrappersSettings


class DiambraWrapper(gym.Env):
    def __init__(
        self,
        id: str,
        action_space: str = "DISCRETE",
        screen_size: Union[int, Tuple[int, int]] = 64,
        grayscale: bool = False,
        repeat_action: int = 1,
        rank: int = 0,
        diambra_settings: Optional[Dict[str, Any]] = None,
        diambra_wrappers: Optional[Dict[str, Any]] = None,
        render_mode: str = "rgb_array",
        log_level: int = 0,
        increase_performance: bool = True,
    ) -> None:
        if isinstance(screen_size, int):
            screen_size = (screen_size,) * 2
        if action_space not in ("DISCRETE", "MULTI_DISCRETE"):
            raise ValueError(
                "The valid values for the `action_space` attribute are "
                f"'DISCRETE' or 'MULTI_DISCRETE', got {action_space}"
            )
        diambra_settings = dict(diambra_settings or {})
        diambra_wrappers = dict(diambra_wrappers or {})
        for owned in ("frame_shape", "n_players"):
            if diambra_settings.pop(owned, None) is not None:
                warnings.warn(f"The DIAMBRA {owned} setting is disabled")
        role = diambra_settings.pop("role", None)
        if role is not None and role not in ("P1", "P2"):
            raise ValueError(f"The valid values for the `role` attribute are 'P1' or 'P2' or None, got {role}")
        self._action_type = action_space.lower()

        settings = EnvironmentSettings(
            **{
                **diambra_settings,
                "game_id": id,
                "action_space": getattr(diambra.arena.SpaceTypes, action_space, diambra.arena.SpaceTypes.DISCRETE),
                "n_players": 1,
                "role": getattr(diambra.arena.Roles, role, diambra.arena.Roles.P1) if role is not None else None,
                "render_mode": render_mode,
            }
        )
        if repeat_action > 1:
            if "step_ratio" not in settings or settings["step_ratio"] > 1:
                warnings.warn(
                    f"step_ratio parameter modified to 1 because the sticky action is active ({repeat_action})"
                )
            settings["step_ratio"] = 1
        for owned in ("frame_shape", "stack_frames", "dilation", "flatten"):
            if diambra_wrappers.pop(owned, None) is not None:
                warnings.warn(f"The DIAMBRA {owned} wrapper is disabled")
        wrappers = WrappersSettings(
            **{**diambra_wrappers, "flatten": True, "repeat_action": repeat_action}
        )
        if increase_performance:
            settings.frame_shape = screen_size + (int(grayscale),)
        else:
            wrappers.frame_shape = screen_size + (int(grayscale),)
        self._env = diambra.arena.make(
            id, settings, wrappers, rank=rank, render_mode=render_mode, log_level=log_level
        )

        self.action_space = self._env.action_space
        obs: Dict[str, gym.spaces.Space] = {}
        for k, space in self._env.observation_space.spaces.items():
            if isinstance(space, gym.spaces.Box):
                obs[k] = space
            elif isinstance(space, gym.spaces.Discrete):
                obs[k] = gym.spaces.Box(0, space.n - 1, (1,), np.int32)
            elif isinstance(space, gym.spaces.MultiDiscrete):
                obs[k] = gym.spaces.Box(
                    np.zeros_like(space.nvec), space.nvec - 1, (len(space.nvec),), np.int32
                )
            else:
                raise RuntimeError(f"Invalid observation space, got: {type(space)}")
        self.observation_space = gym.spaces.Dict(obs)
        self.render_mode = render_mode

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            k: np.asarray(v).reshape(self.observation_space[k].shape) for k, v in obs.items()
        }

    def step(self, action):
        if self._action_type == "discrete" and isinstance(action, np.ndarray):
            action = action.squeeze().item()
        obs, reward, terminated, truncated, infos = self._env.step(action)
        infos["env_domain"] = "DIAMBRA"
        return (
            self._convert_obs(obs),
            reward,
            terminated or infos.get("env_done", False),
            truncated,
            infos,
        )

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs, infos = self._env.reset(seed=seed, options=options)
        infos["env_domain"] = "DIAMBRA"
        return self._convert_obs(obs), infos

    def render(self, **kwargs):
        return self._env.render()

    def close(self) -> None:
        self._env.close()
