"""DeepMind Control Suite adapter (capability parity with reference
sheeprl/envs/dmc.py:49-244; dm_control is optional — the module import is gated).

Exposes every dm_control task as a gymnasium env with a Dict observation holding an
``rgb`` render and/or a flattened ``state`` vector, a [-1, 1]-normalized continuous
action space, and dm_env discount-based terminated/truncated semantics.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_DMC_AVAILABLE

if not _IS_DMC_AVAILABLE:
    raise ModuleNotFoundError("dm_control is not installed: pip install dm_control")

from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import numpy as np
from dm_control import suite
from dm_env import specs
from gymnasium import spaces


def _spec_to_box(spec_list, dtype) -> spaces.Box:
    lows, highs = [], []
    for s in spec_list:
        dim = int(np.prod(s.shape))
        if isinstance(s, specs.BoundedArray):
            lows.append(np.broadcast_to(s.minimum, (dim,)).astype(np.float64))
            highs.append(np.broadcast_to(s.maximum, (dim,)).astype(np.float64))
        elif isinstance(s, specs.Array):
            lows.append(np.full(dim, -np.inf))
            highs.append(np.full(dim, np.inf))
        else:
            raise ValueError(f"Unrecognized spec: {type(s)}")
    return spaces.Box(
        np.concatenate(lows).astype(dtype), np.concatenate(highs).astype(dtype), dtype=dtype
    )


def _flatten(obs: Dict[Any, Any]) -> np.ndarray:
    return np.concatenate(
        [np.atleast_1d(np.asarray(v)).ravel() for v in obs.values()], axis=0
    )


class DMCWrapper(gym.Env):
    """dm_control task as a gymnasium env.

    Observation: Dict with ``rgb`` (from_pixels) and/or ``state`` (from_vectors).
    A dm_env episode ends with discount==0 → terminated; discount==1 at the final
    step → truncated (time limit), matching reference dmc.py:228-229.
    """

    def __init__(
        self,
        domain_name: str,
        task_name: str,
        from_pixels: bool = False,
        from_vectors: bool = True,
        height: int = 84,
        width: int = 84,
        camera_id: int = 0,
        task_kwargs: Optional[Dict[Any, Any]] = None,
        environment_kwargs: Optional[Dict[Any, Any]] = None,
        channels_first: bool = True,
        visualize_reward: bool = False,
        seed: Optional[int] = None,
    ):
        if not (from_vectors or from_pixels):
            raise ValueError(
                "'from_vectors' and 'from_pixels' must not be both False: "
                f"got {from_vectors} and {from_pixels} respectively."
            )
        self._from_pixels = from_pixels
        self._from_vectors = from_vectors
        self._height = height
        self._width = width
        self._camera_id = camera_id
        self._channels_first = channels_first

        task_kwargs = dict(task_kwargs or {})
        task_kwargs.pop("random", None)  # seeding goes through reset()
        self._env = suite.load(
            domain_name=domain_name,
            task_name=task_name,
            task_kwargs=task_kwargs,
            visualize_reward=visualize_reward,
            environment_kwargs=environment_kwargs,
        )

        self._true_action_space = _spec_to_box([self._env.action_spec()], np.float32)
        self.action_space = spaces.Box(
            -1.0, 1.0, shape=self._true_action_space.shape, dtype=np.float32
        )
        reward_space = _spec_to_box([self._env.reward_spec()], np.float32)
        self.reward_range = (float(reward_space.low.item()), float(reward_space.high.item()))

        obs_space: Dict[str, spaces.Space] = {}
        if from_pixels:
            shape = (3, height, width) if channels_first else (height, width, 3)
            obs_space["rgb"] = spaces.Box(0, 255, shape=shape, dtype=np.uint8)
        if from_vectors:
            obs_space["state"] = _spec_to_box(self._env.observation_spec().values(), np.float64)
        self.observation_space = spaces.Dict(obs_space)
        self.state_space = _spec_to_box(self._env.observation_spec().values(), np.float64)
        self.current_state: Optional[np.ndarray] = None
        self.render_mode = "rgb_array"
        self.metadata = {}
        self._seed_spaces(seed)

    def _seed_spaces(self, seed: Optional[int]) -> None:
        self.action_space.seed(seed)
        self._true_action_space.seed(seed)
        self.observation_space.seed(seed)

    def _obs(self, time_step) -> Dict[str, np.ndarray]:
        obs = {}
        if self._from_pixels:
            rgb = self.render()
            obs["rgb"] = rgb.transpose(2, 0, 1).copy() if self._channels_first else rgb
        if self._from_vectors:
            obs["state"] = _flatten(time_step.observation)
        return obs

    def _denormalize(self, action: np.ndarray) -> np.ndarray:
        low, high = self._true_action_space.low, self._true_action_space.high
        action = (np.asarray(action, np.float64) + 1.0) / 2.0  # [-1,1] → [0,1]
        return (action * (high - low) + low).astype(np.float32)

    def step(self, action):
        time_step = self._env.step(self._denormalize(action))
        self.current_state = _flatten(time_step.observation)
        info = {
            "discount": time_step.discount,
            "internal_state": self._env.physics.get_state().copy(),
        }
        terminated = bool(not time_step.first() and time_step.last() and time_step.discount == 0)
        truncated = bool(time_step.last() and time_step.discount == 1)
        return self._obs(time_step), time_step.reward or 0.0, terminated, truncated, info

    def reset(self, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        # dm_control draws task randomness from a numpy RandomState owned by the task
        self._env.task._random = np.random.RandomState(seed)
        time_step = self._env.reset()
        self.current_state = _flatten(time_step.observation)
        return self._obs(time_step), {}

    def render(self, camera_id: Optional[int] = None) -> np.ndarray:
        return self._env.physics.render(
            height=self._height, width=self._width, camera_id=camera_id or self._camera_id
        )

    def close(self) -> None:
        self._env.close()
