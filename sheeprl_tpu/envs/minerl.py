"""MineRL adapter (capability parity with reference sheeprl/envs/minerl.py:48-322;
minerl==0.4.4 is optional).

Flattens MineRL's dict action space into one Discrete space (a no-op plus one entry
per key/camera-bin/enum-value), vectorizes the inventory/equipment per item, and
adds sticky attack/jump. Pitch is clamped to ``pitch_limits``.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MINERL_AVAILABLE

if not _IS_MINERL_AVAILABLE:
    raise ModuleNotFoundError("minerl is not installed: pip install minerl==0.4.4")

import copy
from typing import Any, Dict, Optional, Tuple

import gymnasium as gym
import minerl
import numpy as np
from minerl.herobraine.hero import mc

from sheeprl_tpu.envs.minerl_envs.navigate import CustomNavigate
from sheeprl_tpu.envs.minerl_envs.obtain import CustomObtainDiamond, CustomObtainIronPickaxe

CUSTOM_ENVS = {
    "custom_navigate": CustomNavigate,
    "custom_obtain_diamond": CustomObtainDiamond,
    "custom_obtain_iron_pickaxe": CustomObtainIronPickaxe,
}

N_ALL_ITEMS = len(mc.ALL_ITEMS)
ITEM_ID_TO_NAME = dict(enumerate(mc.ALL_ITEMS))
ITEM_NAME_TO_ID = {name: i for i, name in enumerate(mc.ALL_ITEMS)}
NOOP: Dict[str, Any] = {
    "camera": (0, 0),
    "forward": 0,
    "back": 0,
    "left": 0,
    "right": 0,
    "attack": 0,
    "sprint": 0,
    "jump": 0,
    "sneak": 0,
    "craft": "none",
    "nearbyCraft": "none",
    "nearbySmelt": "none",
    "place": "none",
    "equip": "none",
}
_CAMERA_BINS = [np.array([-15, 0]), np.array([15, 0]), np.array([0, -15]), np.array([0, 15])]


class MineRLWrapper(gym.Env):
    def __init__(
        self,
        id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: Optional[int] = 30,
        sticky_jump: Optional[int] = 10,
        break_speed_multiplier: Optional[int] = 100,
        multihot_inventory: bool = True,
        **kwargs: Any,
    ):
        self._height = height
        self._width = width
        self._pitch_limits = pitch_limits
        self._sticky_attack = 0 if break_speed_multiplier > 1 else (sticky_attack or 0)
        self._sticky_jump = sticky_jump or 0
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._multihot_inventory = multihot_inventory
        if "navigate" not in id.lower():
            kwargs.pop("extreme", None)
        self._env = CUSTOM_ENVS[id.lower()](break_speed=break_speed_multiplier, **kwargs).make()

        # Discrete action index → MineRL dict-action override (reference
        # minerl.py:100-138): one no-op, one entry per binary key (jump/sneak/sprint
        # also move forward), 4 camera bins, one entry per non-none enum value.
        self.ACTIONS_MAP: Dict[int, Dict[str, Any]] = {0: {}}
        idx = 1
        for act in self._env.action_space:
            space = self._env.action_space[act]
            if isinstance(space, minerl.herobraine.hero.spaces.Enum):
                values = sorted(set(space.values.tolist()) - {"none"})
            elif act == "camera":
                values = _CAMERA_BINS
            else:
                values = [1]
            for v in values:
                entry = {act: v}
                if act in ("jump", "sneak", "sprint") and v == 1:
                    entry["forward"] = 1
                self.ACTIONS_MAP[idx] = entry
                idx += 1
        self.action_space = gym.spaces.Discrete(len(self.ACTIONS_MAP))

        if multihot_inventory:
            self.inventory_size = N_ALL_ITEMS
            self.inventory_item_to_id = ITEM_NAME_TO_ID
        else:
            inv_items = list(self._env.observation_space["inventory"])
            self.inventory_size = len(inv_items)
            self.inventory_item_to_id = {name: i for i, name in enumerate(inv_items)}

        obs_space: Dict[str, gym.spaces.Space] = {
            "rgb": gym.spaces.Box(0, 255, (3, height, width), np.uint8),
            "life_stats": gym.spaces.Box(0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32),
            "inventory": gym.spaces.Box(0.0, np.inf, (self.inventory_size,), np.float32),
            "max_inventory": gym.spaces.Box(0.0, np.inf, (self.inventory_size,), np.float32),
        }
        if "compass" in self._env.observation_space.spaces:
            obs_space["compass"] = gym.spaces.Box(-180, 180, (1,), np.float32)
        if "equipped_items" in self._env.observation_space.spaces:
            if multihot_inventory:
                self.equip_size = N_ALL_ITEMS
                self.equip_item_to_id = ITEM_NAME_TO_ID
            else:
                equip_items = self._env.observation_space["equipped_items"]["mainhand"]["type"].values.tolist()
                self.equip_size = len(equip_items)
                self.equip_item_to_id = {name: i for i, name in enumerate(equip_items)}
            obs_space["equipment"] = gym.spaces.Box(0.0, 1.0, (self.equip_size,), np.int32)
        self.observation_space = gym.spaces.Dict(obs_space)

        self._pos = {"pitch": 0.0, "yaw": 0.0}
        self._max_inventory = np.zeros(self.inventory_size)
        self.render_mode = "rgb_array"
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    def _convert_action(self, action: np.ndarray) -> Dict[str, Any]:
        out = copy.deepcopy(NOOP)
        out.update(self.ACTIONS_MAP[int(np.asarray(action).item())])
        if self._sticky_attack:
            if out["attack"]:
                self._sticky_attack_counter = self._sticky_attack
            if self._sticky_attack_counter > 0:
                out["attack"] = 1
                out["jump"] = 0
                self._sticky_attack_counter -= 1
        if self._sticky_jump:
            if out["jump"]:
                self._sticky_jump_counter = self._sticky_jump
            if self._sticky_jump_counter > 0:
                out["jump"] = 1
                out["forward"] = 1
                self._sticky_jump_counter -= 1
        return out

    def _convert_inventory(self, inventory: Dict[str, Any]) -> Dict[str, np.ndarray]:
        counts = np.zeros(self.inventory_size)
        for item, quantity in inventory.items():
            counts[self.inventory_item_to_id[item]] += 1 if item == "air" else quantity
        self._max_inventory = np.maximum(counts, self._max_inventory)
        return {"inventory": counts, "max_inventory": self._max_inventory.copy()}

    def _convert_equipment(self, equipment: Dict[str, Any]) -> np.ndarray:
        out = np.zeros(self.equip_size, dtype=np.int32)
        out[self.equip_item_to_id.get(equipment["mainhand"]["type"], self.equip_item_to_id["air"])] = 1
        return out

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        converted = {
            "rgb": obs["pov"].copy().transpose(2, 0, 1),
            "life_stats": np.array(
                [obs["life_stats"]["life"], obs["life_stats"]["food"], obs["life_stats"]["air"]],
                dtype=np.float32,
            ),
            **self._convert_inventory(obs["inventory"]),
        }
        if "equipment" in self.observation_space.spaces:
            converted["equipment"] = self._convert_equipment(obs["equipped_items"])
        if "compass" in self.observation_space.spaces:
            converted["compass"] = obs["compass"]["angle"].reshape(-1)
        return converted

    def step(self, action: np.ndarray):
        converted = self._convert_action(action)
        next_pitch = self._pos["pitch"] + converted["camera"][0]
        next_yaw = ((self._pos["yaw"] + converted["camera"][1]) + 180) % 360 - 180
        if not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            converted["camera"] = np.array([0, converted["camera"][1]])
            next_pitch = self._pos["pitch"]
        obs, reward, done, info = self._env.step(converted)
        self._pos = {"pitch": next_pitch, "yaw": next_yaw}
        # the Malmo time limit is disabled in the custom specs — `done` is terminal;
        # truncation comes from the framework TimeLimit wrapper
        return self._convert_obs(obs), reward, done, False, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        obs = self._env.reset()
        self._max_inventory = np.zeros(self.inventory_size)
        self._sticky_attack_counter = 0
        self._sticky_jump_counter = 0
        self._pos = {"pitch": 0.0, "yaw": 0.0}
        return self._convert_obs(obs), {}

    def render(self):
        return self._env.render(self.render_mode)

    def close(self) -> None:
        self._env.close()
