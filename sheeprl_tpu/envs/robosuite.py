"""Robosuite / LIBERO adapter (capability parity with reference
sheeprl/envs/robosuite.py:17-301; robosuite and libero are optional).

Exposes a robosuite manipulation task (or a LIBERO bddl task) as a gymnasium env
with a Dict observation — per-camera images (first camera under ``rgb``, further
cameras under ``rgb_<name>``), robot proprioception under ``state``/``state<i>``,
and the task's object state under ``object_state`` — plus a [-1, 1]-normalized
continuous action space.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_ROBOSUITE_AVAILABLE

if not _IS_ROBOSUITE_AVAILABLE:
    raise ModuleNotFoundError("robosuite is not installed: pip install robosuite")

import os
from typing import Any, Dict, Optional, Sequence

import gymnasium as gym
import numpy as np
import robosuite as suite
from gymnasium import spaces


class RobosuiteWrapper(gym.Env):
    def __init__(
        self,
        env_name: str,
        env_config: str,
        robot: str,
        bddl_file: Optional[str] = None,
        controller: Any = "OSC_POSE",
        controller_kwargs: Optional[Dict[str, Any]] = None,
        hard_reset: bool = False,
        horizon: int = 500,
        reward_scale: float = 1.0,
        reward_shaping: bool = True,
        ignore_done: bool = True,
        has_renderer: bool = False,
        has_offscreen_renderer: bool = False,
        use_camera_obs: bool = False,
        use_object_obs: bool = True,
        camera_names: Sequence[str] = ("agentview",),
        camera_heights: int = 84,
        camera_widths: int = 84,
        render_camera: str = "agentview",
        control_freq: int = 20,
        keys: Optional[Sequence[str]] = None,
        channels_first: bool = True,
    ):
        """Option surface of reference robosuite.py:18-52, extended with the camera
        block (names/sizes/render camera), object-state exposure, per-controller
        kwargs and raw-key selection the reference leaves at robosuite defaults."""
        controller_configs = suite.controllers.load_controller_config(default_controller=controller)
        if controller_kwargs:
            controller_configs = {**controller_configs, **dict(controller_kwargs)}
        camera_names = list(camera_names)
        # robosuite only produces `<cam>_image` entries for cameras in camera_names;
        # an unlisted render_camera would KeyError at the first render() (e.g. video
        # capture during evaluation), long after training started — fall back.
        if camera_names and render_camera not in camera_names:
            render_camera = camera_names[0]
        make_args = dict(
            env_configuration=env_config,
            robots=[robot],
            controller_configs=controller_configs,
            hard_reset=hard_reset,
            horizon=horizon,
            reward_scale=reward_scale,
            reward_shaping=reward_shaping,
            ignore_done=ignore_done,
            has_renderer=has_renderer,
            has_offscreen_renderer=has_offscreen_renderer or use_camera_obs,
            use_camera_obs=use_camera_obs,
            use_object_obs=use_object_obs,
            camera_names=camera_names,
            camera_heights=camera_heights,
            camera_widths=camera_widths,
            control_freq=control_freq,
        )
        if bddl_file:
            # LIBERO task described by a bddl file (reference robosuite.py:103-109)
            import libero.libero.envs.bddl_utils as BDDLUtils
            from libero.libero.envs import TASK_MAPPING

            if not os.path.exists(bddl_file):
                raise FileNotFoundError(bddl_file)
            problem_info = BDDLUtils.get_problem_info(bddl_file)
            self._env = TASK_MAPPING[problem_info["problem_name"]](
                bddl_file_name=bddl_file, **make_args
            )
        else:
            self._env = suite.make(env_name=env_name, **make_args)

        first_obs = self._env.reset()
        obs_spec = self._env.observation_spec()
        self._channels_first = channels_first
        self._from_pixels = bool(self._env.use_camera_obs)
        self._cameras = camera_names
        self._render_camera = render_camera
        self.name = f"{robot}_{type(self._env).__name__}"

        # raw-key selection (reference robosuite.py:128-154): by default every
        # available modality is exposed; ``keys`` restricts to a subset of the raw
        # robosuite observation keys.
        available: Dict[str, str] = {}  # raw robosuite key -> exposed dict key
        if self._from_pixels:
            for i, cam in enumerate(self._cameras):
                available[f"{cam}_image"] = "rgb" if i == 0 else f"rgb_{cam}"
        for idx in range(len(self._env.robots)):
            available[f"robot{idx}_proprio-state"] = "state" if idx == 0 else f"state{idx}"
        if use_object_obs and "object-state" in obs_spec:
            available["object-state"] = "object_state"
        if keys is not None:
            unknown = set(keys) - set(available)
            if unknown:
                raise ValueError(
                    f"unknown robosuite observation keys {sorted(unknown)}; "
                    f"available: {sorted(available)}"
                )
            available = {k: v for k, v in available.items() if k in set(keys)}
        self._key_map = available

        obs_space: Dict[str, spaces.Space] = {}
        for raw, exposed in available.items():
            if raw.endswith("_image"):
                shape = (
                    (3, camera_heights, camera_widths)
                    if channels_first
                    else (camera_heights, camera_widths, 3)
                )
                obs_space[exposed] = spaces.Box(0, 255, shape=shape, dtype=np.uint8)
            else:
                spec = obs_spec[raw]
                obs_space[exposed] = spaces.Box(-np.inf, np.inf, shape=spec.shape, dtype=np.float64)
        self.observation_space = spaces.Dict(obs_space)
        self.state_space = obs_space.get("state")

        a_low, a_high = self._env.action_spec
        self._true_action_space = spaces.Box(a_low, a_high, dtype=np.float32)
        self.action_space = spaces.Box(-1.0, 1.0, shape=self._true_action_space.shape, dtype=np.float32)
        self.reward_range = (0, self._env.reward_scale)
        self.render_mode = "rgb_array"
        self.current_state = first_obs

    def _denormalize(self, action: np.ndarray) -> np.ndarray:
        low, high = self._true_action_space.low, self._true_action_space.high
        action = (np.asarray(action, np.float64) + 1.0) / 2.0
        return (action * (high - low) + low).astype(np.float32)

    def _obs(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        obs = {}
        for raw_key, exposed in self._key_map.items():
            v = np.asarray(raw[raw_key])
            if raw_key.endswith("_image") and self._channels_first:
                v = v.transpose(2, 0, 1).copy()
            obs[exposed] = v
        return obs

    def step(self, action):
        raw, reward, done, info = self._env.step(self._denormalize(action))
        self.current_state = raw
        info["internal_state"] = raw
        # robosuite's flat `done` covers both the horizon and task success; without a
        # success flag it is reported as truncation (the horizon is the common case)
        return self._obs(raw), reward, False, bool(done), info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        raw = self._env.reset()
        self.current_state = raw
        return self._obs(raw), {}

    def render(self):
        return self._env._get_observations()[f"{self._render_camera}_image"]

    def close(self) -> None:
        self._env.close()
