"""Robosuite / LIBERO adapter (capability parity with reference
sheeprl/envs/robosuite.py:17-301; robosuite and libero are optional).

Exposes a robosuite manipulation task (or a LIBERO bddl task) as a gymnasium env
with a Dict observation: ``rgb`` (agentview camera) and/or ``state`` (robot
proprioception), and a [-1, 1]-normalized continuous action space.
"""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_ROBOSUITE_AVAILABLE

if not _IS_ROBOSUITE_AVAILABLE:
    raise ModuleNotFoundError("robosuite is not installed: pip install robosuite")

import os
from typing import Any, Dict, Optional

import gymnasium as gym
import numpy as np
import robosuite as suite
from gymnasium import spaces


class RobosuiteWrapper(gym.Env):
    def __init__(
        self,
        env_name: str,
        env_config: str,
        robot: str,
        bddl_file: Optional[str] = None,
        controller: Any = "OSC_POSE",
        hard_reset: bool = False,
        horizon: int = 500,
        reward_scale: float = 1.0,
        reward_shaping: bool = True,
        ignore_done: bool = True,
        has_renderer: bool = False,
        has_offscreen_renderer: bool = False,
        use_camera_obs: bool = False,
        control_freq: int = 20,
        channels_first: bool = True,
    ):
        make_args = dict(
            env_configuration=env_config,
            robots=[robot],
            controller_configs=suite.controllers.load_controller_config(default_controller=controller),
            hard_reset=hard_reset,
            horizon=horizon,
            reward_scale=reward_scale,
            reward_shaping=reward_shaping,
            ignore_done=ignore_done,
            has_renderer=has_renderer,
            has_offscreen_renderer=has_offscreen_renderer,
            use_camera_obs=use_camera_obs,
            control_freq=control_freq,
        )
        if bddl_file:
            # LIBERO task described by a bddl file (reference robosuite.py:103-109)
            import libero.libero.envs.bddl_utils as BDDLUtils
            from libero.libero.envs import TASK_MAPPING

            if not os.path.exists(bddl_file):
                raise FileNotFoundError(bddl_file)
            problem_info = BDDLUtils.get_problem_info(bddl_file)
            self._env = TASK_MAPPING[problem_info["problem_name"]](
                bddl_file_name=bddl_file, **make_args
            )
        else:
            self._env = suite.make(env_name=env_name, **make_args)

        first_obs = self._env.reset()
        obs_spec = self._env.observation_spec()
        self._channels_first = channels_first
        self._from_pixels = bool(self._env.use_camera_obs)
        self._from_vectors = "robot0_proprio-state" in obs_spec
        self.name = f"{robot}_{type(self._env).__name__}"

        obs_space: Dict[str, spaces.Space] = {}
        if self._from_pixels:
            h, w = first_obs["agentview_image"].shape[:2]
            shape = (3, h, w) if channels_first else (h, w, 3)
            obs_space["rgb"] = spaces.Box(0, 255, shape=shape, dtype=np.uint8)
        for idx in range(len(self._env.robots)):
            key = "state" if idx == 0 else f"state{idx}"
            spec = obs_spec[f"robot{idx}_proprio-state"]
            obs_space[key] = spaces.Box(-np.inf, np.inf, shape=spec.shape, dtype=np.float64)
        self.observation_space = spaces.Dict(obs_space)
        self.state_space = obs_space.get("state")

        a_low, a_high = self._env.action_spec
        self._true_action_space = spaces.Box(a_low, a_high, dtype=np.float32)
        self.action_space = spaces.Box(-1.0, 1.0, shape=self._true_action_space.shape, dtype=np.float32)
        self.reward_range = (0, self._env.reward_scale)
        self.render_mode = "rgb_array"
        self.current_state = first_obs

    def _denormalize(self, action: np.ndarray) -> np.ndarray:
        low, high = self._true_action_space.low, self._true_action_space.high
        action = (np.asarray(action, np.float64) + 1.0) / 2.0
        return (action * (high - low) + low).astype(np.float32)

    def _obs(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        obs = {}
        if self._from_pixels:
            rgb = raw["agentview_image"]
            obs["rgb"] = rgb.transpose(2, 0, 1).copy() if self._channels_first else rgb
        if self._from_vectors:
            for idx in range(len(self._env.robots)):
                key = "state" if idx == 0 else f"state{idx}"
                obs[key] = raw[f"robot{idx}_proprio-state"]
        return obs

    def step(self, action):
        raw, reward, done, info = self._env.step(self._denormalize(action))
        self.current_state = raw
        info["internal_state"] = raw
        # robosuite's flat `done` covers both the horizon and task success; without a
        # success flag it is reported as truncation (the horizon is the common case)
        return self._obs(raw), reward, False, bool(done), info

    def reset(self, *, seed: Optional[int] = None, options: Optional[Dict[str, Any]] = None):
        raw = self._env.reset()
        self.current_state = raw
        return self._obs(raw), {}

    def render(self):
        return self._env._get_observations()["agentview_image"]

    def close(self) -> None:
        self._env.close()
