"""``RunTelemetry``: the per-run observability facade every training loop threads.

One instance is built per run (``build_telemetry``, from the ``metric.telemetry``
config group) and driven by four hooks, each a no-op when the feature is off:

- ``attach_sampler(sampler)`` — once, after the replay sampler exists; wires the
  prefetch pipeline gauges (``Time/prefetch_wait``, ``Buffer/pipeline_occupancy``,
  ``Buffer/pipeline_staleness``).
- ``observe_train(units, losses)`` — after each train round; accumulates the
  gradient-step count that scales the in-loop MFU and keeps the latest host/device
  losses for the periodic loss-finiteness health guard.
- ``observe_learn(stats)`` — after each train round, with the fused program's
  device-side ``Learn/*`` scalar block (``utils/learn_stats.py``): grad norms
  pre/post clip, clip fraction, update-to-param ratios, param/moment norms,
  policy entropy, value stats, TD-error quantiles, dreamer KL balance. Only
  REFERENCES are kept (a bounded stride-doubling reservoir per window); the
  host fetches them in ONE ``jax.device_get`` at window cadence, so the
  zero-steady-state-host-transfer contract survives.
- ``observe_episodes(returns, lengths)`` — whenever episodes finish; feeds the
  per-window episode-return distribution (count/mean/p10/p50/p90) the
  reward-plateau detector and ``compare``'s learning-curve extraction read.
- ``register_program(name, fn, args, units=...)`` — once (guard with
  ``wants_program``) with the live fused train program; lowers it from avals
  (no execution, donation-safe) to read XLA's own FLOPs/memory numbers.
- ``step(policy_step)`` — once per loop iteration; drives the windowed profiler
  capture and, every ``telemetry.every`` policy steps, emits one telemetry window:
  TensorBoard gauges (``Mem/*``, ``Compile/*``, ``Perf/mfu``, ``Time/prefetch_*``,
  ``Buffer/pipeline_*``, ``Perf/sps``) plus one JSONL ``window`` event.
- ``close(policy_step, clean_exit=...)`` — from the loop's ``finally`` path;
  flushes the final window, writes the ``summary`` event ``bench.py`` attaches
  to BENCH JSONs (``clean_exit=False`` on an exception unwind, so crashed and
  preempted attempts leave end-of-attempt state too), and stops an open
  profiler window.

Every ``window`` event carries a ``phases`` wall-time breakdown (env
interaction, fused on-device rollout, replay/prefetch wait, device train,
checkpoint write, logging, eval/test, unattributed remainder — see
``_PHASE_TIMERS``) and every event the
stream identity triple ``rank``/``attempt``/``seq`` (``obs/jsonl.py``). At
window cadence the in-loop diagnosis (``metric.telemetry.diagnosis``, default
on) runs the ``obs/diagnose.py`` detector catalog over the run's own history
and emits live ``health`` events with ``status=diagnosis``.

Telemetry is rank-0-only and fully decoupled from ``metric.log_level``: a bench
run with logging off still produces ``telemetry.jsonl``. With
``metric.telemetry.enabled=false`` (the default) and ``metric.profiler.mode`` not
``window``, :func:`build_telemetry` returns the :class:`NullTelemetry` no-op and
the loops behave byte-for-byte as before.
"""

from __future__ import annotations

import os
import time
import warnings
from typing import Any, Dict, Mapping, Optional, Sequence

import numpy as np

from sheeprl_tpu.obs.compile_monitor import compile_snapshot, install_compile_monitor
from sheeprl_tpu.obs.jsonl import JsonlEventSink
from sheeprl_tpu.obs.profiler import ProfilerWindow, resolve_profiler_config
from sheeprl_tpu.utils.mfu import peak_flops, program_analysis
from sheeprl_tpu.utils.timer import timer

# cumulative counter keys of a sampler telemetry snapshot (diffed per window)
_PREFETCH_COUNTERS = (
    "wait_seconds",
    "sample_calls",
    "units",
    "occupancy_sum",
    "staleness_sum",
    "empty_waits",
)

# phase attribution: named loop phases and the Time/* span each one harvests.
# Every window event carries a ``phases`` breakdown built from these (plus
# ``replay_wait``, carved out of the train span from the sampler's wait counter,
# and the ``other`` remainder) with the invariant
# sum(phases.values()) ≈ window wall_seconds.
_PHASE_TIMERS = {
    "env": "Time/env_interaction_time",
    # fused on-device env+act (the Anakin loops: the rollout half of ONE jitted
    # rollout+train program, split from `train` by a one-shot measured
    # rollout-only wall time — algos/ppo/anakin.py). Host-env loops simply
    # contribute zero here.
    "rollout": "Time/rollout_time",
    "train": "Time/train_time",
    "checkpoint": "Time/checkpoint_time",
    "logging": "Time/logging_time",
    "eval": "Time/test_time",
}

# window/health events the in-loop diagnosis keeps (bounded history)
_HISTORY_CAP = 512

# learn-stats reservoir: at most this many per-round device-stat dicts are held
# per window; past it the reservoir drops every other entry and doubles its
# sampling stride, so coverage stays spread over the whole window at O(1) memory
_LEARN_RESERVOIR = 64

# episode returns kept per window for the return distribution (count stays exact)
_EPISODE_RESERVOIR = 4096

# the Learn/* key grammar lives in utils/learn_stats.py (the producers' module);
# importing it keeps the filter and the gauges on the one shared definition
from sheeprl_tpu.utils.learn_stats import LEARN_PREFIX, learn_keys

# live (built, not yet closed) RunTelemetry instances of this process. The loops
# close their own instance on the normal path; an exception that unwinds past a
# loop leaves its instance here, and cli.run_algorithm's finally flushes it with
# clean_exit=False — so a crashed/preempted attempt still writes its summary
# event (the supervisor's cross-attempt history needs end-of-attempt state).
# WeakSet: instances abandoned by unit tests drop out on GC instead of being
# closed by an unrelated later run.
import weakref

_LIVE_TELEMETRY: "weakref.WeakSet[RunTelemetry]" = weakref.WeakSet()


def close_all_live_telemetry(clean_exit: bool = False) -> None:
    """Close every still-open RunTelemetry of this process (crash path; the
    normal path leaves nothing live). Each instance flushes at the last policy
    step its loop reported."""
    for t in list(_LIVE_TELEMETRY):
        try:
            t.close(t._last_step, clean_exit=clean_exit)
        except Exception:
            continue


class NullTelemetry:
    """The disabled facade: every hook is an attribute-cheap no-op so call sites
    never branch on whether telemetry is configured."""

    enabled = False

    def attach_sampler(self, sampler: Any) -> None:
        pass

    def attach_dataflow(self, provider: Any) -> None:
        pass

    def wants_program(self, name: str) -> bool:
        return False

    def register_program(self, name: str, fn: Any, args: Sequence[Any], **_: Any) -> None:
        pass

    def observe_train(self, units: int, losses: Any = None) -> None:
        pass

    def observe_learn(self, stats: Any = None) -> None:
        pass

    def observe_episodes(
        self, returns: Any = None, lengths: Any = None, count: Any = None
    ) -> None:
        pass

    def observe_env_restart(self, count: int = 1) -> None:
        pass

    def emit_event(self, event: str, step: Optional[int] = None, **fields: Any) -> bool:
        return False

    def step(self, policy_step: int) -> None:
        pass

    def close(self, policy_step: Optional[int] = None, clean_exit: bool = True) -> None:
        pass


def _rss_bytes() -> Optional[int]:
    """Current resident set size of this process (Linux /proc, cheap)."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        return None


def rss_peak_bytes() -> Optional[int]:
    """Peak RSS (ru_maxrss is KiB on Linux) — the CPU stand-in for peak HBM."""
    try:
        import resource

        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024
    except Exception:
        return None


def device_memory(device: Any) -> Optional[Dict[str, int]]:
    """``{bytes_in_use, peak_bytes}`` from ``device.memory_stats()`` (TPU/GPU),
    or None on backends without allocator stats (host CPU)."""
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out: Dict[str, int] = {}
    if "bytes_in_use" in stats:
        out["bytes_in_use"] = int(stats["bytes_in_use"])
    if "peak_bytes_in_use" in stats:
        out["peak_bytes"] = int(stats["peak_bytes_in_use"])
    for extra in ("largest_alloc_size", "bytes_limit", "num_allocs"):
        if extra in stats:
            out[extra] = int(stats[extra])
    return out or None


def mesh_device_memory(devices: Sequence[Any]) -> Optional[Dict[str, Any]]:
    """Allocator stats across EVERY local mesh device: the top-level keys
    report the worst device (max — one hot model-axis shard is what OOMs a
    run, not the mean) and ``per_device`` carries the full breakdown when more
    than one device reports, so ``watch``/``diagnose`` can see a model-axis
    imbalance instead of a single-device guess. None on backends without
    allocator stats (host CPU)."""
    per = []
    for d in devices:
        mem = device_memory(d)
        if mem:
            per.append({"id": getattr(d, "id", None), **mem})
    if not per:
        return None
    out: Dict[str, Any] = {}
    for key in ("bytes_in_use", "peak_bytes", "largest_alloc_size", "bytes_limit", "num_allocs"):
        vals = [p[key] for p in per if key in p]
        if vals:
            out[key] = max(vals)
    if len(per) > 1:
        out["per_device"] = per
    return out or None


def _nonfinite_losses(losses: Any) -> list:
    """Names of non-finite entries in the latest observed losses. Accepts the
    loops' two shapes: a metrics mapping (dreamer host metrics) or an array of
    stacked losses (sac-family ``mean_losses``). Device arrays sync here — the
    guard runs once per telemetry window, not on the hot path."""
    bad = []
    if isinstance(losses, Mapping):
        for k, v in losses.items():
            try:
                if not np.all(np.isfinite(np.asarray(v))):
                    bad.append(str(k))
            except TypeError:
                continue
        return bad
    arr = np.asarray(losses)
    if arr.ndim == 0:
        return [] if np.isfinite(arr) else ["loss"]
    flat = arr.reshape(-1)
    return [f"loss[{i}]" for i in range(flat.shape[0]) if not np.isfinite(flat[i])]


class RunTelemetry:
    """See the module docstring for the hook contract. Construct via
    :func:`build_telemetry` (which handles rank gating and the disabled path)."""

    def __init__(
        self,
        fabric: Any,
        cfg: Any,
        log_dir: Optional[str],
        logger: Any = None,
        *,
        enabled: bool = True,
        profiler_cfg: Optional[Mapping[str, Any]] = None,
        jsonl_path: Optional[str] = None,
        rank: Optional[int] = None,
        http: bool = False,
    ) -> None:
        metric_cfg = cfg.metric
        tcfg = dict(metric_cfg.get("telemetry") or {})
        self.enabled = bool(enabled)
        self._logger = logger
        self._log_dir = log_dir

        # stream identity: rank = the writing process's launch-topology position
        # (role streams override it), attempt = supervisor restart counter
        self._rank = int(rank if rank is not None else getattr(fabric, "global_rank", 0) or 0)
        self._attempt = int(tcfg.get("attempt") or 0)

        pcfg = dict(profiler_cfg or resolve_profiler_config(metric_cfg))
        base_dump = pcfg.get("dir") or (os.path.join(log_dir, "profiler") if log_dir else "profiler")
        # attempt-scoped capture dir: a supervised restart must never collide
        # with (or overwrite) a prior attempt's capture. The resolved path is
        # written back into pcfg so the start event records where the captures
        # actually land, and the profiler stop event repeats it — `profile`
        # enumerates captures from the stream alone.
        dump_dir = os.path.join(base_dump, f"attempt_{self._attempt}")
        pcfg["dir"] = dump_dir
        self.profiler = ProfilerWindow(
            pcfg.get("mode", "off"), pcfg.get("start_step", 0), pcfg.get("num_steps", 0), dump_dir
        )
        self._last_profile: Optional[Dict[str, Any]] = None

        self.every = int(tcfg.get("every") or metric_cfg.get("log_every") or 5000)
        self.health_every = max(1, int(tcfg.get("health_every") or 1))
        self.abort_on_nonfinite = bool(tcfg.get("abort_on_nonfinite", False))
        self.compile_warmup_steps = int(tcfg.get("compile_warmup_steps") or 0)
        self._program_analysis = bool(tcfg.get("program_analysis", True))
        self.diagnosis = bool(tcfg.get("diagnosis", True))
        self.learning = bool(tcfg.get("learning", True))

        # SLO plane (obs/slo.py + obs/alerts.py): objectives resolved from
        # metric.telemetry.slo + a per-run slo.yaml. On a pure training stream
        # the serving objectives never see their signal (structural no-ops);
        # the training floors (step_rate/mfu/episode_return) default to null
        # targets and only judge when declared per experiment.
        self._slo_evaluator: Any = None
        self._alert_engine: Any = None
        if self.enabled:
            try:
                from sheeprl_tpu.obs.alerts import AlertEngine
                from sheeprl_tpu.obs.slo import SloEvaluator, load_objectives

                objectives = load_objectives(tcfg.get("slo"), run_dir=log_dir)
            except Exception:
                objectives = []
            if objectives:
                self._slo_evaluator = SloEvaluator(objectives)
                self._alert_engine = AlertEngine(objectives)

        self._sink: Optional[JsonlEventSink] = None
        if self.enabled and bool(tcfg.get("jsonl", True)):
            path = jsonl_path or tcfg.get("jsonl_path") or (
                os.path.join(log_dir, "telemetry.jsonl") if log_dir else "telemetry.jsonl"
            )
            self._sink = JsonlEventSink(path, rank=self._rank, attempt=self._attempt)

        self._device = getattr(fabric, "device", None)
        # every LOCAL mesh device: Mem/hbm_* gauges report the max across them
        # and window events carry a per-device breakdown (a 2-D model-axis
        # mesh can be imbalanced; one device's stats would hide that)
        try:
            local_pid = getattr(self._device, "process_index", 0)
            self._devices = [
                d
                for d in (getattr(fabric, "devices", None) or [])
                if getattr(d, "process_index", 0) == local_pid
            ] or ([self._device] if self._device is not None else [])
        except Exception:
            self._devices = [self._device] if self._device is not None else []
        self._peak_flops = peak_flops(self._device) if self._device is not None else None
        self._world_size = int(getattr(fabric, "world_size", 1) or 1)

        # window state
        self._anchor_step: Optional[int] = None
        self._anchor_time = 0.0
        self._start_step: Optional[int] = None
        self._start_time = 0.0
        self._timer_last: Dict[str, tuple] = {}  # name -> (total, reset generation)
        # "analysis" has no backing timer: register_program accounts its one-shot
        # program-introspection wall time there (it already shifts the open train
        # span past itself, so the window would otherwise leak it into `other`)
        self._window_phases: Dict[str, float] = {**{k: 0.0 for k in _PHASE_TIMERS}, "analysis": 0.0}
        self._total_phases: Dict[str, float] = {}
        self._total_wall_seconds = 0.0
        self._window_idx = 0
        self._window_train_units = 0
        self._total_train_units = 0
        self._total_train_seconds = 0.0
        self._last_losses: Any = None
        self._history: list = []  # window/health payloads for the in-loop diagnosis
        self._last_diagnosis_key: Any = None
        self._env_restarts = 0
        self._health_status = "unknown"
        self._sampler: Any = None
        self._prefetch_last: Optional[Dict[str, float]] = None
        self._prefetch_total: Dict[str, float] = {}
        self._programs: Dict[str, Dict[str, Any]] = {}
        self._mfu_flops_per_unit: Optional[float] = None
        self._compile_base = {"count": 0, "seconds": 0.0, "cache_hits": 0}
        self._compile_last = {"count": 0, "seconds": 0.0, "cache_hits": 0}
        self._last_mfu: Optional[float] = None
        self._peak_hbm = 0
        self._last_step: Optional[int] = None
        # learning-health state: per-window device-stat reservoir (references
        # only — fetched in one device_get at window cadence), per-window
        # episode-return sample, and run-level accumulators for the summary
        self._learn_window: list = []
        self._learn_stride = 1
        self._learn_seen = 0
        self._learn_rounds_total = 0
        self._learn_run_sums: Dict[str, float] = {}
        self._learn_run_counts: Dict[str, int] = {}
        self._learn_run_max: Dict[str, float] = {}
        self._last_learning: Optional[Dict[str, Any]] = None
        self._ep_returns: list = []
        self._ep_lengths: list = []
        self._ep_count_window = 0
        self._ep_count_total = 0
        self._ep_return_total = 0.0
        self._dataflow: Any = None  # attach_dataflow provider (experience plane)
        self._last_dataflow: Optional[Dict[str, Any]] = None
        # opt-in Prometheus endpoint (metric.telemetry.http_port): serves the
        # SAME gauges the window emit aggregates — no second bookkeeping path.
        # Only the primary facade binds it (`http=`): per-role streams of a gang
        # are separate processes that would race one configured port.
        self.metrics_endpoint = None
        if self.enabled and http:
            from sheeprl_tpu.obs.metrics_http import build_endpoint

            labels = {}
            run_name = getattr(cfg, "run_name", None)
            if run_name:
                labels["run"] = str(run_name)
            self.metrics_endpoint = build_endpoint(tcfg, labels=labels or None)
        _LIVE_TELEMETRY.add(self)

        if self.enabled:
            install_compile_monitor()
            self._compile_base = compile_snapshot()
            self._compile_last = dict(self._compile_base)
            dev = self._device
            # the run fingerprint makes this stream comparable-by-construction:
            # `compare`/`bench-diff` refuse-or-warn on mismatched fingerprints
            # instead of silently diffing different experiments (obs/fingerprint.py)
            from sheeprl_tpu.obs.fingerprint import run_fingerprint

            try:
                fingerprint: Optional[Dict[str, Any]] = run_fingerprint(cfg, fabric)
            except Exception:
                fingerprint = None
            from sheeprl_tpu.obs.schema import SCHEMA_VERSION

            start_event: Dict[str, Any] = dict(
                schema=SCHEMA_VERSION,
                platform=getattr(dev, "platform", None),
                device_kind=getattr(dev, "device_kind", None),
                world_size=self._world_size,
                peak_flops=self._peak_flops,
                every=self.every,
                compile_warmup_steps=self.compile_warmup_steps,
                profiler=dict(pcfg),
                fingerprint=fingerprint,
            )
            # the in-loop diagnosis needs the start event too (the recompile
            # detector reads compile_warmup_steps from it), sink or no sink
            self._append_history("start", start_event)
            if self._sink is not None:
                self._sink.emit("start", step=None, **start_event)

    # -- wiring ------------------------------------------------------------------

    def attach_sampler(self, sampler: Any) -> None:
        """Wire the replay sampler's pipeline gauges (any object exposing
        ``telemetry_snapshot()``; others are ignored)."""
        if self.enabled and hasattr(sampler, "telemetry_snapshot"):
            self._sampler = sampler
            self._prefetch_last = None

    def attach_dataflow(self, provider: Any) -> None:
        """Wire the experience-plane dataflow view (any object exposing
        ``dataflow_snapshot()`` — ``data/service.py``'s :class:`ActorDataflow` /
        :class:`LearnerDataflow`). Every window/summary event then carries a
        ``dataflow`` block (weight version/lag, sampled-row ages, ingest
        latency, queue depth) and the ``Service/*`` gauges light up."""
        if self.enabled and hasattr(provider, "dataflow_snapshot"):
            self._dataflow = provider

    def wants_program(self, name: str) -> bool:
        """Cheap per-iteration guard: True until ``name`` has been registered."""
        return self.enabled and self._program_analysis and name not in self._programs

    def register_program(
        self,
        name: str,
        fn: Any,
        args: Sequence[Any],
        kwargs: Optional[Mapping[str, Any]] = None,
        *,
        units: int = 1,
    ) -> None:
        """Introspect a live jitted program once: lower from avals (no execution,
        donation-safe), read XLA's FLOPs / bytes-accessed / memory_analysis, and
        emit a ``program`` event. ``units`` is how many logical gradient steps one
        call performs (a ``[G, ...]``-scanned program registers units=G) so MFU
        accounting is per gradient step regardless of fusion shape. The first
        registered program with FLOPs drives ``Perf/mfu``."""
        if not self.wants_program(name):
            return
        # record before analyzing: a failing analysis must not retry every round
        info: Dict[str, Any] = {"units": int(max(units, 1))}
        self._programs[name] = info
        # The memory_analysis() half needs a backend compile. The loop's first
        # real call just compiled the same HLO, so with the persistent compile
        # cache on (cli._setup_xla_env default) the AOT compile is a cache hit;
        # without it (SHEEPRL_JAX_CACHE=0) a remote-TPU compile would be a cold
        # multi-minute stall, so only the CPU backend compiles then — FLOPs
        # still come from the pre-compile lowering either way.
        import jax

        do_compile = bool(jax.config.jax_compilation_cache_dir) or (
            getattr(self._device, "platform", "cpu") == "cpu"
        )
        t0 = time.perf_counter()
        compiles_before = compile_snapshot()
        try:
            analysis = program_analysis(fn, args, kwargs, compile=do_compile)
        except Exception as exc:
            info["error"] = repr(exc)[:300]
            warnings.warn(f"telemetry: program analysis of {name!r} failed: {exc!r}")
            if self._sink is not None:
                self._sink.emit("program", name=name, error=info["error"])
            return
        finally:
            # the analysis must not pollute the run's own gauges: shift the open
            # Time/train_time span (the loops register inside it) past the
            # analysis, and credit its compile events out of the Compile/* base
            spent = time.perf_counter() - t0
            self._window_phases["analysis"] += spent
            span = timer.timers.get("Time/train_time")
            if span is not None and span._start is not None:
                span._start += spent
            compiles_after = compile_snapshot()
            for key in ("count", "seconds", "cache_hits"):
                own = compiles_after[key] - compiles_before[key]
                self._compile_base[key] += own
                self._compile_last[key] += own
        info.update(analysis)
        flops = analysis.get("flops")
        if flops:
            info["flops_per_unit"] = float(flops) / info["units"]
            if self._mfu_flops_per_unit is None:
                self._mfu_flops_per_unit = info["flops_per_unit"]
        if self._sink is not None:
            self._sink.emit("program", name=name, **info)

    # -- per-iteration hooks -----------------------------------------------------

    def observe_train(self, units: int, losses: Any = None) -> None:
        """Account ``units`` gradient steps for this window's MFU and keep the
        latest losses for the health guard (device arrays are fine — they are
        only synced at window boundaries)."""
        if not self.enabled:
            return
        self._window_train_units += int(units)
        self._total_train_units += int(units)
        if losses is not None:
            self._last_losses = losses

    def observe_learn(self, stats: Any = None) -> None:
        """Keep this train round's ``Learn/*`` device-stat block (references
        only — no sync here; see the module docstring). Accepts either a pure
        learn dict or a mixed metrics mapping (the dreamer family's) and keeps
        the ``Learn/``-prefixed subset, prefix stripped."""
        if not self.enabled or not self.learning or not isinstance(stats, Mapping):
            return
        learn = {k[len(LEARN_PREFIX) :]: v for k, v in learn_keys(stats).items()}
        if not learn:
            return
        self._learn_seen += 1
        self._learn_rounds_total += 1
        if (self._learn_seen - 1) % self._learn_stride:
            return
        self._learn_window.append(learn)
        if len(self._learn_window) >= _LEARN_RESERVOIR:
            # stride-doubling decimation: coverage stays spread across the
            # whole window instead of biasing to its head or tail
            self._learn_window = self._learn_window[::2]
            self._learn_stride *= 2

    def observe_episodes(
        self, returns: Any = None, lengths: Any = None, count: Optional[int] = None
    ) -> None:
        """Account finished episodes: exact counts + return sums, plus a bounded
        per-window return sample for the p10/p50/p90 distribution. ``count``
        overrides the episode count when the caller aggregates on device and
        only ships a batch mean (the Anakin loops: one sample, exact count)."""
        if not self.enabled or not self.learning or returns is None:
            return
        r = np.asarray(returns, dtype=np.float64).reshape(-1)
        if r.size == 0:
            return
        n = int(count) if count is not None else int(r.size)
        self._ep_count_window += n
        self._ep_count_total += n
        self._ep_return_total += float(r.mean()) * n
        room = _EPISODE_RESERVOIR - len(self._ep_returns)
        if room > 0:
            self._ep_returns.extend(float(x) for x in r[:room])
        if lengths is not None:
            ln = np.asarray(lengths, dtype=np.float64).reshape(-1)
            room = _EPISODE_RESERVOIR - len(self._ep_lengths)
            if room > 0:
                self._ep_lengths.extend(float(x) for x in ln[:room])

    def observe_env_restart(self, count: int = 1) -> None:
        """Account ``RestartOnException`` env restarts (previously invisible):
        a ``Health/env_restarts`` gauge plus an immediate ``health`` event — a
        flapping env is an operational signal, not noise to average away."""
        if not self.enabled or count <= 0:
            return
        self._env_restarts += int(count)
        event = {"status": "env_restart", "restarts": int(count), "total": self._env_restarts}
        self._append_history("health", event)
        if self._sink is not None:
            self._sink.emit("health", **event)

    def emit_event(self, event: str, step: Optional[int] = None, **fields: Any) -> bool:
        """Write an arbitrary event to the run's JSONL stream (used by the
        resilience subsystem for preempt/checkpoint/stall events). Returns False
        when no sink is open so the caller can fall back to its own."""
        if self._sink is None:
            return False
        self._sink.emit(event, step=step, **fields)
        return True

    def step(self, policy_step: int) -> None:
        """Once per loop iteration: advance the profiler window and emit a
        telemetry window every ``every`` policy steps. Idle cost is two int
        compares plus a method call."""
        self._last_step = policy_step
        was_started, was_stopped = self.profiler.started_at, self.profiler.stopped_at
        self.profiler.on_step(policy_step)
        if self._sink is not None:
            if self.profiler.started_at is not None and was_started is None:
                self._sink.emit("profiler", step=policy_step, action="start", dir=self.profiler.dump_dir)
            if (
                self.profiler.stopped_at is not None
                and was_stopped is None
                and self.profiler.started_at is not None  # a failed start never opened a trace
            ):
                self._sink.emit(
                    "profiler",
                    step=policy_step,
                    action="stop",
                    dir=self.profiler.dump_dir,
                    covered_steps=self.profiler.stopped_at - self.profiler.started_at,
                )
                self._emit_profile_analysis(policy_step)
        if not self.enabled:
            return
        if self._anchor_step is None:
            now = time.perf_counter()
            self._anchor_step = self._start_step = policy_step
            self._anchor_time = self._start_time = now
            # baseline the non-monotonic sources so window 0 diffs cleanly
            # (the one-shot analysis accumulator is kept — register_program can
            # legitimately run before the anchor in warmup-heavy loops)
            self._harvest_timers()
            analysis = self._window_phases["analysis"]
            self._window_phases = {**{k: 0.0 for k in _PHASE_TIMERS}, "analysis": analysis}
            self._prefetch_delta()
            return
        # harvest EVERY iteration, not just at window boundaries: the metric log
        # sites reset the timer registry on their own (log_every) cadence, and a
        # reset between two windows would otherwise drop everything accrued
        # before it. The loops call step() right before the log block, so the
        # read always lands ahead of the reset.
        self._harvest_timers()
        if policy_step - self._anchor_step >= self.every:
            self._emit_window(policy_step)

    def close(self, policy_step: Optional[int] = None, clean_exit: bool = True) -> None:
        """Flush the last partial window, write the run ``summary`` event and
        finalize the profiler/JSONL artifacts. The loops call this from a
        ``finally`` path, so a crashed or preempted run still leaves its summary
        — ``clean_exit=False`` marks an exception unwind (the supervisor's
        cross-attempt history reads end-of-attempt state from it). Idempotent:
        a second call is a no-op."""
        _LIVE_TELEMETRY.discard(self)
        window_truncated = self.profiler.active
        self.profiler.close(policy_step)
        if window_truncated and self._sink is not None and self.profiler.started_at is not None:
            # pair the earlier 'start': a window still open at loop exit is
            # finalized here, so consumers always see a start/stop pair
            self._sink.emit(
                "profiler",
                step=policy_step,
                action="stop",
                dir=self.profiler.dump_dir,
                covered_steps=(self.profiler.stopped_at or self.profiler.started_at)
                - self.profiler.started_at,
                truncated=True,
            )
            self._emit_profile_analysis(policy_step)
        if not self.enabled:
            return
        if (
            policy_step is not None
            and self._anchor_step is not None
            and policy_step > self._anchor_step
        ):
            self._emit_window(policy_step, final=True)
        if self._sink is not None:
            total_steps = (
                (policy_step - self._start_step)
                if (policy_step is not None and self._start_step is not None)
                else 0
            )
            wall = time.perf_counter() - self._start_time if self._start_step is not None else 0.0
            snap = compile_snapshot()
            hbm = mesh_device_memory(self._devices)
            peak_hbm = max(self._peak_hbm, (hbm or {}).get("peak_bytes", 0)) or None
            overall_mfu = None
            if (
                self._mfu_flops_per_unit
                and self._peak_flops
                and self._total_train_seconds > 0
                and self._total_train_units > 0
            ):
                overall_mfu = (
                    self._mfu_flops_per_unit * self._total_train_units / self._total_train_seconds
                ) / self._peak_flops
            phases_total = {k: round(v, 3) for k, v in self._total_phases.items()}
            attributed = None
            if self._total_wall_seconds > 0:
                named = sum(v for k, v in self._total_phases.items() if k != "other")
                attributed = round(min(named / self._total_wall_seconds, 1.0), 4)
            self._sink.emit(
                "summary",
                step=policy_step,
                clean_exit=bool(clean_exit),
                windows=self._window_idx,
                total_steps=total_steps,
                wall_seconds=round(wall, 3),
                sps=round(total_steps / wall, 3) if wall > 0 else None,
                train_units=self._total_train_units,
                train_seconds=round(self._total_train_seconds, 3),
                phases=phases_total or None,
                attributed_fraction=attributed,
                mfu=overall_mfu,
                compile={
                    "count": snap["count"] - self._compile_base["count"],
                    "seconds": round(snap["seconds"] - self._compile_base["seconds"], 3),
                    # persistent-cache hits counted inside `count`: count minus
                    # cache_hits is the COLD compiles (the fleet cold-start gauge)
                    "cache_hits": snap.get("cache_hits", 0)
                    - self._compile_base.get("cache_hits", 0),
                },
                hbm_peak_bytes=peak_hbm,
                rss_peak_bytes=rss_peak_bytes(),
                prefetch=self._prefetch_total or None,
                env_restarts=self._env_restarts,
                health=self._health_status,
                # end-of-run dataflow state (weight lag, row ages, queue): the
                # numbers bench.py attaches under conditions.dataflow; absent
                # entirely on runs without an experience plane
                dataflow=self._dataflow_snapshot() or None,
                # run-level learning rollup: per-stat run means, grad-norm run
                # maxes, episode totals + the last window's block — what
                # bench.py attaches under conditions.learning and the fleet
                # leaderboard rolls up
                learning=self._learning_summary() or None,
                programs={k: v for k, v in self._programs.items()},
                # final error-budget accounting; None when no objective ever
                # saw its signal (pure training stream with default objectives)
                slo=(
                    self._slo_evaluator.slo_block()
                    if self._slo_evaluator is not None
                    else None
                ),
            )
            self._sink.close()
            self._sink = None
        if self.metrics_endpoint is not None:
            self.metrics_endpoint.close()
            self.metrics_endpoint = None
        self.enabled = False

    # -- internals ---------------------------------------------------------------

    def _timer_delta(self, name: str) -> float:
        """Non-destructive delta of a named timer's accumulated seconds since the
        last harvest, exact across the log sites' ``to_dict(reset=True)``: the
        timer's reset generation tells a reset apart from plain accrual (a
        magnitude heuristic would miss a reset whose post-reset accrual already
        caught up with the pre-reset total, e.g. log_every <= steps-per-iter)."""
        t = timer.timers.get(name)
        if t is None:
            return 0.0
        cur, resets = float(t._total), t._resets
        last, last_resets = self._timer_last.get(name, (0.0, resets))
        # after a reset the whole current total is fresh accrual; harvesting
        # every step() (right before the loops' log block, the only reset site)
        # makes the pre-reset remainder since the last harvest zero
        delta = cur if resets != last_resets else cur - last
        self._timer_last[name] = (cur, resets)
        return max(delta, 0.0)

    def _harvest_timers(self) -> None:
        """Accumulate the named phase timers' fresh seconds into the current
        window (see ``_PHASE_TIMERS``; loops that lack a span simply contribute
        zero to that phase)."""
        for phase, name in _PHASE_TIMERS.items():
            self._window_phases[phase] += self._timer_delta(name)

    def _append_history(self, event: str, payload: Dict[str, Any]) -> None:
        """Feed the in-loop diagnosis history (bounded; same payloads the sink
        writes — including the wall-clock ``time`` the sink would stamp, which
        the env-restart clustering detector reads — so the offline and live
        detectors see the same shapes)."""
        self._history.append({"event": event, "time": round(time.time(), 3), **payload})
        if len(self._history) > _HISTORY_CAP:
            del self._history[: len(self._history) - _HISTORY_CAP]

    def _run_live_diagnosis(self, policy_step: int) -> None:
        """Run the detector catalog over this run's own window/health history and
        emit a ``health`` event (``status=diagnosis``) when the finding set
        changes — the live half of ``obs/diagnose.py``'s offline CLI."""
        from sheeprl_tpu.obs.diagnose import run_detectors

        findings = run_detectors(self._history)
        key = tuple(sorted((f["detector"], f["severity"]) for f in findings))
        if findings and key != self._last_diagnosis_key and self._sink is not None:
            self._sink.emit(
                "health",
                step=policy_step,
                status="diagnosis",
                findings=[
                    {k: f[k] for k in ("detector", "severity", "summary", "suggestion")}
                    for f in findings
                ],
            )
        self._last_diagnosis_key = key

    def _emit_profile_analysis(self, policy_step: Optional[int]) -> None:
        """Parse the window capture the profiler just finalized and emit the
        schema-registered ``profile_analysis`` event (obs/xprof.py). The
        fractions are cached so the next window's ``Perf/xla_*`` gauges carry
        them to TB + the Prometheus endpoint. Parsing a capture must never take
        the run down — any failure leaves the raw capture for the offline
        ``sheeprl.py profile`` verb."""
        if self._sink is None:
            return
        try:
            from sheeprl_tpu.obs.xprof import analyze_capture, profile_event_payload

            analysis = analyze_capture(
                self.profiler.dump_dir,
                self._programs,
                peak_flops=self._peak_flops,
                device_kind=getattr(self._device, "device_kind", None),
            )
        except Exception:
            return
        if analysis is None:
            return
        self._last_profile = analysis
        self._sink.emit("profile_analysis", step=policy_step, **profile_event_payload(analysis))

    def _prefetch_delta(self) -> Optional[Dict[str, Any]]:
        if self._sampler is None:
            return None
        try:
            snap = self._sampler.telemetry_snapshot()
        except Exception:
            return None
        last = self._prefetch_last or {}
        delta = {k: float(snap.get(k, 0.0)) - float(last.get(k, 0.0)) for k in _PREFETCH_COUNTERS}
        self._prefetch_last = {k: float(snap.get(k, 0.0)) for k in _PREFETCH_COUNTERS}
        for k, v in delta.items():
            self._prefetch_total[k] = self._prefetch_total.get(k, 0.0) + v
        calls = max(delta["sample_calls"], 1.0)
        units = max(delta["units"], 1.0)
        out = {
            "wait_seconds": delta["wait_seconds"],
            "sample_calls": int(delta["sample_calls"]),
            "units": int(delta["units"]),
            "occupancy": delta["occupancy_sum"] / calls,
            "staleness": delta["staleness_sum"] / units,
            "empty_waits": int(delta["empty_waits"]),
            "pipeline_len": int(snap.get("pipeline_len", 0)),
            "depth": int(snap.get("depth", 0)),
            "is_async": bool(snap.get("is_async", False)),
        }
        # device-ring storage gauges (DeviceRingSampler.telemetry_snapshot):
        # occupancy = fill/capacity, overwritten = slots lost to wraparound
        if snap.get("ring_capacity"):
            capacity = float(snap["ring_capacity"])
            out["ring"] = {
                "fill": int(snap.get("ring_fill", 0)),
                "capacity": int(capacity),
                "occupancy": float(snap.get("ring_fill", 0)) / max(capacity, 1.0),
                "overwritten": int(snap.get("ring_overwritten", 0)),
            }
        return out

    def _dataflow_snapshot(self) -> Optional[Dict[str, Any]]:
        if self._dataflow is None:
            return None
        try:
            snap = self._dataflow.dataflow_snapshot()
        except Exception:
            return self._last_dataflow  # a dying KV plane must not kill the window
        self._last_dataflow = snap
        return snap

    @staticmethod
    def _dataflow_gauges(dataflow: Optional[Mapping[str, Any]]) -> Dict[str, float]:
        """The ``Service/*`` gauge projection of one dataflow block (only the
        keys the role actually reports)."""
        if not dataflow:
            return {}
        gauges: Dict[str, float] = {}
        lag = dataflow.get("weight_lag")
        if isinstance(lag, Mapping):
            lag = lag.get("max")
        if isinstance(lag, (int, float)):
            gauges["Service/weight_lag"] = float(lag)
        row_age = (dataflow.get("row_age") or {}).get("seconds") if dataflow.get("row_age") else None
        if isinstance(row_age, Mapping):
            if row_age.get("p50") is not None:
                gauges["Service/row_age_p50"] = float(row_age["p50"])
            if row_age.get("p99") is not None:
                gauges["Service/row_age_p99"] = float(row_age["p99"])
        latency = dataflow.get("ingest_latency_ms")
        if isinstance(latency, Mapping) and latency.get("p99") is not None:
            gauges["Service/ingest_latency_p99_ms"] = float(latency["p99"])
        for key, gauge in (
            ("queue_depth", "Service/queue_depth"),
            ("rows_per_sec", "Service/rows_per_sec"),
            ("inflight", "Service/ingest_inflight"),
        ):
            value = dataflow.get(key)
            if isinstance(value, (int, float)):
                gauges[gauge] = float(value)
        return gauges

    def _learning_block(self) -> Optional[Dict[str, Any]]:
        """Fetch the window's learn-stat reservoir (ONE ``jax.device_get`` of
        scalar buffers — the only host transfer the learning plane ever pays)
        and distill it plus the episode sample into the window event's
        ``learning`` block. Resets the per-window state. None when the window
        saw neither train stats nor episodes."""
        if not self._learn_window and self._ep_count_window == 0:
            return None
        stats: Dict[str, Optional[float]] = {}
        nonfinite: list = []
        if self._learn_window:
            try:
                import jax

                host = jax.device_get(self._learn_window)
            except Exception:
                host = []
            if host:
                keys = sorted({k for entry in host for k in entry})
                series: Dict[str, np.ndarray] = {}
                for k in keys:
                    vals = np.asarray(
                        [float(np.asarray(e[k])) for e in host if k in e], dtype=np.float64
                    )
                    series[k] = vals
                    finite = vals[np.isfinite(vals)]
                    if finite.size < vals.size:
                        nonfinite.append(k)
                    if finite.size == 0:
                        stats[k] = None
                    elif k.startswith("grad_norm_max/"):
                        stats[k] = round(float(finite.max()), 6)
                    else:
                        stats[k] = round(float(finite.mean()), 6)
                # single-step programs emit no per-round max: synthesize the
                # window max from the per-round grad norms so the explosion
                # detector always has a spike-sensitive series to read
                for k, vals in series.items():
                    if not k.startswith("grad_norm/"):
                        continue
                    group = k[len("grad_norm/") :]
                    max_key = f"grad_norm_max/{group}"
                    if max_key not in stats:
                        finite = vals[np.isfinite(vals)]
                        if finite.size:
                            stats[max_key] = round(float(finite.max()), 6)
        episodes: Optional[Dict[str, Any]] = None
        if self._ep_count_window:
            r = np.asarray(self._ep_returns, dtype=np.float64)
            episodes = {
                "count": int(self._ep_count_window),
                "return_mean": round(float(r.mean()), 4),
                "return_p10": round(float(np.quantile(r, 0.1)), 4),
                "return_p50": round(float(np.quantile(r, 0.5)), 4),
                "return_p90": round(float(np.quantile(r, 0.9)), 4),
            }
            if self._ep_lengths:
                episodes["len_mean"] = round(float(np.mean(self._ep_lengths)), 2)
        samples = len(self._learn_window)
        for k, v in stats.items():
            if v is None:
                continue
            if k.startswith("grad_norm_max/"):
                self._learn_run_max[k] = max(self._learn_run_max.get(k, float("-inf")), v)
            else:
                self._learn_run_sums[k] = self._learn_run_sums.get(k, 0.0) + v * samples
                self._learn_run_counts[k] = self._learn_run_counts.get(k, 0) + samples
        block: Dict[str, Any] = {"rounds": int(self._learn_seen)}
        if stats:
            block["stats"] = stats
        if episodes is not None:
            block["episodes"] = episodes
        if nonfinite:
            block["nonfinite"] = nonfinite
        self._last_learning = block
        # reset the per-window state
        self._learn_window = []
        self._learn_stride = 1
        self._learn_seen = 0
        self._ep_returns = []
        self._ep_lengths = []
        self._ep_count_window = 0
        return block

    @staticmethod
    def _learning_gauges(learning: Optional[Mapping[str, Any]]) -> Dict[str, float]:
        """The ``Learn/*`` gauge projection of one learning block (finite stats
        plus the episode-return mean/count — what the Prometheus endpoint and
        the metric logger see)."""
        if not learning:
            return {}
        gauges: Dict[str, float] = {}
        for k, v in (learning.get("stats") or {}).items():
            if isinstance(v, (int, float)) and np.isfinite(v):
                gauges[f"{LEARN_PREFIX}{k}"] = float(v)
        episodes = learning.get("episodes") or {}
        if isinstance(episodes.get("return_mean"), (int, float)):
            gauges[f"{LEARN_PREFIX}ep_return_mean"] = float(episodes["return_mean"])
        if episodes.get("count"):
            gauges[f"{LEARN_PREFIX}ep_count"] = float(episodes["count"])
        return gauges

    def _learning_summary(self) -> Optional[Dict[str, Any]]:
        """Run-level learning rollup for the summary event: per-stat run means
        (sample-weighted across windows), run-max grad norms, exact episode
        totals, and the last window's block (the freshest state — what the
        fleet leaderboard ranks on)."""
        if self._learn_rounds_total == 0 and self._ep_count_total == 0:
            return None
        stats = {
            k: round(s / max(self._learn_run_counts.get(k, 1), 1), 6)
            for k, s in self._learn_run_sums.items()
        }
        stats.update({k: round(v, 6) for k, v in self._learn_run_max.items()})
        out: Dict[str, Any] = {"rounds": int(self._learn_rounds_total)}
        if stats:
            out["stats"] = stats
        if self._ep_count_total:
            out["episodes"] = {
                "count": int(self._ep_count_total),
                "return_mean": round(self._ep_return_total / self._ep_count_total, 4),
            }
        if self._last_learning is not None:
            out["last"] = {
                k: v for k, v in self._last_learning.items() if k in ("stats", "episodes")
            }
        return out

    def _check_health(self, policy_step: int) -> Optional[Dict[str, Any]]:
        if self._window_idx % self.health_every != 0:
            return None
        if self._last_losses is None:
            self._health_status = "no-train"
            return {"status": "no-train"}
        bad = _nonfinite_losses(self._last_losses)
        self._health_status = "nonfinite" if bad else "ok"
        event = {"status": self._health_status}
        if bad:
            event["nonfinite"] = bad
        return event

    def _emit_window(self, policy_step: int, final: bool = False) -> None:
        now = time.perf_counter()
        steps = policy_step - (self._anchor_step or 0)
        wall = max(now - self._anchor_time, 1e-9)
        sps = steps / wall

        self._harvest_timers()  # pick up anything accrued since the last step()
        train_seconds = self._window_phases["train"]
        env_seconds = self._window_phases["env"]
        self._total_train_seconds += train_seconds

        snap = compile_snapshot()
        window_compiles = snap["count"] - self._compile_last["count"]
        window_compile_seconds = snap["seconds"] - self._compile_last["seconds"]
        self._compile_last = dict(snap)
        total_compiles = snap["count"] - self._compile_base["count"]
        total_compile_seconds = snap["seconds"] - self._compile_base["seconds"]
        if (
            window_compiles > 0
            and not final  # the close-time window absorbs the end-of-run
            # test's first-time eval compiles — legitimate, not shape churn
            and self.compile_warmup_steps > 0
            and policy_step > self.compile_warmup_steps
        ):
            warnings.warn(
                f"telemetry: {window_compiles} unexpected XLA recompile(s) "
                f"({window_compile_seconds:.1f}s) after warmup (policy step {policy_step}) — "
                "look for shape churn (varying gradient-step counts, env batch changes)"
            )

        hbm = mesh_device_memory(self._devices)
        if hbm and hbm.get("peak_bytes"):
            self._peak_hbm = max(self._peak_hbm, hbm["peak_bytes"])
        rss = _rss_bytes()
        rss_peak = rss_peak_bytes()

        mfu = None
        if (
            self._mfu_flops_per_unit
            and self._peak_flops
            and train_seconds > 0
            and self._window_train_units > 0
        ):
            mfu = (self._mfu_flops_per_unit * self._window_train_units / train_seconds) / self._peak_flops
        self._last_mfu = mfu

        prefetch = self._prefetch_delta()
        dataflow = self._dataflow_snapshot()
        learning = self._learning_block()
        health = self._check_health(policy_step)

        # phase attribution: replay/prefetch wait is carved OUT of the train span
        # (sampler.sample runs inside `with timer("Time/train_time")` in every
        # off-policy loop), so `train` below is pure device-train time and the
        # named phases tile the window: sum(phases) + other ≈ wall_seconds.
        # `train_seconds`/MFU keep the PR 2 semantics (wait included) unchanged.
        replay_wait = 0.0
        if prefetch is not None:
            replay_wait = min(max(float(prefetch["wait_seconds"]), 0.0), train_seconds)
        phases = {
            "env": env_seconds,
            "rollout": self._window_phases["rollout"],
            "replay_wait": replay_wait,
            "train": train_seconds - replay_wait,
            "checkpoint": self._window_phases["checkpoint"],
            "logging": self._window_phases["logging"],
            "eval": self._window_phases["eval"],
            "analysis": self._window_phases["analysis"],
        }
        phases["other"] = max(wall - sum(phases.values()), 0.0)
        phases = {k: round(v, 4) for k, v in phases.items()}
        for k, v in phases.items():
            self._total_phases[k] = self._total_phases.get(k, 0.0) + v
        self._total_wall_seconds += wall

        gauges: Dict[str, float] = {
            "Perf/sps": sps,
            "Compile/count": float(total_compiles),
            "Compile/seconds": float(total_compile_seconds),
        }
        if hbm is not None:
            if "bytes_in_use" in hbm:
                gauges["Mem/hbm_bytes_in_use"] = float(hbm["bytes_in_use"])
            if "peak_bytes" in hbm:
                gauges["Mem/hbm_peak"] = float(hbm["peak_bytes"])
        if rss is not None:
            gauges["Mem/host_rss_bytes"] = float(rss)
        if rss_peak is not None:
            gauges["Mem/host_rss_peak"] = float(rss_peak)
        if mfu is not None:
            gauges["Perf/mfu"] = float(mfu)
        if prefetch is not None:
            gauges["Time/prefetch_wait"] = float(prefetch["wait_seconds"])
            gauges["Buffer/pipeline_occupancy"] = float(prefetch["occupancy"])
            gauges["Buffer/pipeline_staleness"] = float(prefetch["staleness"])
            ring = prefetch.get("ring")
            if ring is not None:
                gauges["Buffer/ring_fill"] = float(ring["fill"])
                gauges["Buffer/ring_occupancy"] = float(ring["occupancy"])
                gauges["Buffer/ring_overwritten"] = float(ring["overwritten"])
        if self._last_profile is not None:
            # the latest window capture's attribution (obs/xprof.py): fractions
            # of device time, so TB/Prometheus trend them across captures
            fractions = self._last_profile.get("fractions") or {}
            gauges["Perf/xla_comm_fraction"] = float(fractions.get("comm", 0.0))
            gauges["Perf/xla_mxu_fraction"] = float(fractions.get("mxu", 0.0))
            gauges["Perf/xla_idle_fraction"] = float(fractions.get("idle", 0.0))
        if self._env_restarts > 0:
            gauges["Health/env_restarts"] = float(self._env_restarts)
        gauges.update(self._dataflow_gauges(dataflow))
        gauges.update(self._learning_gauges(learning))
        if self._logger is not None:
            self._logger.log_metrics(gauges, policy_step)
        if self.metrics_endpoint is not None:
            self.metrics_endpoint.update({**gauges, "Run/policy_step": float(policy_step)})

        window_event: Dict[str, Any] = dict(
            step=policy_step,
            window=self._window_idx,
            final=bool(final),
            steps=steps,
            wall_seconds=round(wall, 4),
            sps=round(sps, 3),
            train_units=self._window_train_units,
            train_seconds=round(train_seconds, 4),
            env_seconds=round(env_seconds, 4),
            phases=phases,
            mfu=mfu,
            hbm=hbm,
            rss_bytes=rss,
            rss_peak_bytes=rss_peak,
            compile={
                "count": total_compiles,
                "seconds": round(total_compile_seconds, 3),
                "window_count": window_compiles,
                "window_seconds": round(window_compile_seconds, 3),
            },
            prefetch=prefetch,
        )
        if dataflow is not None:
            window_event["dataflow"] = dataflow
        if learning is not None:
            window_event["learning"] = learning
        # SLO plane: feed this window to the burn-rate evaluator, attach the
        # budget block, advance the stateful alert engine — the same machinery
        # `sheeprl.py slo` replays offline, so verdicts cannot drift
        alert_transitions: list = []
        slo_snapshot: Dict[str, Any] = {}
        if self._slo_evaluator is not None:
            self._slo_evaluator.observe_window(window_event)
            slo_block = self._slo_evaluator.slo_block()
            if slo_block is not None:
                window_event["slo"] = slo_block
            slo_snapshot = self._slo_evaluator.snapshot()
            alert_transitions = self._alert_engine.evaluate(slo_snapshot)
        self._append_history("window", window_event)
        if self._sink is not None:
            self._sink.emit("window", **window_event)
            if health is not None:
                self._append_history("health", {"step": policy_step, **health})
                self._sink.emit("health", step=policy_step, **health)
            for transition in alert_transitions:
                self._sink.emit("alert", step=policy_step, **transition)
                # critical alerts escalate through the existing health path
                if (
                    transition["status"] == "firing"
                    and transition.get("severity") == "critical"
                ):
                    self._sink.emit(
                        "health",
                        step=policy_step,
                        status="alert",
                        findings=[
                            {
                                "detector": f"slo:{transition['name']}",
                                "severity": "critical",
                                "summary": (
                                    f"SLO alert {transition['name']} firing "
                                    f"(budget remaining {transition.get('budget_remaining')})"
                                ),
                                "suggestion": "see `sheeprl.py slo` for the budget breakdown",
                            }
                        ],
                    )
        if self.metrics_endpoint is not None and slo_snapshot:
            # merged on top of this window's replace=True push; the NEXT window's
            # full push wipes anything resolved, so firing gauges never linger
            slo_gauges: Dict[str, float] = {}
            worst_remaining = None
            for name, stats in slo_snapshot.items():
                if not stats.get("samples"):
                    continue
                remaining = stats.get("budget_remaining")
                slo_gauges[f"Slo/budget_remaining/{name}"] = remaining
                if worst_remaining is None or remaining < worst_remaining:
                    worst_remaining = remaining
            if worst_remaining is not None:
                slo_gauges["Slo/worst_budget_remaining"] = worst_remaining
            firing = self._alert_engine.firing()
            slo_gauges["Alerts/firing"] = float(len(firing))
            for name in firing:
                slo_gauges[f"Alerts/firing/{name}"] = 1.0
            self.metrics_endpoint.update(slo_gauges, replace=False)
        if self.diagnosis:
            self._run_live_diagnosis(policy_step)

        self._window_idx += 1
        self._window_train_units = 0
        self._window_phases = {**{k: 0.0 for k in _PHASE_TIMERS}, "analysis": 0.0}
        self._anchor_step = policy_step
        self._anchor_time = now

        if health is not None and health.get("nonfinite") and self.abort_on_nonfinite:
            raise RuntimeError(
                f"telemetry.abort_on_nonfinite: non-finite training losses at policy step "
                f"{policy_step}: {health['nonfinite']}"
            )


def build_telemetry(fabric: Any, cfg: Any, log_dir: Optional[str], logger: Any = None):
    """Build the run's telemetry facade from the ``metric.telemetry`` +
    ``metric.profiler`` config groups. Rank-0-only (SPMD: one controller process
    observes the whole mesh; MPMD roles build their own). Returns the
    :class:`NullTelemetry` no-op when neither full telemetry nor a windowed
    profiler capture is configured — the zero-overhead off path."""
    if not getattr(fabric, "is_global_zero", True):
        return NullTelemetry()
    metric_cfg = cfg.metric
    tcfg = metric_cfg.get("telemetry") or {}
    enabled = bool(tcfg.get("enabled", False))
    pcfg = resolve_profiler_config(metric_cfg)
    if not enabled and pcfg["mode"] != "window":
        return NullTelemetry()
    return RunTelemetry(fabric, cfg, log_dir, logger, enabled=enabled, profiler_cfg=pcfg, http=True)


def role_stream_path(cfg: Any, role: str) -> str:
    """Per-role sibling of the run's main telemetry stream: the configured
    ``jsonl_path`` with ``.<role>`` spliced in before the extension, or
    ``telemetry.<role>.jsonl`` in the run-base dir — either way a path
    ``obs/streams.py`` discovers next to the player's stream."""
    tcfg = (cfg.metric.get("telemetry") or {}) if cfg.metric is not None else {}
    base = tcfg.get("jsonl_path")
    if base:
        root, ext = os.path.splitext(str(base))
        return f"{root}.{role}{ext or '.jsonl'}"
    from sheeprl_tpu.utils.logger import run_base_dir

    return str(run_base_dir(cfg.root_dir, cfg.run_name) / f"telemetry.{role}.jsonl")


def build_role_telemetry(fabric: Any, cfg: Any, role: str, *, rank: int, leader: bool = True):
    """Telemetry stream for a decoupled MPMD role process (the learner slice of
    sac_decoupled / ppo_decoupled / dv3_decoupled). The player's rank-0 stream
    cannot see learner-side train time, HBM or compiles — this gives the role
    its own ``telemetry.<role>.jsonl`` (one per role: only the slice ``leader``
    writes; the other slice members get the no-op), merged with the player's by
    ``obs/streams.py``. No logger, no profiler — the JSONL stream only."""
    tcfg = cfg.metric.get("telemetry") or {}
    if not (bool(tcfg.get("enabled", False)) and bool(tcfg.get("jsonl", True)) and leader):
        return NullTelemetry()
    return RunTelemetry(
        fabric,
        cfg,
        None,
        None,
        enabled=True,
        profiler_cfg={"mode": "off", "start_step": 0, "num_steps": 0, "dir": None},
        jsonl_path=role_stream_path(cfg, role),
        rank=rank,
    )
