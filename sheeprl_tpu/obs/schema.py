"""Versioned schema for the ``telemetry.jsonl`` event stream.

The stream has many producers (``RunTelemetry``, ``ServingTelemetry``, the
resilience monitor/supervisors, the experience-service roles, the fleet
runner) and many consumers (``diagnose``, ``watch``, ``compare``, ``trace``,
``bench.py``) — and the consumers deliberately parse with defaults, so a
producer-side field rename would not crash anything; it would silently turn a
detector into a no-op. This module makes that drift FAIL LOUDLY instead: every
event type has a declared field table, CI validates the recorded fixtures
(``tests/data/recorded_run*``) and the live-smoke outputs against it, and a
producer adding/renaming a field must update the table (and, for a breaking
change, bump :data:`SCHEMA_VERSION`) in the same commit.

Validation policy, by event family:

- **core telemetry events** (``start`` / ``window`` / ``summary`` /
  ``profiler``) are validated STRICTLY: every field must be declared with a
  matching type, unknown fields are errors. These are the events the consumer
  stack keys on.
- **open events** (``program`` / ``health`` / ``service`` and the resilience /
  fleet lifecycle events) validate their declared fields' types but tolerate
  extras — their payloads are deliberately extensible (a fault event carries
  whatever its fault kind needs).
- **identity fields** (``rank`` / ``attempt`` / ``seq`` / ``time``) are
  optional everywhere: pre-identity recordings (PR 2-era fixtures) must keep
  validating, exactly as the stream readers keep parsing them.

``start`` events stamp ``schema`` = :data:`SCHEMA_VERSION`; a stream stamped
NEWER than this reader fails validation (the reader is too old to judge it).
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "SCHEMA_VERSION",
    "validate_event",
    "validate_events",
    "validate_stream",
]

# bump on a BREAKING change to a core event's shape (a rename, a type change, a
# removed field); adding an optional field is compatible — declare it below.
SCHEMA_VERSION = 1

_NUM = (int, float)
_STR = (str,)
_BOOL = (bool,)
_INT = (int,)
_DICT = (dict,)
_LIST = (list,)

# field tables: name -> (allowed python types, required). ``None`` is accepted
# for every non-required field (producers emit explicit nulls: mfu on CPU,
# fingerprint when hashing failed, prefetch before attach_sampler).
_IDENTITY: Dict[str, Tuple[tuple, bool]] = {
    "event": (_STR, True),
    "time": (_NUM, False),
    "rank": (_INT, False),
    "attempt": (_INT, False),
    "seq": (_INT, False),
    "stream": (_STR, False),  # reader-side annotation (obs/streams.py)
}

_START: Dict[str, Tuple[tuple, bool]] = {
    "schema": (_INT, False),
    "platform": (_STR, False),
    "device_kind": (_STR, False),
    "world_size": (_INT, False),
    "peak_flops": (_NUM, False),
    "every": (_INT, False),
    "compile_warmup_steps": (_INT, False),
    "profiler": (_DICT, False),
    "fingerprint": (_DICT, False),
    "serve": (_DICT, False),  # serving runs (sheeprl_tpu/serve/telemetry.py)
}

_WINDOW: Dict[str, Tuple[tuple, bool]] = {
    "step": (_INT, True),
    "window": (_INT, True),
    "final": (_BOOL, False),
    "steps": (_INT, False),
    "wall_seconds": (_NUM, True),
    "sps": (_NUM, False),
    "train_units": (_INT, False),
    "train_seconds": (_NUM, False),
    "env_seconds": (_NUM, False),
    "phases": (_DICT, False),
    "mfu": (_NUM, False),
    "hbm": (_DICT, False),
    "rss_bytes": (_INT, False),
    "rss_peak_bytes": (_INT, False),
    "compile": (_DICT, False),
    "prefetch": (_DICT, False),
    "dataflow": (_DICT, False),  # experience-plane lineage (data/service.py)
    "serve": (_DICT, False),
    # training-health block (utils/learn_stats.py → RunTelemetry.observe_learn):
    # {rounds, stats: {grad_norm/<g>, entropy, td_error_p50, ...},
    #  episodes: {count, return_mean, return_p10/p50/p90, len_mean}, nonfinite}
    "learning": (_DICT, False),
    # SLO error-budget block (obs/slo.py): {worst: {objective, budget_remaining},
    # objectives: {<name>: {value, target, budget_remaining, burn_fast/slow}}}
    "slo": (_DICT, False),
}

_SUMMARY: Dict[str, Tuple[tuple, bool]] = {
    "step": (_INT, False),
    "clean_exit": (_BOOL, True),
    "windows": (_INT, False),
    "total_steps": (_INT, False),
    "wall_seconds": (_NUM, False),
    "sps": (_NUM, False),
    "train_units": (_INT, False),
    "train_seconds": (_NUM, False),
    "phases": (_DICT, False),
    "attributed_fraction": (_NUM, False),
    "mfu": (_NUM, False),
    "compile": (_DICT, False),
    "hbm_peak_bytes": (_INT, False),
    "rss_peak_bytes": (_INT, False),
    "prefetch": (_DICT, False),
    "env_restarts": (_INT, False),
    "health": (_STR, False),
    "dataflow": (_DICT, False),
    "learning": (_DICT, False),  # run-level learning rollup (+ last window)
    "programs": (_DICT, False),
    "serve": (_DICT, False),
    "slo": (_DICT, False),  # final error-budget accounting (obs/slo.py)
}

_PROFILER: Dict[str, Tuple[tuple, bool]] = {
    "step": (_INT, False),
    "action": (_STR, True),
    "dir": (_STR, False),
    "covered_steps": (_INT, False),
    "truncated": (_BOOL, False),
}

# open events: declared fields are type-checked, extras tolerated
_HEALTH: Dict[str, Tuple[tuple, bool]] = {
    "step": (_INT, False),
    "status": (_STR, True),
    "findings": (_LIST, False),
    "nonfinite": (_LIST, False),
    "restarts": (_INT, False),
    "total": (_INT, False),
}

_PROGRAM: Dict[str, Tuple[tuple, bool]] = {
    "name": (_STR, True),
    "units": (_INT, False),
    "error": (_STR, False),
    "flops": (_NUM, False),
    "flops_per_unit": (_NUM, False),
}

_SERVICE: Dict[str, Tuple[tuple, bool]] = {
    "step": (_INT, False),
    "role": (_STR, True),
    "rows": (_INT, False),
    "rows_per_actor": (_DICT, False),
    "messages": (_INT, False),
    "bytes": (_INT, False),
    "gradient_steps": (_INT, False),
    "weight_version": (_INT, False),
    "queue_depth_mean": (_NUM, False),
    "queue_depth_max": (_INT, False),
    "eos": (_LIST, False),
}

# resilience / fleet lifecycle events: payloads are fault/topology specific by
# design; only their discriminators are pinned
# op-level attribution of one completed profiler window capture (obs/xprof.py):
# category fractions (comm/mxu/elementwise/copy/loop/host/idle, tiling to 1.0)
# plus per-registered-program roofline verdicts
_PROFILE_ANALYSIS: Dict[str, Tuple[tuple, bool]] = {
    "step": (_INT, False),
    "capture": (_STR, False),
    "device_seconds": (_NUM, True),
    "busy_seconds": (_NUM, False),
    "categories": (_DICT, True),
    "programs": (_DICT, False),
}

_OPEN_EVENTS: Dict[str, Dict[str, Tuple[tuple, bool]]] = {
    "health": _HEALTH,
    "program": _PROGRAM,
    "profile_analysis": _PROFILE_ANALYSIS,
    "service": _SERVICE,
    "preempt": {},
    "preempt_exit": {},
    "fault": {"kind": (_STR, False)},
    # serving robustness plane (sheeprl_tpu/serve): hot-reload lifecycle
    # (applied/rejected with the version bookkeeping) and graceful-drain
    # lifecycle (begin/end with shed/aborted accounting)
    "reload": {
        "status": (_STR, True),
        "version": (_INT, False),
        "available": (_INT, False),
        "reloads": (_INT, False),
        "reason": (_STR, False),
        "source": (_STR, False),
    },
    "drain": {
        "status": (_STR, True),
        "shed": (_INT, False),
        "aborted": (_INT, False),
        "grace_s": (_NUM, False),
    },
    # the live flywheel (sheeprl_tpu/live): gang lifecycle on the supervisor
    # stream (start/shutdown with the role topology and ingest totals) and the
    # serve roles' trajectory-ingest accounting (captured/ingested/dropped —
    # dropped is the bounded queue's explicit shed-don't-stall overflow policy)
    "live": {
        "status": (_STR, True),
        "servers": (_INT, False),
        "sessions": (_INT, False),
        "reloads": (_INT, False),
        "error": (_STR, False),
    },
    "ingest": {
        "role": (_STR, False),
        "rank": (_INT, False),
        "trajectories_captured": (_INT, False),
        "trajectories_ingested": (_INT, False),
        "trajectories_dropped": (_INT, False),
        "trajectory_rows": (_INT, False),
        "queue_depth": (_INT, False),
        "rows": (_INT, False),
        "messages": (_INT, False),
        "weight_version": (_INT, False),
    },
    # SLO/alerting plane (obs/slo.py + obs/alerts.py): the stateful alert
    # lifecycle (pending/firing/resolved with burn-rate evidence) and the
    # per-weight-version promotion verdict the canary router gates on — emitted
    # once a hot-reloaded version accumulates enough post-swap samples to judge
    # against its predecessor (sheeprl_tpu/serve/telemetry.py)
    "alert": {
        "status": (_STR, True),
        "name": (_STR, False),
        "objective": (_STR, False),
        "severity": (_STR, False),
        "value": (_NUM, False),
        "target": (_NUM, False),
        "budget_remaining": (_NUM, False),
        "burn_fast": (_NUM, False),
        "burn_slow": (_NUM, False),
        "for_windows": (_INT, False),
    },
    "promotion": {
        "status": (_STR, True),
        "verdict": (_STR, False),
        "version": (_INT, False),
        "baseline": (_INT, False),
        "samples": (_INT, False),
        "latency_p50_ms": (_NUM, False),
        "baseline_latency_p50_ms": (_NUM, False),
        "latency_spread_ms": (_NUM, False),
        "return_mean": (_NUM, False),
        "baseline_return_mean": (_NUM, False),
        "return_spread": (_NUM, False),
        "reason": (_STR, False),
    },
    "checkpoint": {},
    "restart": {"reason": (_STR, False)},
    "resume": {},
    "giveup": {},
    "supervisor": {},
    "gang": {"status": (_STR, False)},
    "member": {"status": (_STR, False)},
    "fleet": {"status": (_STR, False)},
    "resilience": {},
}

_STRICT_EVENTS: Dict[str, Dict[str, Tuple[tuple, bool]]] = {
    "start": _START,
    "window": _WINDOW,
    "summary": _SUMMARY,
    "profiler": _PROFILER,
}


def _check_fields(
    event: Mapping[str, Any],
    table: Mapping[str, Tuple[tuple, bool]],
    *,
    strict: bool,
    where: str,
) -> List[str]:
    errors: List[str] = []
    known = {**_IDENTITY, **table}
    for name, (types, required) in known.items():
        if name not in event:
            if required:
                errors.append(f"{where}: missing required field {name!r}")
            continue
        value = event[name]
        if value is None:
            if required:
                errors.append(f"{where}: required field {name!r} is null")
            continue
        # bool is an int subclass: only accept it where bools are declared
        if isinstance(value, bool) and _BOOL != types:
            errors.append(f"{where}: field {name!r} is bool, expected {types}")
        elif not isinstance(value, types):
            errors.append(
                f"{where}: field {name!r} is {type(value).__name__}, "
                f"expected {'/'.join(t.__name__ for t in types)}"
            )
    if strict:
        for name in event:
            if name not in known:
                errors.append(
                    f"{where}: undeclared field {name!r} on a strict event type — "
                    "declare it in obs/schema.py (and bump SCHEMA_VERSION if breaking)"
                )
    return errors


def validate_event(event: Mapping[str, Any]) -> List[str]:
    """Errors for one parsed event (empty list = valid)."""
    kind = event.get("event")
    if not isinstance(kind, str):
        return [f"event without a string 'event' discriminator: {str(event)[:120]}"]
    where = f"{kind}#{event.get('seq', '?')}"
    stamped = event.get("schema")
    if isinstance(stamped, int) and stamped > SCHEMA_VERSION:
        return [
            f"{where}: stream schema v{stamped} is newer than this reader's "
            f"v{SCHEMA_VERSION} — upgrade before judging it"
        ]
    if kind in _STRICT_EVENTS:
        return _check_fields(event, _STRICT_EVENTS[kind], strict=True, where=where)
    if kind in _OPEN_EVENTS:
        return _check_fields(event, _OPEN_EVENTS[kind], strict=False, where=where)
    return [
        f"{where}: unknown event type {kind!r} — a new producer must register its "
        "event in obs/schema.py so consumers cannot silently ignore it"
    ]


def validate_events(events: Sequence[Mapping[str, Any]]) -> List[str]:
    errors: List[str] = []
    for event in events:
        errors.extend(validate_event(event))
    return errors


def validate_stream(path: str, base_dir: Optional[str] = None) -> List[str]:
    """Validate one ``telemetry*.jsonl`` file (torn-line tolerant, like every
    other reader); returns the error list, prefixed with the stream label."""
    import os

    from sheeprl_tpu.obs.jsonl import read_events

    label = os.path.relpath(path, base_dir) if base_dir else path
    return [f"{label}: {err}" for err in validate_events(read_events(path))]
