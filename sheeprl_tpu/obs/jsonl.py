"""Structured JSONL event sink: one JSON object per line, flushed per event.

``telemetry.jsonl`` is the machine-readable face of the run telemetry: window
events (sps / mfu / hbm / compile / prefetch gauges), health events from the
loss-finiteness guard, one program event per introspected compiled program, and
a final summary event. ``bench.py`` reads the summary back into
``conditions.telemetry`` without re-measuring, and offline tooling can tail the
file on a live run.

Stream identity: every event carries ``rank`` (the writing process's position in
the launch topology), ``attempt`` (supervisor restart counter, 0 for the first
launch) and a monotonic ``seq``. ``seq`` counters are shared per *path* within a
process, so the several writers that can append to one file (the run telemetry,
the resilience monitor's lazy sink, the supervisor across attempts) produce one
monotonic sequence — the ordering key ``obs/streams.py`` merges on. Old streams
without these fields still parse; readers default them (see
:func:`sheeprl_tpu.obs.streams.load_stream`).

Durability contract (what live followers may rely on):

- every event is serialized to ONE line and handed to the OS in ONE
  ``write()`` call, immediately followed by ``flush()`` — the sink is opened
  line-buffered and never holds an event in a userspace buffer between
  ``emit()`` calls. A same-host reader polling the file (``tail -F``,
  ``obs/streams.py`` follow mode, ``watch``) therefore sees every event as soon
  as ``emit()`` returns; it can never starve behind an OS-buffered writer.
- a reader may still observe a *torn tail*: the prefix of the final line of a
  write that is in flight (or that died mid-``write()``). Torn tails are always
  a strict prefix of one event — never interleaved fragments of two events,
  because appends of up-to-PIPE_BUF-sized single ``write()`` calls do not
  interleave on POSIX filesystems. Readers must treat an unparseable final
  line as "retry later", not as corruption (:func:`read_events` and the stream
  follower do).
- ``fsync`` is deliberately NOT issued per event: the contract covers readers
  on the same host (the watch/diagnose/bench consumers), not crash-consistency
  of the last event across a machine power loss.
- if a writer died mid-line and a LATER writer (a supervisor restart attempt)
  appended to the same file, the torn fragment and the next event share one
  line; :func:`parse_stream_line` recovers the trailing complete event instead
  of dropping both.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

# per-path monotonic sequence counters, shared by every sink of this process that
# appends to the same file (keyed by absolute path; distinct processes write
# distinct per-role files, so cross-process sharing is not needed)
_SEQ_LOCK = threading.Lock()
_SEQ: Dict[str, int] = {}


def _next_seq(path: str) -> int:
    with _SEQ_LOCK:
        n = _SEQ.get(path, 0)
        _SEQ[path] = n + 1
        return n


def _jsonable(value: Any) -> Any:
    """Best-effort conversion: numpy scalars/arrays and other non-JSON leaves
    become plain Python values (or ``repr`` as a last resort)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    item = getattr(value, "item", None)
    if callable(item):
        try:
            return _jsonable(item())
        except Exception:
            pass
    tolist = getattr(value, "tolist", None)
    if callable(tolist):
        try:
            return _jsonable(tolist())
        except Exception:
            pass
    return repr(value)


class JsonlEventSink:
    """Append-mode JSONL writer. Every event gets ``event`` (type), ``step``, a
    wall-clock ``time`` stamp and the stream identity triple
    (``rank``/``attempt``/``seq``); the rest of the payload is passed through
    :func:`_jsonable`. Lines are flushed as written so a crashed or abandoned run
    still leaves a readable stream."""

    def __init__(self, path: str, *, rank: int = 0, attempt: int = 0) -> None:
        self.path = str(path)
        self.rank = int(rank)
        self.attempt = int(attempt)
        self._seq_key = os.path.abspath(self.path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = open(self.path, "a", buffering=1)

    def emit(self, event: str, step: Optional[int] = None, **fields: Any) -> None:
        if self._fh is None:
            return
        payload: Dict[str, Any] = {
            "event": str(event),
            "time": round(time.time(), 3),
            "rank": self.rank,
            "attempt": self.attempt,
            "seq": _next_seq(self._seq_key),
        }
        if step is not None:
            payload["step"] = int(step)
        # explicit fields override the identity defaults (the supervisor stamps
        # the per-attempt counter on its own restart/giveup events this way)
        for k, v in fields.items():
            payload[k] = _jsonable(v)
        self._fh.write(json.dumps(payload) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def parse_stream_line(line: str) -> List[Dict[str, Any]]:
    """Parse one stream line into its event dict(s), tolerating torn writes.

    The crash-window shape this recovers: a writer died mid-line and a later
    writer of the same file — a supervisor restart attempt — appended its next
    event, so one physical line now reads ``{"event": "wind{"event":
    "restart", ...}`` (torn fragment + event) or ``{"event": "summary",
    ...}{"event": "restart", ...}`` (the fragment was a COMPLETE event whose
    only missing byte was the newline — the dying attempt's summary, exactly
    the event ``watch``'s exit protocol needs). A plain ``json.loads`` drops
    everything; here every complete event on the line is recovered with
    ``raw_decode`` from each ``{"`` boundary. Recovered objects must carry an
    ``event`` key — that is what tells a real event apart from a *nested*
    object inside a torn fragment (``"compile": {"count": 3}``), which is
    skipped while the scan continues behind it. A line with no complete event
    (a plain torn tail) yields ``[]`` — the follow-mode reader keeps such a
    tail buffered and retries on the next poll.
    """
    line = line.strip()
    if not line:
        return []
    try:
        obj = json.loads(line)
        return [obj] if isinstance(obj, dict) else []
    except json.JSONDecodeError:
        pass
    decoder = json.JSONDecoder()
    events: List[Dict[str, Any]] = []
    pos = 0
    while True:
        start = line.find('{"', pos)
        if start < 0:
            return events
        try:
            obj, end = decoder.raw_decode(line, start)
        except json.JSONDecodeError:
            pos = start + 1
            continue
        if isinstance(obj, dict) and "event" in obj:
            events.append(obj)
            pos = end
        else:
            # a nested object inside a torn fragment: scan on INSIDE it — the
            # real appended event may start anywhere behind this false match
            pos = start + 1


def read_events(path: str) -> List[Dict[str, Any]]:
    """Parse a telemetry JSONL file back into a list of event dicts. Torn lines
    never poison the read: a trailing in-flight line is skipped (the follow-mode
    reader retries it instead), and an event appended after a crashed writer's
    torn fragment is recovered (see :func:`parse_stream_line`)."""
    events: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            events.extend(parse_stream_line(line))
    return events
