"""``python sheeprl.py trace <run_dir|fleet_dir>`` — telemetry → Perfetto trace.

``diagnose`` answers "what is wrong", ``watch`` answers "what is happening";
this module answers "where does a row's wall time GO" by converting the
k-way-merged telemetry streams (``obs/streams.py``) into a Chrome-trace-format
JSON that Perfetto (https://ui.perfetto.dev) and ``chrome://tracing`` load
directly. Nothing new is measured: every span is reconstructed from events the
run already wrote.

Track layout (Chrome trace ``pid``/``tid`` = process/thread rows):

- one **process track per fleet member** (plus one for the fleet runner's own
  stream) when pointed at a fleet dir; a plain run dir is one process;
- one **thread track per telemetry stream** — the rank-0 player/controller,
  each ``telemetry.actor<r>.jsonl``, the learner role stream — so a service
  gang renders as parallel actor/learner timelines;
- per window, the **phase attribution** becomes a run of slices laid
  end-to-end across the window's wall span (env → rollout → replay_wait →
  train → …). Attribution measures shares, not ordering: inside one window the
  layout order is fixed, the widths are exact;
- **serving runs** get the same treatment for their batch-tick phases
  (``serve_step`` / ``serve_wait``) plus counter tracks for the session state
  (active sessions, admission queue depth, batch occupancy);
- **flow events** stitch the dataflow lineage across tracks: an actor's
  ingested rows to the learner window that had drained them
  (``ingest→sample``), and the learner's published weight version to the first
  actor window acting with it (``publish→refresh``). Flows ride the
  ``dataflow`` blocks (``data/service.py``), so they appear exactly on
  ``buffer.backend=service`` runs.

Timestamps are wall-clock microseconds relative to the earliest event, so the
alignment caveat of the stream merge applies unchanged (single-host clock).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = ["build_trace", "main", "trace_run"]

# fixed within-window layout order for the phase slices (a superset of
# telemetry._PHASE_TIMERS plus the derived/serving phases)
_PHASE_ORDER = (
    "env",
    "rollout",
    "replay_wait",
    "train",
    "serve_step",
    "serve_wait",
    "checkpoint",
    "logging",
    "eval",
    "analysis",
    "other",
)
_MIN_SLICE_S = 1e-4  # drop sub-0.1ms phase slivers: noise, not signal
_MARKER_DUR_US = 1000  # thin anchor slices for flow endpoints (1 ms)


def _f(value: Any) -> float:
    try:
        return float(value or 0.0)
    except (TypeError, ValueError):
        return 0.0


class _TraceBuilder:
    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = []
        self._pids: Dict[str, int] = {}
        self._tids: Dict[Tuple[int, str], int] = {}
        self._flow_ids: Dict[Tuple[str, str], int] = {}
        self.t0: Optional[float] = None

    def us(self, wall: float) -> int:
        base = self.t0 if self.t0 is not None else wall
        return max(int(round((wall - base) * 1e6)), 0)

    def pid(self, name: str) -> int:
        if name not in self._pids:
            self._pids[name] = len(self._pids) + 1
            self.events.append(
                {
                    "ph": "M",
                    "name": "process_name",
                    "pid": self._pids[name],
                    "tid": 0,
                    "args": {"name": name},
                }
            )
        return self._pids[name]

    def tid(self, pid: int, name: str) -> int:
        key = (pid, name)
        if key not in self._tids:
            self._tids[key] = sum(1 for p, _ in self._tids if p == pid) + 1
            self.events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": self._tids[key],
                    "args": {"name": name},
                }
            )
        return self._tids[key]

    def slice(self, pid: int, tid: int, name: str, ts_us: int, dur_us: int, args: Optional[Dict] = None, cat: str = "phase") -> None:
        event = {
            "ph": "X",
            "name": name,
            "cat": cat,
            "pid": pid,
            "tid": tid,
            "ts": ts_us,
            "dur": max(int(dur_us), 1),
        }
        if args:
            event["args"] = args
        self.events.append(event)

    def counter(self, pid: int, name: str, ts_us: int, values: Dict[str, float]) -> None:
        self.events.append(
            {"ph": "C", "name": name, "pid": pid, "tid": 0, "ts": ts_us, "args": values}
        )

    def flow_id(self, cat: str, key: str) -> int:
        pair = (cat, key)
        if pair not in self._flow_ids:
            self._flow_ids[pair] = len(self._flow_ids) + 1
        return self._flow_ids[pair]

    def flow(self, phase: str, cat: str, key: str, name: str, pid: int, tid: int, ts_us: int) -> None:
        event = {
            "ph": phase,  # "s" start | "f" finish
            "id": self.flow_id(cat, key),
            "cat": cat,
            "name": name,
            "pid": pid,
            "tid": tid,
            "ts": ts_us,
        }
        if phase == "f":
            event["bp"] = "e"  # bind to the enclosing slice, Perfetto-style
        self.events.append(event)


def _stream_thread_name(label: str) -> str:
    base = os.path.basename(str(label))
    if base == "telemetry.jsonl":
        return "rank0"
    if base.startswith("telemetry.") and base.endswith(".jsonl"):
        return base[len("telemetry.") : -len(".jsonl")]
    return base


def _window_spans(window: Mapping[str, Any]) -> List[Tuple[str, float]]:
    """The window's phase layout as (name, seconds) in fixed order; a window
    without a phases dict (pre-attribution recordings) is one opaque span."""
    phases = window.get("phases")
    wall = _f(window.get("wall_seconds"))
    if not isinstance(phases, Mapping):
        return [("window", wall)] if wall > 0 else []
    spans = [
        (name, _f(phases.get(name)))
        for name in _PHASE_ORDER
        if _f(phases.get(name)) >= _MIN_SLICE_S
    ]
    # phases the order list does not know yet still render (schema drift shows
    # up as an oddly-named slice, not as silently-vanished wall time)
    known = set(_PHASE_ORDER)
    spans.extend(
        (str(name), _f(value))
        for name, value in phases.items()
        if name not in known and _f(value) >= _MIN_SLICE_S
    )
    return spans


def _emit_window(tb: _TraceBuilder, pid: int, tid: int, window: Mapping[str, Any]) -> None:
    t_end = _f(window.get("time"))
    wall = _f(window.get("wall_seconds"))
    if t_end <= 0 or wall <= 0:
        return
    start = t_end - wall
    args = {
        "window": window.get("window"),
        "step": window.get("step"),
        "sps": window.get("sps"),
    }
    if window.get("mfu") is not None:
        args["mfu"] = window.get("mfu")
    cursor = start
    for name, seconds in _window_spans(window):
        tb.slice(pid, tid, name, tb.us(cursor), int(seconds * 1e6), args=args)
        cursor += seconds
    if window.get("sps") is not None:
        tb.counter(pid, "sps", tb.us(t_end), {"sps": _f(window.get("sps"))})
    serve = window.get("serve")
    if isinstance(serve, Mapping):
        # the session tracks of a serving run: admission/occupancy state per
        # batch-tick window (the phase slices above are the tick timeline)
        sessions = serve.get("sessions") or {}
        tb.counter(
            pid,
            "sessions",
            tb.us(t_end),
            {"active": _f(sessions.get("active")), "queue": _f(serve.get("queue_depth"))},
        )
        if serve.get("occupancy") is not None:
            tb.counter(pid, "occupancy", tb.us(t_end), {"occupancy": _f(serve.get("occupancy"))})


def _emit_dataflow_flows(
    tb: _TraceBuilder,
    windows: Sequence[Tuple[int, int, Dict[str, Any]]],
) -> None:
    """Cross-track lineage flows from the windows' ``dataflow`` blocks.

    ``ingest→sample``: an actor window reporting cumulative ingested rows R
    starts a flow that finishes at the FIRST learner window whose per-actor
    drained row count reaches R — the span of time those rows sat between env
    and buffer. ``publish→refresh``: the first learner window reporting
    published version V starts a flow finishing at the first actor window
    ACTING with V. Unmatched starts are dropped (never half-emitted)."""
    actor_rows: List[Tuple[int, int, int, float, int]] = []  # rank, rows, pid, time, tid
    learner_windows: List[Tuple[int, int, float, Dict[str, Any]]] = []
    actor_first_version: Dict[int, List[Tuple[int, int, int, float]]] = {}
    for pid, tid, w in windows:
        df = w.get("dataflow")
        if not isinstance(df, Mapping):
            continue
        t = _f(w.get("time"))
        if df.get("role") == "actor":
            rank = int(w.get("rank") or 0)
            actor_rows.append((rank, int(_f(df.get("rows"))), pid, t, tid))
            actor_first_version.setdefault(rank, []).append(
                (int(_f(df.get("weight_version"))), pid, tid, t)
            )
        elif df.get("role") == "learner":
            learner_windows.append((pid, tid, t, dict(df)))
    if not learner_windows:
        return
    learner_windows.sort(key=lambda item: item[2])

    # ingest → sample
    pending = sorted(actor_rows, key=lambda item: item[3])
    seen_rows: set = set()
    for rank, rows, a_pid, a_time, a_tid in pending:
        if rows <= 0 or (rank, rows) in seen_rows:
            continue  # an idle window (no new rows) must not duplicate a flow id
        seen_rows.add((rank, rows))
        match = None
        for l_pid, l_tid, l_time, df in learner_windows:
            drained = df.get("rows_per_actor") or {}
            if l_time >= a_time and _f(drained.get(str(rank))) >= rows:
                match = (l_pid, l_tid, l_time)
                break
        if match is None:
            continue
        key = f"rows-r{rank}-{rows}"
        ts_a = tb.us(a_time)
        tb.slice(a_pid, a_tid, "ingest", ts_a, _MARKER_DUR_US, args={"rows": rows, "rank": rank}, cat="dataflow")
        tb.flow("s", "experience", key, "ingest→sample", a_pid, a_tid, ts_a)
        l_pid, l_tid, l_time = match
        ts_l = tb.us(l_time)
        tb.slice(l_pid, l_tid, "sample", ts_l, _MARKER_DUR_US, args={"rows": rows, "rank": rank}, cat="dataflow")
        tb.flow("f", "experience", key, "ingest→sample", l_pid, l_tid, ts_l)

    # publish → refresh
    for rank, held in actor_first_version.items():
        held.sort(key=lambda item: item[3])
        seen: set = set()
        for version, a_pid, a_tid, a_time in held:
            if version <= 0 or version in seen:
                continue
            seen.add(version)
            publish = next(
                (
                    (l_pid, l_tid, l_time)
                    for l_pid, l_tid, l_time, df in learner_windows
                    if int(_f(df.get("weight_version"))) >= version and l_time <= a_time
                ),
                None,
            )
            if publish is None:
                continue
            key = f"w{version}-r{rank}"
            l_pid, l_tid, l_time = publish
            ts_l = tb.us(l_time)
            tb.slice(l_pid, l_tid, "publish", ts_l, _MARKER_DUR_US, args={"version": version}, cat="weights")
            tb.flow("s", "weights", key, "publish→refresh", l_pid, l_tid, ts_l)
            ts_a = tb.us(a_time)
            tb.slice(a_pid, a_tid, "refresh", ts_a, _MARKER_DUR_US, args={"version": version, "rank": rank}, cat="weights")
            tb.flow("f", "weights", key, "publish→refresh", a_pid, a_tid, ts_a)


def _emit_instants(tb: _TraceBuilder, pid: int, tid: int, event: Mapping[str, Any]) -> None:
    """Lifecycle markers: health/preempt/restart/service events render as
    instants so the phase timeline carries its operational context."""
    kind = event.get("event")
    t = _f(event.get("time"))
    if t <= 0:
        return
    name = None
    args: Dict[str, Any] = {}
    if kind == "health" and event.get("status") not in (None, "ok"):
        name = f"health:{event.get('status')}"
    elif kind in ("preempt", "restart", "resume", "giveup"):
        name = str(kind)
        if event.get("reason"):
            args["reason"] = event.get("reason")
    elif kind == "service":
        name = f"service:{event.get('role')}"
        args = {
            k: event.get(k)
            for k in ("rows", "gradient_steps", "weight_version", "queue_depth_mean")
            if event.get(k) is not None
        }
    elif kind == "reload":
        # the flywheel's visible heartbeat: each applied hot swap marks the
        # serving track at the moment a published version went live
        name = f"reload:{event.get('status')}"
        args = {
            k: event.get(k)
            for k in ("version", "available", "reloads", "reason", "source")
            if event.get(k) is not None
        }
    elif kind == "drain":
        name = f"drain:{event.get('status')}"
        args = {
            k: event.get(k)
            for k in ("shed", "aborted", "grace_s")
            if event.get(k) is not None
        }
    elif kind == "live":
        name = f"live:{event.get('status')}"
        args = {
            k: event.get(k)
            for k in ("servers", "sessions", "reloads", "error")
            if event.get(k) is not None
        }
    elif kind == "ingest":
        name = "ingest"
        args = {
            k: event.get(k)
            for k in (
                "rank",
                "trajectories_captured",
                "trajectories_ingested",
                "trajectories_dropped",
                "trajectory_rows",
                "weight_version",
            )
            if event.get(k) is not None
        }
    elif kind == "alert" and event.get("status") in ("firing", "resolved"):
        # SLO alert lifecycle on the timeline: pending transitions are noise
        # at trace zoom, firing/resolved mark the incident's span ends
        name = f"alert:{event.get('status')}:{event.get('name')}"
        args = {
            k: event.get(k)
            for k in ("severity", "value", "target", "budget_remaining", "burn_fast")
            if event.get(k) is not None
        }
    elif kind == "promotion":
        name = f"promotion:{event.get('verdict')}"
        args = {
            k: event.get(k)
            for k in ("version", "baseline", "samples", "reason")
            if event.get(k) is not None
        }
    if name is None:
        return
    tb.events.append(
        {
            "ph": "i",
            "name": name,
            "cat": "lifecycle",
            "s": "t",  # thread-scoped instant
            "pid": pid,
            "tid": tid,
            "ts": tb.us(t),
            "args": args,
        }
    )


def build_trace(run_dir: str) -> Dict[str, Any]:
    """The Chrome-trace JSON object for a run dir, fleet dir, or single
    ``telemetry*.jsonl`` file. Raises ``FileNotFoundError`` when no stream
    exists (the caller maps it to exit 2, like diagnose/compare)."""
    from sheeprl_tpu.obs.streams import (
        discover_streams,
        fleet_members,
        load_stream,
        member_of,
        merge_streams,
    )

    streams = discover_streams(run_dir)
    if not streams:
        raise FileNotFoundError(f"no telemetry*.jsonl stream found under {run_dir!r}")
    base = run_dir if os.path.isdir(run_dir) else os.path.dirname(run_dir)
    events = merge_streams([load_stream(p, base_dir=base) for p in streams])

    tb = _TraceBuilder()
    times = [_f(e.get("time")) for e in events if _f(e.get("time")) > 0]
    if times:
        # anchor at the earliest WINDOW START (window stamps mark the end)
        starts = [
            _f(e.get("time")) - _f(e.get("wall_seconds"))
            for e in events
            if e.get("event") == "window" and _f(e.get("time")) > 0
        ]
        tb.t0 = min(times + [t for t in starts if t > 0])

    members = fleet_members(run_dir)
    run_label = os.path.basename(os.path.normpath(str(run_dir))) or str(run_dir)

    def track_of(event: Mapping[str, Any]) -> Tuple[int, int]:
        stream = str(event.get("stream") or "telemetry.jsonl")
        if members is not None:
            member = member_of(stream)
            pid = tb.pid(f"member:{member}" if member else f"fleet:{run_label}")
        else:
            pid = tb.pid(run_label)
        return pid, tb.tid(pid, _stream_thread_name(stream))

    window_tracks: List[Tuple[int, int, Dict[str, Any]]] = []
    for event in events:
        pid, tid = track_of(event)
        kind = event.get("event")
        if kind == "window":
            _emit_window(tb, pid, tid, event)
            window_tracks.append((pid, tid, event))
        else:
            _emit_instants(tb, pid, tid, event)
    _emit_dataflow_flows(tb, window_tracks)

    return {
        "traceEvents": tb.events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": str(run_dir),
            "streams": [os.path.relpath(p, base) for p in streams],
            "tool": "sheeprl.py trace",
        },
    }


def _write_trace(trace: Dict[str, Any], run_dir: str, out_path: Optional[str]) -> str:
    base = run_dir if os.path.isdir(run_dir) else os.path.dirname(run_dir)
    out = out_path or os.path.join(base, "trace.json")
    with open(out, "w") as fh:
        json.dump(trace, fh)
        fh.write("\n")
    return out


def trace_run(run_dir: str, out_path: Optional[str] = None) -> str:
    """Build and write the trace JSON (default ``<run_dir>/trace.json``);
    returns the written path."""
    return _write_trace(build_trace(run_dir), run_dir, out_path)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py trace <run_dir|fleet_dir>``: write a Perfetto-loadable
    trace JSON next to the streams (exit 2 when no stream exists)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="sheeprl.py trace",
        description="Convert a run's telemetry.jsonl stream(s) into a Chrome-trace/"
        "Perfetto JSON: one track per member/rank/role, phase spans per window, "
        "flow events linking ingest→sample and publish→refresh across tracks. "
        "Open the output at https://ui.perfetto.dev or chrome://tracing.",
    )
    parser.add_argument("run_dir", help="run dir, fleet dir, or a telemetry*.jsonl file")
    parser.add_argument("--out", default=None, help="output path (default: <run_dir>/trace.json)")
    parser.add_argument("--quiet", action="store_true", help="suppress the summary line")
    args = parser.parse_args(list(argv) if argv is not None else sys.argv[1:])
    try:
        trace = build_trace(args.run_dir)
        out = _write_trace(trace, args.run_dir, args.out)
    except FileNotFoundError as exc:
        print(f"trace: {exc}", file=sys.stderr)
        return 2
    if not args.quiet:
        events = trace["traceEvents"]
        flows = sum(1 for e in events if e.get("ph") in ("s", "f"))
        print(
            f"wrote {out} ({len(events)} trace event(s), {flows} flow endpoint(s)) — "
            "open it at https://ui.perfetto.dev"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
