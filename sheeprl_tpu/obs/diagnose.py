"""Rule-based diagnosis over telemetry streams: "why is this run slow/sick?".

PR 2/3 made every run *emit* a structured event stream (``telemetry.jsonl``:
window gauges, health events, resilience lifecycle); this module is the
*consumer*. A catalog of detectors walks the merged, ordered stream
(``obs/streams.py``) and turns raw gauges into findings — each with a severity,
the evidence events that triggered it, and the config knob most likely to fix
it. Exposed three ways:

- ``python sheeprl.py diagnose <run_dir>`` — human bottleneck report on stdout
  plus machine-readable ``diagnosis.json`` in the run dir;
- in-loop: ``RunTelemetry`` runs the same detectors over its own window history
  at window cadence and emits live ``health`` events (``status=diagnosis``);
- ``bench.py`` attaches the verdicts of each steady-window run under
  ``conditions.diagnosis``, so BENCH JSONs are regression-gateable on *causes*
  (a recompile storm, a starved pipeline), not just on env-steps/sec.

Detector catalog (see ``howto/observability.md`` for the full reference):

==================  ============================================================
recompile_storm     XLA recompiles in windows after the first trained window
                    (shape churn: varying gradient-step counts, env batch drift)
prefetch_starvation replay/prefetch wait is a large fraction of train time
mfu_collapse        windows whose MFU falls far below the run median
hbm_creep           device memory marching toward the HBM capacity limit
checkpoint_heavy    checkpoint writes eat a material share of wall time
env_instability     env crash-restart clusters and watchdog stall events
interruptions       preempt / crash-restart / giveup lifecycle events
nonfinite_loss      the loss-finiteness health guard tripped
unattributed_time   the phases breakdown leaves too much wall time unnamed
occupancy_collapse  (serving) batch occupancy fell away with sessions attached
latency_regression  (serving) window p99 step latency far above the run median
slot_starvation     (serving) sessions queued while the slot table ran full
shed_rate           (serving) admissions rejected by overload protection
deadline_misses     (serving) requests dropped past their serve.deadline_ms
reload_stall        (serving) hot reload rejecting candidates / falling behind
weight_staleness    (service) actors acting with weights far behind the learner
row_age_drift       (service) the learner trains on increasingly old rows
ingest_backpressure (service) actors blocked on flow control / ingest backlog
grad_explosion      (learning) gradient norms far above the run median / nonfinite
entropy_collapse    (learning) policy entropy fell off a cliff vs early training
value_overestimation (learning) value estimates grew far past the return scale
update_ratio_anomaly (learning) update-to-param ratio spiked vs the run median
kl_balance_drift    (learning, dreamer) KL collapsed/exploded or the posterior/
                    prior entropy balance drifted (posterior collapse signal)
reward_plateau      (learning) episode returns rose, then flattened for the
                    rest of the run (advisory — sample-efficiency signal)
comm_bound          (profile) collectives dominate the window capture's device
                    time (``profile_analysis`` events — obs/xprof.py)
copy_bound          (profile) copy/layout ops dominate the capture's device time
host_gap            (profile) the device sat idle / fed by host transfers for a
                    large share of the capture (fused calls gapped by the host)
==================  ============================================================

The three serving detectors read the ``serve`` block of a serving run's
windows (``sheeprl_tpu/serve/telemetry.py``); the three experience-plane
detectors read the ``dataflow`` block (``data/service.py`` lineage,
``buffer.backend=service`` runs). Training streams without those blocks carry
none of either, so all six are free no-ops there. The three profile detectors
read ``profile_analysis`` events (emitted when a ``metric.profiler.mode=window``
capture completes, or synthesized by ``sheeprl.py profile``) — runs that never
captured a window carry none, so they too are structural no-ops.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

Finding = Dict[str, Any]
Events = Sequence[Dict[str, Any]]

_SEVERITY_RANK = {"critical": 0, "warning": 1, "info": 2}

# thresholds (module constants so tests and operators can reason about them)
PREFETCH_WAIT_WARNING = 0.25  # replay wait as a fraction of train time
PREFETCH_WAIT_CRITICAL = 0.50
MFU_COLLAPSE_RATIO = 0.5  # window MFU below this fraction of the run median
MFU_MIN_WINDOWS = 4
HBM_NEAR_LIMIT = 0.92  # bytes_in_use / bytes_limit
HBM_CREEP_GROWTH = 0.2  # relative in-use growth over the run that flags a creep
HBM_MIN_WINDOWS = 4
CHECKPOINT_WARNING = 0.10  # checkpoint seconds as a fraction of wall time
CHECKPOINT_CRITICAL = 0.25
ENV_RESTART_CLUSTER = 3  # restarts within ENV_RESTART_CLUSTER_SECONDS
ENV_RESTART_CLUSTER_SECONDS = 120.0
UNATTRIBUTED_FRACTION = 0.10  # >10% of steady wall time unnamed
UNATTRIBUTED_MIN_WALL_SECONDS = 5.0  # ignore micro-runs where noise dominates
RECOMPILE_STORM_WINDOWS = 3  # affected windows that escalate to critical
# serving detectors (windows carrying a `serve` block — sheeprl_tpu/serve)
SERVE_MIN_WINDOWS = 4
OCCUPANCY_COLLAPSE_RATIO = 0.5  # late-half median occupancy vs early-half
OCCUPANCY_COLLAPSE_CRITICAL = 0.25
LATENCY_REGRESSION_RATIO = 2.0  # window p99 vs run median p99
LATENCY_REGRESSION_CRITICAL = 4.0
# co-located live gang (sheeprl.py live): the learner thread CONTENDS with the
# tick loop for host cores by design, so millisecond-scale jitter carries no
# SLO signal there — only spikes past this absolute floor are drift
LIVE_LATENCY_FLOOR_MS = 25.0
SLOT_STARVATION_OCCUPANCY = 0.95  # "table full" occupancy floor
SLOT_STARVATION_FRACTION = 0.5  # share of windows with a waiting queue
# serving robustness plane (shed/deadline/reload state in the serve block)
SHED_RATE_WARNING = 0.1  # window shed/offered fraction that flags overload
SHED_RATE_CRITICAL = 0.5
SHED_MIN_SESSIONS = 3  # total shed sessions before judging (burst noise floor)
DEADLINE_MISS_WARNING = 0.05  # window missed/(missed+served) fraction
DEADLINE_MISS_CRITICAL = 0.25
DEADLINE_MIN_MISSES = 3
RELOAD_STALL_WINDOWS = 2  # windows with available > serving version in a row
# experience-plane (dataflow block) detectors — buffer.backend=service runs
WEIGHT_STALENESS_LAG = 3  # versions behind the publisher that flag an actor
WEIGHT_STALENESS_WINDOWS = 2  # sustained lagging windows before flagging
ROW_AGE_MIN_WINDOWS = 4
ROW_AGE_DRIFT_RATIO = 3.0  # late-half median p50 age vs early-half
ROW_AGE_MIN_SECONDS = 10.0  # ignore drift while everything is seconds-fresh
INGEST_BLOCK_WARNING = 0.25  # actor wall share spent blocked on flow control
INGEST_BLOCK_CRITICAL = 0.50
INGEST_QUEUE_DEPTH = 4.0  # learner-side sustained backlog (messages)
# training-health (learning block) detectors — utils/learn_stats.py producers
LEARN_MIN_WINDOWS = 4  # windows with learning stats before judging trends
GRAD_EXPLOSION_RATIO = 10.0  # window grad norm vs run median that flags
GRAD_EXPLOSION_CRITICAL = 100.0  # ...and that escalates to critical
ENTROPY_COLLAPSE_DROP = 0.5  # late-half entropy drop vs max(|early median|, 1)
VALUE_OVER_SCALE = 5.0  # late value mean vs max(|ep-return median|, 1)
VALUE_OVER_GROWTH = 3.0  # ...and vs the early-half value mean
VALUE_OVER_CRITICAL = 20.0  # value/return ratio that escalates to critical
UPDATE_RATIO_ANOMALY = 10.0  # window update/param ratio vs run median
KL_BALANCE_DRIFT = 0.25  # |late - early| posterior/prior balance shift
KL_COLLAPSE_RATIO = 0.1  # late-half KL vs early-half (posterior collapse)
KL_EXPLOSION_RATIO = 10.0  # late-half KL vs early-half (dynamics divergence)
REWARD_PLATEAU_MIN_WINDOWS = 8  # windows with episode stats before judging
REWARD_PLATEAU_EPS = 0.05  # late improvement below this fraction of the climb
REWARD_PLATEAU_MIN_CLIMB = 0.2  # climb must exceed this fraction of max(|peak|, 1)
# execution-profile (profile_analysis events — obs/xprof.py) detectors
PROFILE_MIN_DEVICE_SECONDS = 1e-4  # ignore empty/degenerate captures
PROFILE_COMM_WARNING = 0.25  # comm share of the capture's device time
PROFILE_COMM_CRITICAL = 0.50
PROFILE_COPY_WARNING = 0.30  # copy/layout share of device time
PROFILE_COPY_CRITICAL = 0.60
PROFILE_HOST_GAP_WARNING = 0.40  # idle + host-transfer share of device time
PROFILE_HOST_GAP_CRITICAL = 0.70


def _ref(event: Dict[str, Any]) -> Dict[str, Any]:
    """Compact evidence pointer back into the merged stream."""
    ref = {"seq": event.get("seq"), "step": event.get("step")}
    if event.get("stream") is not None:
        ref["stream"] = event["stream"]
    if event.get("attempt"):
        ref["attempt"] = event["attempt"]
    return ref


def _finding(
    detector: str,
    severity: str,
    summary: str,
    evidence: Events,
    suggestion: str,
    **metrics: Any,
) -> Finding:
    return {
        "detector": detector,
        "severity": severity,
        "summary": summary,
        "evidence": [_ref(e) for e in list(evidence)[:8]],
        "suggestion": suggestion,
        "metrics": metrics,
    }


def _windows(events: Events, steady: bool = True) -> List[Dict[str, Any]]:
    return [
        e
        for e in events
        if e.get("event") == "window" and not (steady and e.get("final"))
    ]


def _phase(window: Dict[str, Any], name: str) -> float:
    phases = window.get("phases") or {}
    try:
        return float(phases.get(name) or 0.0)
    except (TypeError, ValueError):
        return 0.0


# ---------------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------------
def detect_recompile_storm(events: Events) -> List[Finding]:
    windows = _windows(events, steady=False)
    # warmup = everything up to and including the first window that trained (the
    # act/train programs legitimately compile there), extended by the run's own
    # compile_warmup_steps (the start event carries it) — auxiliary programs
    # (imagination/test heads) legitimately trickle in behind the first round
    first_trained = next(
        (i for i, w in enumerate(windows) if (w.get("train_units") or 0) > 0), None
    )
    if first_trained is None:
        return []
    warmup_steps = max(
        (
            int(e.get("compile_warmup_steps") or 0)
            for e in events
            if e.get("event") == "start"
        ),
        default=0,
    )
    affected = [
        w
        for w in windows[first_trained + 1 :]
        if ((w.get("compile") or {}).get("window_count") or 0) > 0
        and (w.get("step") or 0) > warmup_steps
        # the final (close-time) window absorbs the end-of-run test's
        # first-time eval-program compiles — legitimate, not shape churn
        and not w.get("final")
    ]
    if not affected:
        return []
    count = sum(int(w["compile"]["window_count"]) for w in affected)
    seconds = sum(float(w["compile"].get("window_seconds") or 0.0) for w in affected)
    severity = "critical" if len(affected) >= RECOMPILE_STORM_WINDOWS else "warning"
    return [
        _finding(
            "recompile_storm",
            severity,
            f"{count} XLA recompile(s) ({seconds:.1f}s) across {len(affected)} "
            "window(s) after warmup — the train/act programs should compile once",
            affected,
            "hunt for shape churn (varying per-round gradient-step counts, env batch "
            "drift); pin shapes, or pre-warm with sheeprl-compile and keep the "
            "persistent compile cache on (SHEEPRL_JAX_CACHE)",
            recompiles=count,
            compile_seconds=round(seconds, 3),
            windows=len(affected),
        )
    ]


def detect_prefetch_starvation(events: Events) -> List[Finding]:
    windows = [
        w
        for w in _windows(events)
        if (w.get("train_seconds") or 0) > 0 and (w.get("prefetch") or {}).get("wait_seconds") is not None
    ]
    if not windows:
        return []
    wait = sum(float(w["prefetch"]["wait_seconds"]) for w in windows)
    train = sum(float(w["train_seconds"]) for w in windows)
    if train <= 0:
        return []
    frac = wait / train
    if frac < PREFETCH_WAIT_WARNING:
        return []
    severity = "critical" if frac >= PREFETCH_WAIT_CRITICAL else "warning"
    worst = sorted(
        windows,
        key=lambda w: float(w["prefetch"]["wait_seconds"]) / max(float(w["train_seconds"]), 1e-9),
        reverse=True,
    )
    is_async = bool((worst[0].get("prefetch") or {}).get("is_async", False))
    empty_waits = sum(int((w.get("prefetch") or {}).get("empty_waits") or 0) for w in windows)
    if is_async:
        depth = (worst[0].get("prefetch") or {}).get("depth")
        suggestion = (
            "increase buffer.prefetch.depth"
            + (f" (currently {depth})" if depth else "")
            + ", check host sampling throughput (memmap IO, batch assembly), or "
            "shrink the per-round gradient-step burst"
        )
    else:
        # the sync sampler's "wait" IS the full inline gather — deepening a
        # pipeline that does not exist cannot help
        suggestion = "enable the async replay pipeline: buffer.prefetch.enabled=true"
    return [
        _finding(
            "prefetch_starvation",
            severity,
            f"the train loop spent {frac:.0%} of its train time waiting on replay "
            "sampling — the device is starved by the host pipeline"
            + (f" ({empty_waits} sample call(s) found nothing staged)" if is_async and empty_waits else ""),
            worst,
            suggestion,
            wait_fraction=round(frac, 4),
            wait_seconds=round(wait, 3),
            train_seconds=round(train, 3),
            is_async=is_async,
            empty_waits=empty_waits,
        )
    ]


def detect_mfu_collapse(events: Events) -> List[Finding]:
    windows = [w for w in _windows(events) if w.get("mfu") is not None]
    if len(windows) < MFU_MIN_WINDOWS:
        return []
    values = sorted(float(w["mfu"]) for w in windows)
    median = values[len(values) // 2]
    if median <= 0:
        return []
    affected = [w for w in windows if float(w["mfu"]) < MFU_COLLAPSE_RATIO * median]
    if not affected:
        return []
    worst = min(float(w["mfu"]) for w in affected)
    severity = "critical" if float(windows[-1]["mfu"]) < MFU_COLLAPSE_RATIO * median else "warning"
    return [
        _finding(
            "mfu_collapse",
            severity,
            f"{len(affected)} window(s) ran at MFU {worst:.3f} vs a run median of "
            f"{median:.3f} — the device went quiet mid-run",
            affected,
            "capture a bounded trace around the slow stretch "
            "(metric.profiler.mode=window metric.profiler.start_step=<step>) and "
            "check the same windows for recompiles / prefetch waits / checkpoint time",
            median_mfu=round(median, 4),
            worst_mfu=round(worst, 4),
            windows=len(affected),
        )
    ]


def detect_hbm_creep(events: Events) -> List[Finding]:
    windows = [
        w for w in _windows(events, steady=False) if (w.get("hbm") or {}).get("bytes_in_use")
    ]
    if not windows:
        return []
    last = windows[-1]
    in_use = float(last["hbm"]["bytes_in_use"])
    limit = float(last["hbm"].get("bytes_limit") or 0.0)
    if limit > 0 and in_use / limit >= HBM_NEAR_LIMIT:
        return [
            _finding(
                "hbm_creep",
                "critical",
                f"device memory is at {in_use / limit:.0%} of HBM capacity "
                f"({in_use / 2**30:.2f} GiB of {limit / 2**30:.2f} GiB) — the next "
                "allocation spike can OOM the run",
                [last],
                "shrink per-rank batch/sequence sizes, verify train-state donation is "
                "active (howto/performance.md), or shard over more devices",
                bytes_in_use=int(in_use),
                bytes_limit=int(limit),
                fraction=round(in_use / limit, 4),
            )
        ]
    if len(windows) < HBM_MIN_WINDOWS:
        return []
    series = [float(w["hbm"]["bytes_in_use"]) for w in windows]
    first = series[0]
    growing = all(b >= a for a, b in zip(series, series[1:])) and series[-1] > series[0]
    if first > 0 and growing and (series[-1] - first) / first >= HBM_CREEP_GROWTH:
        return [
            _finding(
                "hbm_creep",
                "warning",
                f"device memory grew monotonically {first / 2**30:.2f} → "
                f"{series[-1] / 2**30:.2f} GiB across {len(windows)} windows — "
                "something is accumulating on-device",
                windows[-3:],
                "look for device arrays retained across iterations (host-side lists "
                "of jax arrays, un-donated train state, growing replay staging)",
                first_bytes=int(first),
                last_bytes=int(series[-1]),
                growth=round((series[-1] - first) / first, 4),
            )
        ]
    return []


def detect_checkpoint_heavy(events: Events) -> List[Finding]:
    windows = [w for w in _windows(events) if w.get("phases")]
    wall = sum(float(w.get("wall_seconds") or 0.0) for w in windows)
    if wall <= 0:
        return []
    ckpt = sum(_phase(w, "checkpoint") for w in windows)
    frac = ckpt / wall
    if frac < CHECKPOINT_WARNING:
        return []
    severity = "critical" if frac >= CHECKPOINT_CRITICAL else "warning"
    affected = sorted(windows, key=lambda w: _phase(w, "checkpoint"), reverse=True)
    return [
        _finding(
            "checkpoint_heavy",
            severity,
            f"checkpoint writes took {frac:.0%} of steady wall time "
            f"({ckpt:.1f}s of {wall:.1f}s)",
            affected,
            "enable async checkpointing (checkpoint.async_save=true with the orbax "
            "backend), raise checkpoint.every, or drop the replay buffer from the "
            "checkpoint (buffer.checkpoint=false) if resume-refill is acceptable",
            checkpoint_seconds=round(ckpt, 3),
            wall_seconds=round(wall, 3),
            fraction=round(frac, 4),
        )
    ]


def detect_env_instability(events: Events) -> List[Finding]:
    findings: List[Finding] = []
    restarts = [
        e for e in events if e.get("event") == "health" and e.get("status") == "env_restart"
    ]
    if restarts:
        total = max(int(e.get("total") or 1) for e in restarts)
        clustered = False
        times = [float(e.get("time") or 0.0) for e in restarts]
        for i in range(len(times)):
            j = i + ENV_RESTART_CLUSTER - 1
            if j < len(times) and times[j] - times[i] <= ENV_RESTART_CLUSTER_SECONDS:
                clustered = True
                break
        findings.append(
            _finding(
                "env_instability",
                "critical" if clustered else "warning",
                f"{total} env crash-restart(s)"
                + (
                    f" including {ENV_RESTART_CLUSTER}+ within "
                    f"{ENV_RESTART_CLUSTER_SECONDS:.0f}s — the env is flapping"
                    if clustered
                    else " absorbed by RestartOnException"
                ),
                restarts,
                "inspect the env worker logs; a deterministic crash at the same step "
                "usually means a bad transition/asset, a flapping env usually means "
                "resource exhaustion in the env process",
                restarts=total,
                clustered=clustered,
            )
        )
    stalls = [
        e for e in events if e.get("event") == "health" and e.get("status") == "stalled"
    ]
    if stalls:
        worst = max(float(e.get("stall_seconds") or 0.0) for e in stalls)
        findings.append(
            _finding(
                "env_instability",
                "critical",
                f"the progress watchdog tripped {len(stalls)} time(s) (worst stall "
                f"{worst:.0f}s) — the loop stopped making progress without dying",
                stalls,
                "read the stack dump in the stall event; common culprits are a "
                "deadlocked env subprocess and a wedged device transfer "
                "(resilience.watchdog.abort=true turns stalls into supervised restarts)",
                stalls=len(stalls),
                worst_stall_seconds=round(worst, 1),
            )
        )
    return findings


def detect_interruptions(events: Events) -> List[Finding]:
    findings: List[Finding] = []
    preempts = [e for e in events if e.get("event") == "preempt"]
    crash_restarts = [
        e for e in events if e.get("event") == "restart" and e.get("reason") == "crash"
    ]
    preempt_restarts = [
        e for e in events if e.get("event") == "restart" and e.get("reason") == "preempt"
    ]
    giveups = [e for e in events if e.get("event") == "giveup"]
    # distributed runs: heartbeat failure detection names the rank that died
    # (health status=rank_dead, resilience/distributed.py), and the gang
    # supervisor's restart events carry the non-zero exit codes per rank — so a
    # gang teardown is attributed to its dead rank, not "an unexplained crash"
    rank_deaths = [
        e for e in events if e.get("event") == "health" and e.get("status") == "rank_dead"
    ]
    dead_rank_ids = sorted(
        {int(e["rank"]) for e in rank_deaths if e.get("rank") is not None}
        | {
            int(r)
            for e in events
            if e.get("event") == "giveup" or (e.get("event") == "restart" and e.get("reason") == "crash")
            for r in (e.get("dead_ranks") or {})
        }
    )
    if rank_deaths:
        observers = sorted(
            {int(e["observed_by"]) for e in rank_deaths if e.get("observed_by") is not None}
        )
        named = sorted({int(e["rank"]) for e in rank_deaths if e.get("rank") is not None})
        findings.append(
            _finding(
                "interruptions",
                "warning",
                f"rank{'s' if len(named) != 1 else ''} "
                f"{', '.join(map(str, named)) or '?'} of the gang "
                f"{'were' if len(named) != 1 else 'was'} declared dead "
                f"({rank_deaths[-1].get('reason') or 'heartbeat timeout'}"
                + (f", observed by rank {observers[0]}" if observers else "")
                + ") — peers tore down instead of hanging",
                rank_deaths,
                "read the dead rank's own log/stream for its last events; recurring "
                "single-rank deaths at the same step are that rank's bug (OOM, env "
                "crash), not infrastructure flakiness",
                dead_ranks=named,
            )
        )
    if preempts:
        findings.append(
            _finding(
                "interruptions",
                "info",
                f"{len(preempts)} cooperative preemption(s) (SIGTERM reclaim) — "
                "emergency checkpoints were written"
                + (f"; {len(preempt_restarts)} supervised resume(s)" if preempt_restarts else ""),
                preempts + preempt_restarts,
                "expected on preemptible capacity; tighten checkpoint.every if the "
                "re-done work between checkpoint and preempt is material",
                preempts=len(preempts),
                resumed=len(preempt_restarts),
            )
        )
    if crash_restarts:
        last_error = next(
            (e.get("error") for e in reversed(crash_restarts) if e.get("error")), None
        )
        findings.append(
            _finding(
                "interruptions",
                "warning",
                f"the run crashed and was auto-restarted {len(crash_restarts)} time(s)"
                + (
                    f" (dead rank{'s' if len(dead_rank_ids) != 1 else ''}: "
                    f"{', '.join(map(str, dead_rank_ids))})"
                    if dead_rank_ids
                    else ""
                )
                + (f" (last error: {str(last_error)[:120]})" if last_error else ""),
                crash_restarts,
                "read the restart events' error fields; recurring crashes at the same "
                "step are a code/data bug, not flakiness — the supervisor is masking it",
                restarts=len(crash_restarts),
                **({"dead_ranks": dead_rank_ids} if dead_rank_ids else {}),
            )
        )
    if giveups:
        findings.append(
            _finding(
                "interruptions",
                "critical",
                "the supervisor exhausted its restart budget and gave up",
                giveups,
                "fix the underlying crash (see the giveup event's error) or raise "
                "resilience.supervisor.max_restarts if the failures are environmental",
                giveups=len(giveups),
                **({"dead_ranks": dead_rank_ids} if dead_rank_ids else {}),
            )
        )
    return findings


def detect_nonfinite_loss(events: Events) -> List[Finding]:
    bad = [
        e for e in events if e.get("event") == "health" and e.get("status") == "nonfinite"
    ]
    if not bad:
        return []
    names = sorted({str(n) for e in bad for n in (e.get("nonfinite") or [])})
    return [
        _finding(
            "nonfinite_loss",
            "critical",
            f"training losses went non-finite ({', '.join(names) or 'unnamed'}) in "
            f"{len(bad)} health check(s)",
            bad,
            "lower the learning rate / loosen gradient clipping, and consider "
            "metric.telemetry.abort_on_nonfinite=true so a diverged run fails fast",
            checks=len(bad),
            losses=names,
        )
    ]


def detect_unattributed_time(events: Events) -> List[Finding]:
    att = attribution(events)
    if att is None or att["wall_seconds"] < UNATTRIBUTED_MIN_WALL_SECONDS:
        return []
    unattributed = 1.0 - att["named_fraction"]
    if unattributed <= UNATTRIBUTED_FRACTION:
        return []
    windows = [w for w in _windows(events) if w.get("phases")]
    worst = sorted(
        windows,
        key=lambda w: _phase(w, "other") / max(float(w.get("wall_seconds") or 0.0), 1e-9),
        reverse=True,
    )
    return [
        _finding(
            "unattributed_time",
            "warning",
            f"{unattributed:.0%} of steady wall time is not attributed to any named "
            "phase — the attribution invariant is leaking",
            worst,
            "a loop phase is missing its Time/* span (env interaction, fused "
            "rollout, checkpoint, logging); see howto/observability.md §phase "
            "attribution",
            named_fraction=round(att["named_fraction"], 4),
            wall_seconds=round(att["wall_seconds"], 3),
        )
    ]


def _serve_windows(events: Events) -> List[Dict[str, Any]]:
    """Steady windows carrying a ``serve`` block (serving runs only — training
    streams contribute none, so the serving detectors are free no-ops there)."""
    return [w for w in _windows(events) if isinstance(w.get("serve"), dict)]


def _median(values: List[float]) -> float:
    values = sorted(values)
    n = len(values)
    if n == 0:
        return 0.0
    mid = n // 2
    return values[mid] if n % 2 else 0.5 * (values[mid - 1] + values[mid])


def detect_occupancy_collapse(events: Events) -> List[Finding]:
    """Batch occupancy fell away while sessions were still attached: the server
    is ticking mostly-empty batches — throughput is latency-bound, not
    compute-bound (coalescing window too short, or client think-time dominates)."""
    windows = _serve_windows(events)
    if len(windows) < SERVE_MIN_WINDOWS:
        return []
    occ = [_f(w["serve"].get("occupancy")) for w in windows]
    half = len(occ) // 2
    early, late = _median(occ[:half]), _median(occ[half:])
    late_windows = windows[half:]
    active = _median(
        [_f((w["serve"].get("sessions") or {}).get("active")) for w in late_windows]
    )
    if early <= 0 or active < 1 or late >= OCCUPANCY_COLLAPSE_RATIO * early:
        return []
    severity = "critical" if late < OCCUPANCY_COLLAPSE_CRITICAL * early else "warning"
    return [
        _finding(
            "occupancy_collapse",
            severity,
            f"batch occupancy collapsed {early:.2f} → {late:.2f} with ~{active:.0f} "
            "session(s) still attached — the step program is ticking mostly-empty batches",
            late_windows,
            "raise serve.max_batch_wait_ms so slow clients coalesce into one tick, "
            "or shrink serve.slots to match the real concurrency",
            early_occupancy=round(early, 4),
            late_occupancy=round(late, 4),
            active_sessions=active,
        )
    ]


def detect_latency_regression(events: Events) -> List[Finding]:
    """Per-step p99 latency of later windows far above the run's own median:
    the server got slower while serving (queue pressure, host contention, a
    recompile) — the SLO signal, independent of any absolute target. In a
    co-located live gang (a learner stream merged next to the serve stream —
    ``sheeprl.py live``) the learner's gradient bursts contend with the tick
    loop by design, so only spikes past :data:`LIVE_LATENCY_FLOOR_MS` count."""
    windows = _serve_windows(events)
    if len(windows) < SERVE_MIN_WINDOWS:
        return []
    p99s = [
        (_w, _f((_w["serve"].get("latency_ms") or {}).get("p99"))) for _w in windows
    ]
    p99s = [(w, v) for w, v in p99s if v > 0]
    if len(p99s) < SERVE_MIN_WINDOWS:
        return []
    live_gang = bool(_dataflow_windows(events, "learner"))
    floor = LIVE_LATENCY_FLOOR_MS if live_gang else 0.0
    baseline = _median([v for _, v in p99s])
    # window 0 absorbs the cold compiles — a spike there is startup, not drift
    affected = [
        (w, v)
        for w, v in p99s[1:]
        if v > max(LATENCY_REGRESSION_RATIO * baseline, floor)
    ]
    if not affected:
        return []
    worst = max(v for _, v in affected)
    severity = (
        "critical"
        if worst > LATENCY_REGRESSION_CRITICAL * baseline and len(affected) >= 2
        else "warning"
    )
    return [
        _finding(
            "latency_regression",
            severity,
            f"step-latency p99 regressed to {worst:.1f}ms in {len(affected)} window(s) "
            f"vs the run median {baseline:.1f}ms",
            [w for w, _ in affected],
            "check for host contention and recompiles (compile.window_count in the "
            "affected windows); if occupancy also rose, the table is saturated — "
            "raise serve.slots",
            baseline_p99_ms=round(baseline, 3),
            worst_p99_ms=round(worst, 3),
            windows=len(affected),
        )
    ]


def detect_slot_starvation(events: Events) -> List[Finding]:
    """Sessions queued for a slot while the table ran full: admission is
    throttled by capacity, not by traffic — sessions/sec is capped below demand."""
    windows = _serve_windows(events)
    if len(windows) < 2:
        return []
    starved = [
        w
        for w in windows
        if _f(w["serve"].get("queue_depth")) >= 1.0
        and _f(w["serve"].get("occupancy")) >= SLOT_STARVATION_OCCUPANCY
    ]
    if len(starved) < max(2, int(SLOT_STARVATION_FRACTION * len(windows))):
        return []
    depth = _median([_f(w["serve"].get("queue_depth")) for w in starved])
    slots = max(
        (
            int((e.get("serve") or {}).get("slots") or 0)
            for e in events
            if e.get("event") == "start"
        ),
        default=0,
    )
    return [
        _finding(
            "slot_starvation",
            "warning",
            f"sessions queued for a slot (median queue depth {depth:.1f}) while the "
            f"table ran full in {len(starved)}/{len(windows)} window(s)",
            starved,
            f"raise serve.slots (currently {slots or 'unknown'}) — the step program "
            "recompiles once for the new shape, then admission is O(1) again",
            queue_depth=round(depth, 2),
            starved_windows=len(starved),
            slots=slots or None,
        )
    ]


def detect_shed_rate(events: Events) -> List[Finding]:
    """Overload protection rejected admissions: demand exceeded `serve.slots` +
    `serve.max_queue` capacity. Working as designed — but an operator must see
    that traffic is being turned away (and how much) to size the server."""
    windows = _serve_windows(events)
    shed_windows = [
        w for w in windows if _f((w["serve"].get("sessions") or {}).get("shed")) > 0
    ]
    if not shed_windows:
        return []
    total_shed = int(sum(_f((w["serve"].get("sessions") or {}).get("shed")) for w in shed_windows))
    if total_shed < SHED_MIN_SESSIONS:
        return []
    worst = max(_f(w["serve"].get("shed_rate")) for w in shed_windows)
    if worst < SHED_RATE_WARNING:
        return []
    severity = "critical" if worst >= SHED_RATE_CRITICAL else "warning"
    return [
        _finding(
            "shed_rate",
            severity,
            f"{total_shed} session(s) shed by overload protection across "
            f"{len(shed_windows)} window(s) (worst window shed rate {worst:.0%})",
            shed_windows,
            "capacity is below demand: raise serve.slots (one recompile, then O(1) "
            "again), raise serve.max_queue if the bursts are short, or add servers",
            sessions_shed=total_shed,
            worst_shed_rate=round(worst, 4),
            windows=len(shed_windows),
        )
    ]


def detect_deadline_misses(events: Events) -> List[Finding]:
    """Requests dropped before the tick because their `serve.deadline_ms`
    expired: the server cannot turn batches around inside the latency budget
    (slow ticks, saturation, or a too-tight deadline)."""
    windows = _serve_windows(events)
    missed_windows = [
        w for w in windows if _f(w["serve"].get("deadline_missed")) > 0
    ]
    if not missed_windows:
        return []
    total_missed = int(sum(_f(w["serve"].get("deadline_missed")) for w in missed_windows))
    if total_missed < DEADLINE_MIN_MISSES:
        return []
    fractions = [
        _f(w["serve"].get("deadline_missed"))
        / max(_f(w["serve"].get("deadline_missed")) + _f(w.get("steps")), 1.0)
        for w in missed_windows
    ]
    worst = max(fractions)
    if worst < DEADLINE_MISS_WARNING:
        return []
    severity = "critical" if worst >= DEADLINE_MISS_CRITICAL else "warning"
    return [
        _finding(
            "deadline_misses",
            severity,
            f"{total_missed} request(s) exceeded serve.deadline_ms before their tick "
            f"across {len(missed_windows)} window(s) (worst window {worst:.0%} of requests)",
            missed_windows,
            "check the same windows' latency p99 and compile counts (a slow/stalling "
            "tick starves deadlines); widen serve.deadline_ms or shrink "
            "serve.max_batch_wait_ms if the budget is real",
            deadline_missed=total_missed,
            worst_miss_fraction=round(worst, 4),
            windows=len(missed_windows),
        )
    ]


def detect_reload_stall(events: Events) -> List[Finding]:
    """The hot-reload path is not keeping the server current: candidates are
    being rejected (torn/invalid — the old params keep serving, by design, but
    someone is producing bad checkpoints), or newer versions keep appearing
    without ever being applied (a wedged reload thread / unreadable source)."""
    # the weights block is CUMULATIVE state, conclusive from the last window
    # alone — so the final window is evidence here, not startup noise
    windows = [
        w for w in _windows(events, steady=False) if isinstance(w.get("serve"), dict)
    ]
    weighted = [w for w in windows if isinstance(w["serve"].get("weights"), dict)]
    if not weighted:
        return []
    findings: List[Finding] = []
    last = weighted[-1]["serve"]["weights"]
    failures = int(_f(last.get("failures")))
    if failures > 0:
        failed_windows = [
            w for w in weighted if _f(w["serve"]["weights"].get("failures")) > 0
        ]
        findings.append(
            _finding(
                "reload_stall",
                "warning",
                f"hot reload rejected {failures} candidate(s) (torn/invalid) — the old "
                f"version (v{int(_f(last.get('version')))}) kept serving",
                failed_windows[-4:],
                "inspect the producing run's checkpoints (sha256 sidecar mismatch = "
                "torn write); the server is safe but will not pick up new weights "
                "until a valid candidate lands",
                failures=failures,
                serving_version=int(_f(last.get("version"))),
            )
        )
    stalled = [
        w
        for w in weighted
        if _f(w["serve"]["weights"].get("available")) > _f(w["serve"]["weights"].get("version"))
    ]
    # judge only a stall that PERSISTS to the end of the run — a version that
    # was behind mid-run and applied later is the normal reload cadence
    tail = weighted[-RELOAD_STALL_WINDOWS:]
    if (
        len(tail) >= RELOAD_STALL_WINDOWS
        and all(w in stalled for w in tail)
        and failures == 0
    ):
        behind = int(
            _f(last.get("available")) - _f(last.get("version"))
        )
        findings.append(
            _finding(
                "reload_stall",
                "warning",
                f"a newer weight version has been available for {len(tail)}+ window(s) "
                f"without being applied (serving v{int(_f(last.get('version')))}, "
                f"available v{int(_f(last.get('available')))})",
                tail,
                "the reload thread is stalled or the source is unreadable: check "
                "serve.reload.poll_s and the reload events in the stream",
                versions_behind=behind,
                serving_version=int(_f(last.get("version"))),
                available_version=int(_f(last.get("available"))),
            )
        )
    return findings


def _dataflow_windows(events: Events, role: str) -> List[Dict[str, Any]]:
    """Steady windows carrying a ``dataflow`` block of the given role
    (``buffer.backend=service`` runs only — everything else contributes none,
    so the experience-plane detectors are free no-ops there)."""
    return [
        w
        for w in _windows(events)
        if isinstance(w.get("dataflow"), dict) and w["dataflow"].get("role") == role
    ]


def _by_stream(windows: List[Dict[str, Any]]) -> List[Tuple[Any, List[Dict[str, Any]]]]:
    """Group windows by their writer (stream label, falling back to rank) so a
    merged multi-actor dir is judged per actor, in stable order."""
    groups: Dict[Any, List[Dict[str, Any]]] = {}
    for w in windows:
        groups.setdefault(w.get("stream") or f"rank{w.get('rank', 0)}", []).append(w)
    return sorted(groups.items(), key=lambda kv: str(kv[0]))


def detect_weight_staleness(events: Events) -> List[Finding]:
    """Actors acting with weights materially behind the learner's published
    version: every env step they take trains the learner on off-policy-er data
    than the topology intends (the Podracer actor/learner-lag failure mode).
    An actor that NEVER refreshed (held version 0 while the plane advanced) is
    critical — its refresh path is broken, not slow."""
    findings: List[Finding] = []
    for stream, ws in _by_stream(_dataflow_windows(events, "actor")):
        lagging = [w for w in ws if _f(w["dataflow"].get("weight_lag")) >= WEIGHT_STALENESS_LAG]
        last = ws[-1]["dataflow"]
        # "never refreshed" is conclusive from the FINAL window alone: the held
        # version is cumulative, so 0-while-the-plane-advanced is a broken
        # refresh path, not a transient blip — no sustain requirement (the
        # actors may outrun the learner's first publish and still end stale)
        never = (
            int(_f(last.get("weight_version"))) == 0
            and _f(last.get("weight_latest")) >= WEIGHT_STALENESS_LAG
        )
        if len(lagging) < WEIGHT_STALENESS_WINDOWS and not never:
            continue
        worst = max(_f(w["dataflow"].get("weight_lag")) for w in (lagging or ws))
        if not lagging:
            lagging = [ws[-1]]
        findings.append(
            _finding(
                "weight_staleness",
                "critical" if never else "warning",
                (
                    f"actor stream {stream} never refreshed its weights "
                    f"(still at version 0 with {int(_f(last.get('weight_latest')))} published)"
                    if never
                    else f"actor stream {stream} acted {int(worst)} weight version(s) behind "
                    f"the learner across {len(lagging)} window(s)"
                ),
                lagging,
                "check the actor's weight-refresh path (buffer.service.poll_weights, "
                "the subscriber poll in its loop) and the learner's "
                "buffer.service.publish_every cadence",
                stream=str(stream),
                worst_lag=int(worst),
                windows=len(lagging),
                never_refreshed=never,
            )
        )
    if findings:
        return findings
    # learner-side fallback (a learner stream diagnosed alone, e.g. the in-loop
    # catalog): the ingest messages' held versions tell the same story
    for stream, ws in _by_stream(_dataflow_windows(events, "learner")):
        lagging = [
            w
            for w in ws
            if isinstance(w["dataflow"].get("weight_lag"), dict)
            and _f(w["dataflow"]["weight_lag"].get("max")) >= WEIGHT_STALENESS_LAG
        ]
        if not lagging:
            continue
        # held version = publisher current − lag: an actor whose lag equals the
        # whole published history never refreshed — conclusive, same rationale
        # as the actor-side check. Judged from the FINAL window only: mid-run a
        # drained backlog of early version-0 messages looks identical while the
        # actor has long since caught up.
        final_block = ws[-1]["dataflow"]
        final_lag = final_block.get("weight_lag") if isinstance(final_block.get("weight_lag"), dict) else {}
        current = _f(final_block.get("weight_version"))
        never_actors = sorted(
            r
            for r, v in (final_lag.get("per_actor") or {}).items()
            if _f(v) >= WEIGHT_STALENESS_LAG and current > 0 and _f(v) >= current
        )
        if len(lagging) < WEIGHT_STALENESS_WINDOWS and not never_actors:
            continue
        last = lagging[-1]["dataflow"]["weight_lag"]
        stale_actors = sorted(
            r for r, v in (last.get("per_actor") or {}).items() if _f(v) >= WEIGHT_STALENESS_LAG
        )
        worst = max(_f(w["dataflow"]["weight_lag"].get("max")) for w in lagging)
        findings.append(
            _finding(
                "weight_staleness",
                # same severity rule as the actor-side view of the identical
                # condition: a broken refresh path is critical from either side
                "critical" if never_actors else "warning",
                (
                    f"actor(s) {', '.join(never_actors)} never refreshed their weights "
                    f"(lag spans the whole published history, {int(worst)} version(s)) — "
                    "seen from the learner's ingest lineage"
                    if never_actors
                    else f"actor(s) {', '.join(stale_actors) or '?'} acted {int(worst)} weight "
                    f"version(s) behind the learner across {len(lagging)} window(s) "
                    "(seen from the learner's ingest lineage)"
                ),
                lagging,
                "check those actors' weight-refresh paths (buffer.service.poll_weights, "
                "subscriber polls) and buffer.service.publish_every",
                stream=str(stream),
                worst_lag=int(worst),
                actors=stale_actors,
                never_refreshed=bool(never_actors),
                windows=len(lagging),
            )
        )
    return findings


def detect_row_age_drift(events: Events) -> List[Finding]:
    """The learner's sampled-row age marching upward: training data is getting
    older in wall-clock terms — ingestion is outpacing consumption into a deep
    buffer, or the learner slowed down mid-run. Judged against the run's own
    early windows, not an absolute bar."""
    findings: List[Finding] = []
    for stream, ws in _by_stream(_dataflow_windows(events, "learner")):
        aged = [
            w
            for w in ws
            if isinstance((w["dataflow"].get("row_age") or {}).get("seconds"), dict)
        ]
        if len(aged) < ROW_AGE_MIN_WINDOWS:
            continue
        p50s = [_f(w["dataflow"]["row_age"]["seconds"].get("p50")) for w in aged]
        half = len(p50s) // 2
        early, late = _median(p50s[:half]), _median(p50s[half:])
        if late < ROW_AGE_MIN_SECONDS or (early > 0 and late < ROW_AGE_DRIFT_RATIO * early):
            continue
        severity = (
            "critical" if early > 0 and late >= 2 * ROW_AGE_DRIFT_RATIO * early else "warning"
        )
        last_age = aged[-1]["dataflow"]["row_age"]
        findings.append(
            _finding(
                "row_age_drift",
                severity,
                f"the learner's sampled-row age drifted {early:.1f}s → {late:.1f}s (p50) "
                f"over {len(aged)} window(s) — it is training on increasingly old data",
                aged[half:],
                "raise the learner's consumption (algo.replay_ratio, faster train "
                "rounds) or shrink buffer.size so the retained span stays fresh; "
                "check the same windows for ingest backpressure",
                stream=str(stream),
                early_p50_s=round(early, 3),
                late_p50_s=round(late, 3),
                late_p99_s=_f((last_age.get("seconds") or {}).get("p99")),
                late_p50_rounds=_f((last_age.get("rounds") or {}).get("p50")),
            )
        )
    return findings


def detect_ingest_backpressure(events: Events) -> List[Finding]:
    """Actors blocked on the flow-control watermark (the learner's drain cannot
    keep up) or a sustained learner-side ingest backlog: acting throughput is
    being throttled by the data plane, not by the envs."""
    findings: List[Finding] = []
    for stream, ws in _by_stream(_dataflow_windows(events, "actor")):
        if len(ws) < 2:
            continue
        # flow_block_seconds is cumulative: per-window deltas against wall time
        blocked: List[Tuple[Dict[str, Any], float]] = []
        prev = _f(ws[0]["dataflow"].get("flow_block_seconds"))
        for w in ws[1:]:
            cur = _f(w["dataflow"].get("flow_block_seconds"))
            wall = _f(w.get("wall_seconds"))
            frac = (cur - prev) / wall if wall > 0 else 0.0
            prev = cur
            if frac >= INGEST_BLOCK_WARNING:
                blocked.append((w, frac))
        if len(blocked) < 2:
            continue
        worst = max(frac for _, frac in blocked)
        findings.append(
            _finding(
                "ingest_backpressure",
                "critical" if worst >= INGEST_BLOCK_CRITICAL else "warning",
                f"actor stream {stream} spent up to {worst:.0%} of window wall time "
                f"blocked on ingest flow control across {len(blocked)} window(s) — "
                "the learner's drain cannot keep up",
                [w for w, _ in blocked],
                "raise buffer.service.max_inflight (more credit absorbs learner "
                "hiccups), speed up the learner's drain, or batch ingestion with "
                "buffer.service.flush_every",
                stream=str(stream),
                worst_block_fraction=round(worst, 4),
                windows=len(blocked),
            )
        )
    if findings:
        return findings
    # learner-side signal: a standing message backlog without actor streams in
    # view (the mean is cumulative — sustained means the backlog never drained)
    for stream, ws in _by_stream(_dataflow_windows(events, "learner")):
        deep = [w for w in ws if _f(w["dataflow"].get("queue_depth")) >= INGEST_QUEUE_DEPTH]
        if len(deep) < max(2, len(ws) // 2):
            continue
        worst = max(_f(w["dataflow"].get("queue_depth")) for w in deep)
        findings.append(
            _finding(
                "ingest_backpressure",
                "warning",
                f"the learner's ingest backlog held {worst:.1f} message(s) across "
                f"{len(deep)}/{len(ws)} window(s) — drain is behind publication",
                deep,
                "speed up the ingest drain (it contends with the sampler lock) or "
                "slow the actors (buffer.service.max_inflight bounds the damage)",
                stream=str(stream),
                worst_queue_depth=round(worst, 2),
                windows=len(deep),
            )
        )
    return findings


def _learning_windows(events: Events) -> List[Dict[str, Any]]:
    """Steady windows carrying a ``learning`` block (training runs with the
    learning plane on — everything else contributes none, so the training-
    health detectors are free no-ops on serving/old streams).

    Decoupled topologies MIRROR the learner's Learn block onto the player's
    primary stream (the channel reply ships it host-side), so a merged run dir
    would otherwise present every real window twice — doubling the affected
    counts the escalation thresholds key on. Judge ONE stream: the primary when
    it carries learning windows, else the stream with the most (the service
    learner's, whose player never trains)."""
    wins = [w for w in _windows(events) if isinstance(w.get("learning"), dict)]
    if not wins:
        return []
    groups: Dict[Any, List[Dict[str, Any]]] = {}
    for w in wins:
        groups.setdefault(w.get("stream") or f"rank{w.get('rank', 0)}", []).append(w)
    if len(groups) == 1:
        return wins
    from sheeprl_tpu.obs.streams import is_primary_event

    primary = [w for w in wins if is_primary_event(w)]
    if primary:
        return primary
    return max(groups.values(), key=len)


def _learn_stat(window: Dict[str, Any], key: str) -> Optional[float]:
    stats = (window.get("learning") or {}).get("stats") or {}
    value = stats.get(key)
    if isinstance(value, (int, float)) and value == value:  # NaN-safe
        return float(value)
    return None


def _learn_keys(windows: List[Dict[str, Any]], prefix: str) -> List[str]:
    keys: set = set()
    for w in windows:
        for k in ((w.get("learning") or {}).get("stats") or {}):
            if k.startswith(prefix):
                keys.add(k)
    return sorted(keys)


def _ep_return_series(events: Events) -> List[Tuple[Dict[str, Any], float]]:
    out: List[Tuple[Dict[str, Any], float]] = []
    for w in _learning_windows(events):
        ep = (w.get("learning") or {}).get("episodes") or {}
        ret = ep.get("return_p50", ep.get("return_mean"))
        if isinstance(ret, (int, float)):
            out.append((w, float(ret)))
    return out


def detect_grad_explosion(events: Events) -> List[Finding]:
    """Gradient norms far above the run's own median (or non-finite): the
    first casualty of a mis-scaled update, a bad batch, or an lr spike. Judged
    per module group on the window-max series (a one-step spike inside a fused
    multi-step round is exactly what must not be averaged away)."""
    windows = _learning_windows(events)
    findings: List[Finding] = []
    # non-finite gradient stats are conclusive from a single window
    bad = [
        (w, k)
        for w in windows
        for k in (w["learning"].get("nonfinite") or [])
        if k.startswith("grad_norm")
    ]
    if bad:
        names = sorted({k for _, k in bad})
        findings.append(
            _finding(
                "grad_explosion",
                "critical",
                f"non-finite gradient norm(s) ({', '.join(names)}) in "
                f"{len({id(w) for w, _ in bad})} window(s) — training is diverging",
                [w for w, _ in bad],
                "lower the learning rate / tighten gradient clipping; "
                "metric.telemetry.abort_on_nonfinite=true fails the run fast",
                stats=names,
            )
        )
    for key in _learn_keys(windows, "grad_norm_max/"):
        series = [(w, v) for w in windows if (v := _learn_stat(w, key)) is not None]
        if len(series) < LEARN_MIN_WINDOWS:
            continue
        median = _median([v for _, v in series])
        if median <= 0:
            continue
        affected = [(w, v) for w, v in series if v >= GRAD_EXPLOSION_RATIO * median]
        if not affected:
            continue
        group = key.split("/", 1)[1]
        worst = max(v for _, v in affected)
        severity = (
            "critical"
            if worst >= GRAD_EXPLOSION_CRITICAL * median or len(affected) >= 3
            else "warning"
        )
        findings.append(
            _finding(
                "grad_explosion",
                severity,
                f"the {group} gradient norm spiked to {worst:.3g} — "
                f"{worst / median:.0f}x the run median ({median:.3g}) across "
                f"{len(affected)} window(s)",
                [w for w, _ in affected],
                "look for an lr spike / bad batch at those steps (the window "
                "events' step field); tighten the group's clip_gradients, or "
                "lower its learning rate",
                group=group,
                worst=round(worst, 4),
                median=round(median, 4),
                windows=len(affected),
            )
        )
    return findings


def detect_entropy_collapse(events: Events) -> List[Finding]:
    """Policy entropy fell off a cliff relative to early training: the policy
    went (near-)deterministic long before the return justified it — exploration
    is dead and learning will plateau. Judged on DELTAS (continuous policies
    report differential entropy, which is legitimately negative)."""
    windows = _learning_windows(events)
    series = [(w, v) for w in windows if (v := _learn_stat(w, "entropy")) is not None]
    if len(series) < LEARN_MIN_WINDOWS:
        return []
    half = len(series) // 2
    early = _median([v for _, v in series[:half]])
    late = _median([v for _, v in series[half:]])
    drop = early - late
    scale = max(abs(early), 1.0)
    if drop < ENTROPY_COLLAPSE_DROP * scale:
        return []
    last = series[-1][1]
    severity = "critical" if drop >= 2 * ENTROPY_COLLAPSE_DROP * scale else "warning"
    return [
        _finding(
            "entropy_collapse",
            severity,
            f"policy entropy collapsed {early:.3g} → {late:.3g} (late-half median; "
            f"last window {last:.3g}) — the policy went near-deterministic",
            [w for w, _ in series[half:]],
            "raise the entropy coefficient (algo.ent_coef / actor.ent_coef), "
            "check the reward scale, and compare the episode-return curve — a "
            "collapse without a matching return rise is premature convergence",
            early=round(early, 4),
            late=round(late, 4),
            drop=round(drop, 4),
        )
    ]


def detect_value_overestimation(events: Events) -> List[Finding]:
    """Value/Q estimates growing far past the scale of anything the agent has
    actually collected: optimistic bootstrapping feeding on itself (the classic
    off-policy overestimation spiral). Needs both value stats and episode
    returns — without a return scale, big values might be legitimate."""
    windows = _learning_windows(events)
    key = next((k for k in ("q_mean", "value_mean") if any(_learn_stat(w, k) is not None for w in windows)), None)
    if key is None:
        return []
    series = [(w, v) for w in windows if (v := _learn_stat(w, key)) is not None]
    returns = _ep_return_series(events)
    if len(series) < LEARN_MIN_WINDOWS or not returns:
        return []
    half = len(series) // 2
    early = _median([v for _, v in series[:half]])
    late = _median([v for _, v in series[half:]])
    ret_scale = max(abs(_median([r for _, r in returns])), 1.0)
    if late < VALUE_OVER_SCALE * ret_scale or late < VALUE_OVER_GROWTH * max(abs(early), 1e-9):
        return []
    severity = "critical" if late >= VALUE_OVER_CRITICAL * ret_scale else "warning"
    return [
        _finding(
            "value_overestimation",
            severity,
            f"the {key.split('_')[0]} estimate grew {early:.3g} → {late:.3g} while episode "
            f"returns sit around {ret_scale:.3g} — bootstrapped optimism is "
            "feeding on itself",
            [w for w, _ in series[half:]],
            "check the TD-error quantiles in the same windows (a fat positive "
            "tail confirms it); lower gamma/learning rate, or strengthen the "
            "pessimism mechanism (twin critics, target-network cadence)",
            early=round(early, 4),
            late=round(late, 4),
            return_scale=round(ret_scale, 4),
        )
    ]


def detect_update_ratio_anomaly(events: Events) -> List[Finding]:
    """Update-to-param ratio of a module group spiking far above the run
    median: the optimizer briefly rewrote a material fraction of the weights —
    an lr-schedule bug, a moment-state corruption, or an unclipped spike that
    got through."""
    windows = _learning_windows(events)
    findings: List[Finding] = []
    for key in _learn_keys(windows, "update_ratio/"):
        series = [(w, v) for w in windows if (v := _learn_stat(w, key)) is not None]
        if len(series) < LEARN_MIN_WINDOWS:
            continue
        median = _median([v for _, v in series])
        if median <= 0:
            continue
        affected = [(w, v) for w, v in series if v >= UPDATE_RATIO_ANOMALY * median]
        if not affected:
            continue
        group = key.split("/", 1)[1]
        worst = max(v for _, v in affected)
        findings.append(
            _finding(
                "update_ratio_anomaly",
                "critical" if len(affected) >= 3 else "warning",
                f"the {group} update-to-param ratio spiked to {worst:.3g} — "
                f"{worst / median:.0f}x the run median across {len(affected)} window(s)",
                [w for w, _ in affected],
                "inspect the lr schedule around those steps and the matching "
                "grad_norm windows (an unclipped gradient spike shows in both)",
                group=group,
                worst=round(worst, 6),
                median=round(median, 6),
                windows=len(affected),
            )
        )
    return findings


def detect_kl_balance_drift(events: Events) -> List[Finding]:
    """Dreamer-family latent-dynamics health: the posterior/prior KL collapsing
    toward zero (posterior collapse — the representation stops carrying
    information) or exploding (the prior never catches the dynamics), or the
    posterior/prior entropy balance drifting materially."""
    windows = _learning_windows(events)
    series = [(w, v) for w in windows if (v := _learn_stat(w, "kl")) is not None]
    if len(series) < LEARN_MIN_WINDOWS:
        return []
    findings: List[Finding] = []
    half = len(series) // 2
    early = _median([v for _, v in series[:half]])
    late = _median([v for _, v in series[half:]])
    if early > 0 and late <= KL_COLLAPSE_RATIO * early:
        findings.append(
            _finding(
                "kl_balance_drift",
                "warning",
                f"the posterior/prior KL collapsed {early:.3g} → {late:.3g} — the "
                "posterior is converging onto the prior (representation collapse)",
                [w for w, _ in series[half:]],
                "lower kl_regularizer / raise kl_free_nats, and check the "
                "reconstruction losses — a collapsed KL with flat recon means "
                "the world model stopped learning",
                early=round(early, 4),
                late=round(late, 4),
                mode="collapse",
            )
        )
    elif early > 0 and late >= KL_EXPLOSION_RATIO * early:
        findings.append(
            _finding(
                "kl_balance_drift",
                "warning",
                f"the posterior/prior KL exploded {early:.3g} → {late:.3g} — the "
                "prior is not tracking the dynamics",
                [w for w, _ in series[half:]],
                "check kl_dynamic/kl_representation weighting and the world "
                "model's learning rate; a grad_explosion finding in the same "
                "windows points at the same root cause",
                early=round(early, 4),
                late=round(late, 4),
                mode="explosion",
            )
        )
    balance = [(w, v) for w in windows if (v := _learn_stat(w, "kl_balance")) is not None]
    if len(balance) >= LEARN_MIN_WINDOWS:
        bhalf = len(balance) // 2
        b_early = _median([v for _, v in balance[:bhalf]])
        b_late = _median([v for _, v in balance[bhalf:]])
        if abs(b_late - b_early) >= KL_BALANCE_DRIFT:
            findings.append(
                _finding(
                    "kl_balance_drift",
                    "warning",
                    f"the posterior/prior entropy balance drifted {b_early:.2f} → "
                    f"{b_late:.2f} — toward "
                    + ("posterior collapse" if b_late < b_early else "an uninformative prior"),
                    [w for w, _ in balance[bhalf:]],
                    "rebalance kl_dynamic vs kl_representation (dv3) or "
                    "kl_balancing_alpha (dv2); watch post/prior entropies in the "
                    "learning block",
                    early=round(b_early, 4),
                    late=round(b_late, 4),
                    mode="balance",
                )
            )
    return findings


def detect_reward_plateau(events: Events) -> List[Finding]:
    """Episode returns climbed, then flattened for the rest of the run: the
    sample-efficiency signal. Advisory (info): a plateau can be the task
    ceiling — the finding points at the step where improvement stopped so the
    learning-curve comparison (`compare`) can judge against another run."""
    returns = _ep_return_series(events)
    if len(returns) < REWARD_PLATEAU_MIN_WINDOWS:
        return []
    values = [r for _, r in returns]
    third = max(len(values) // 3, 1)
    early = _median(values[:third])
    peak = max(values)
    peak_idx = values.index(peak)
    mid = _median(values[-2 * third : -third])
    late = _median(values[-third:])
    climb = peak - early
    # the peak is a sample MAX against an early MEDIAN, so pure noise always
    # shows a small positive "climb" — require a material one (relative to the
    # curve's own scale) before claiming the run ever improved
    if climb < REWARD_PLATEAU_MIN_CLIMB * max(abs(peak), 1.0):
        return []
    # plateau = the curve climbed, then the final third stopped improving over
    # the third before it (a still-climbing run has late >> mid and never fires)
    if (late - mid) > REWARD_PLATEAU_EPS * climb:
        return []
    plateau_step = returns[peak_idx][0].get("step")
    return [
        _finding(
            "reward_plateau",
            "info",
            f"episode returns climbed {early:.3g} → {peak:.3g} (around step "
            f"{plateau_step}) then flattened at {late:.3g} for the rest of the run",
            [w for w, _ in returns[-third:]],
            "if this is below the task's known ceiling: check entropy_collapse "
            "(dead exploration) and the replay ratio; `sheeprl.py compare` "
            "against a healthy run gates the sample-efficiency regression",
            early=round(early, 4),
            peak=round(peak, 4),
            late=round(late, 4),
            peak_step=plateau_step,
        )
    ]


def _profile_events(events: Events) -> List[Dict[str, Any]]:
    """``profile_analysis`` events carrying a usable fractions dict (emitted
    in-loop when a window capture completes, or synthesized by the ``profile``
    verb from on-disk captures). Runs that never captured carry none — the
    three profile detectors below are structural no-ops there."""
    return [
        e
        for e in events
        if e.get("event") == "profile_analysis"
        and isinstance(e.get("categories"), dict)
        and _f(e.get("device_seconds")) >= PROFILE_MIN_DEVICE_SECONDS
    ]


def _worst_profile(events: Events, fraction_of: Callable[[Dict[str, Any]], float]):
    profiles = _profile_events(events)
    if not profiles:
        return None, 0.0
    worst = max(profiles, key=fraction_of)
    return worst, fraction_of(worst)


def _top_comm_program(profile: Dict[str, Any]) -> str:
    programs = profile.get("programs") or {}
    ranked = sorted(
        ((name, _f(p.get("comm_fraction"))) for name, p in programs.items()),
        key=lambda kv: -kv[1],
    )
    if ranked and ranked[0][1] > 0:
        return f" (worst program: {ranked[0][0]} at {ranked[0][1]:.0%} comm)"
    return ""


def detect_comm_bound(events: Events) -> List[Finding]:
    """Collectives dominate a window capture's device time: the program is
    scaling-bound, not chip-bound — more chips would make it *worse*."""
    worst, frac = _worst_profile(events, lambda e: _f(e["categories"].get("comm")))
    if worst is None or frac < PROFILE_COMM_WARNING:
        return []
    severity = "critical" if frac >= PROFILE_COMM_CRITICAL else "warning"
    return [
        _finding(
            "comm_bound",
            severity,
            f"collective communication is {frac:.0%} of the capture's device time"
            + _top_comm_program(worst),
            [worst],
            "shrink the synced surface (donate + keep state device-resident), "
            "overlap collectives with compute, or rebalance the mesh axes; "
            "`sheeprl.py profile` lists the per-program comm shares",
            comm_fraction=round(frac, 4),
            capture=worst.get("capture"),
        )
    ]


def detect_copy_bound(events: Events) -> List[Finding]:
    """Copy/layout ops dominate the capture: the program moves data instead of
    computing — usually a layout mismatch or host-visible staging."""
    worst, frac = _worst_profile(events, lambda e: _f(e["categories"].get("copy")))
    if worst is None or frac < PROFILE_COPY_WARNING:
        return []
    severity = "critical" if frac >= PROFILE_COPY_CRITICAL else "warning"
    return [
        _finding(
            "copy_bound",
            severity,
            f"copy/layout ops are {frac:.0%} of the capture's device time",
            [worst],
            "look for layout changes at program boundaries (transposes feeding "
            "donated carries), host-staged batches, or gather/scatter-heavy "
            "indexing that a reshape of the storage would remove",
            copy_fraction=round(frac, 4),
            capture=worst.get("capture"),
        )
    ]


def detect_host_gap(events: Events) -> List[Finding]:
    """The device sat idle (or fed by infeed/outfeed) for a large share of the
    capture: the fused calls are gapped by host work between dispatches."""
    worst, frac = _worst_profile(
        events,
        lambda e: _f(e["categories"].get("idle")) + _f(e["categories"].get("host")),
    )
    if worst is None or frac < PROFILE_HOST_GAP_WARNING:
        return []
    severity = "critical" if frac >= PROFILE_HOST_GAP_CRITICAL else "warning"
    return [
        _finding(
            "host_gap",
            severity,
            f"the device was idle or host-fed for {frac:.0%} of the capture",
            [worst],
            "move the loop's host round trips onto the device (fused rollout, "
            "buffer.backend=device), raise the per-dispatch work "
            "(algo.rollout_steps / scan length), or prefetch the host inputs",
            gap_fraction=round(frac, 4),
            capture=worst.get("capture"),
        )
    ]


VERSION_REGRESSION_MIN_STEPS = 20  # per-version ticks before the split is judged


def detect_version_regression(events: Events) -> List[Finding]:
    """A hot-reloaded weight version serves WORSE than its predecessor: either
    the in-loop promotion judge (serve/telemetry.py) already recorded a
    ``regressed`` verdict, or the cumulative per-version split shows the newest
    version's latency p50 beyond both versions' own p50→p90 spread."""
    regressed = [
        e
        for e in events
        if e.get("event") == "promotion" and e.get("verdict") == "regressed"
    ]
    if regressed:
        last = regressed[-1]
        return [
            _finding(
                "version_regression",
                "warning",
                f"the in-loop promotion judge marked weight v{last.get('version')} "
                f"REGRESSED vs v{last.get('baseline')}"
                + (f": {last.get('reason')}" if last.get("reason") else ""),
                regressed,
                "hot-reload the previous checkpoint back (howto/serving.md §hot "
                "reload) and `sheeprl.py compare` the learner run that published "
                "it against the last good one",
                version=last.get("version"),
                baseline=last.get("baseline"),
                reason=last.get("reason"),
            )
        ]
    carrier = None
    for e in reversed(events):
        if e.get("event") not in ("summary", "window"):
            continue
        serve = e.get("serve")
        versions = serve.get("versions") if isinstance(serve, dict) else None
        if isinstance(versions, dict) and len(versions) >= 2:
            carrier = e
            break
    if carrier is None:
        return []
    versions = carrier["serve"]["versions"]
    try:
        order = sorted(versions, key=lambda k: int(k))
    except (TypeError, ValueError):
        return []
    new_key, base_key = order[-1], order[-2]
    new, base = versions.get(new_key) or {}, versions.get(base_key) or {}
    if min(_f(new.get("steps")), _f(base.get("steps"))) < VERSION_REGRESSION_MIN_STEPS:
        return []
    nl, bl = new.get("latency_ms") or {}, base.get("latency_ms") or {}
    new_p50, base_p50 = _f(nl.get("p50")), _f(bl.get("p50"))
    spread = max(_f(nl.get("p90")) - new_p50, 0.0) + max(_f(bl.get("p90")) - base_p50, 0.0)
    if new_p50 <= 0 or base_p50 <= 0 or new_p50 <= base_p50 + spread:
        return []
    return [
        _finding(
            "version_regression",
            "warning",
            f"weight v{int(new_key)} serves slower than v{int(base_key)}: latency "
            f"p50 {new_p50:.1f}ms vs {base_p50:.1f}ms — beyond both versions' own "
            "p50→p90 spread",
            [carrier],
            "hot-reload the previous checkpoint back (howto/serving.md §hot "
            "reload); `sheeprl.py compare` the publishing learner run against "
            "the last good one for why the new policy got heavier",
            version=int(new_key),
            baseline=int(base_key),
            latency_p50_ms=round(new_p50, 3),
            baseline_latency_p50_ms=round(base_p50, 3),
        )
    ]


def detect_slo_alert(events: Events) -> List[Finding]:
    """SLO alerts still FIRING when the stream ended (obs/alerts.py): the
    stateful in-loop engine's verdict surfaces as a diagnosis finding, at the
    objective's own severity, so ``diagnose --fail-on`` gates on burned error
    budgets like any other defect."""
    last: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e.get("event") == "alert" and (e.get("name") or e.get("objective")):
            last[str(e.get("name") or e.get("objective"))] = e
    findings: List[Finding] = []
    for name in sorted(last):
        e = last[name]
        if e.get("status") != "firing":
            continue
        severity = e.get("severity") if e.get("severity") in _SEVERITY_RANK else "warning"
        value, target = e.get("value"), e.get("target")
        detail = (
            f" (value {value:g} vs target {target:g})"
            if isinstance(value, (int, float)) and isinstance(target, (int, float))
            else ""
        )
        findings.append(
            _finding(
                "slo_alert",
                str(severity),
                f"the `{name}` SLO alert was still firing when the stream ended"
                + detail,
                [e],
                "`sheeprl.py slo` prints the burn-rate report; the objective's "
                "signal names the subsystem the other detectors here diagnose",
                objective=name,
                value=value,
                target=target,
                budget_remaining=e.get("budget_remaining"),
            )
        )
    return findings


DETECTORS: Dict[str, Callable[[Events], List[Finding]]] = {
    "recompile_storm": detect_recompile_storm,
    "prefetch_starvation": detect_prefetch_starvation,
    "mfu_collapse": detect_mfu_collapse,
    "hbm_creep": detect_hbm_creep,
    "checkpoint_heavy": detect_checkpoint_heavy,
    "env_instability": detect_env_instability,
    "interruptions": detect_interruptions,
    "nonfinite_loss": detect_nonfinite_loss,
    "unattributed_time": detect_unattributed_time,
    "occupancy_collapse": detect_occupancy_collapse,
    "latency_regression": detect_latency_regression,
    "slot_starvation": detect_slot_starvation,
    "shed_rate": detect_shed_rate,
    "deadline_misses": detect_deadline_misses,
    "reload_stall": detect_reload_stall,
    "version_regression": detect_version_regression,
    "slo_alert": detect_slo_alert,
    "weight_staleness": detect_weight_staleness,
    "row_age_drift": detect_row_age_drift,
    "ingest_backpressure": detect_ingest_backpressure,
    "grad_explosion": detect_grad_explosion,
    "entropy_collapse": detect_entropy_collapse,
    "value_overestimation": detect_value_overestimation,
    "update_ratio_anomaly": detect_update_ratio_anomaly,
    "kl_balance_drift": detect_kl_balance_drift,
    "reward_plateau": detect_reward_plateau,
    "comm_bound": detect_comm_bound,
    "copy_bound": detect_copy_bound,
    "host_gap": detect_host_gap,
}


# ---------------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------------
def _f(value: Any) -> float:
    try:
        return float(value or 0.0)
    except (TypeError, ValueError):
        return 0.0


def attribution(events: Events) -> Optional[Dict[str, Any]]:
    """Share of steady-window wall time attributed to named phases. None when no
    steady window carries a phases breakdown (pre-attribution recordings)."""
    windows = [w for w in _windows(events) if isinstance(w.get("phases"), dict)]
    wall = sum(_f(w.get("wall_seconds")) for w in windows)
    if not windows or wall <= 0:
        return None
    named = sum(
        sum(_f(v) for k, v in w["phases"].items() if k != "other") for w in windows
    )
    return {
        "windows": len(windows),
        "wall_seconds": round(wall, 3),
        "named_seconds": round(named, 3),
        "named_fraction": round(min(named / wall, 1.0), 4),
    }


def run_detectors(
    events: Events, detectors: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Run (a subset of) the catalog over an ordered event stream; findings come
    back most-severe first. Detectors never raise on malformed/old events —
    anything they cannot read simply contributes no finding."""
    findings: List[Finding] = []
    for name in detectors or DETECTORS:
        fn = DETECTORS[name]
        try:
            findings.extend(fn(events))
        except Exception:  # a broken detector must not take diagnosis down
            continue
    findings.sort(key=lambda f: _SEVERITY_RANK.get(f["severity"], 3))
    return findings


def diagnose_events(events: Events) -> Dict[str, Any]:
    """The full diagnosis of one ordered event stream (merged or single-file)."""
    windows = _windows(events, steady=False)
    summaries = [e for e in events if e.get("event") == "summary"]
    return {
        "findings": run_detectors(events),
        "attribution": attribution(events),
        "counts": {
            "events": len(events),
            "windows": len(windows),
            "attempts": 1 + max((int(e.get("attempt") or 0) for e in events), default=0),
            "streams": len({e.get("stream") for e in events if e.get("stream")}),
            "clean_exit": bool(summaries[-1].get("clean_exit", True)) if summaries else None,
        },
    }


def diagnose_run(run_dir: str, json_path: Optional[str] = None) -> Dict[str, Any]:
    """Merge every telemetry stream under ``run_dir`` (obs/streams.py), diagnose,
    and write ``diagnosis.json`` (to ``json_path``, or into ``run_dir``)."""
    from sheeprl_tpu.obs.streams import discover_streams, load_stream, merge_streams

    streams = discover_streams(run_dir)
    if not streams:
        raise FileNotFoundError(f"no telemetry*.jsonl stream found under {run_dir!r}")
    base = run_dir if os.path.isdir(run_dir) else os.path.dirname(run_dir)
    events = merge_streams([load_stream(p, base_dir=base) for p in streams])
    result = diagnose_events(events)
    result["run_dir"] = str(run_dir)
    result["streams"] = [os.path.relpath(p, base) for p in streams]
    out = json_path or os.path.join(base, "diagnosis.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=False)
        fh.write("\n")
    result["json_path"] = out
    return result


def diagnose_fleet(
    fleet_dir: str, members: Dict[str, str], json_path: Optional[str] = None
) -> Dict[str, Any]:
    """Diagnose every member run of a fleet dir as ONE unit: per-member
    ``diagnose_run`` (each member keeps its own ``diagnosis.json``), plus an
    aggregate ``diagnosis.json`` at the fleet root whose ``findings`` are the
    union (member-tagged) — so ``--fail-on`` gates the whole sweep."""
    member_results: Dict[str, Any] = {}
    findings: List[Finding] = []
    for name, member_dir in members.items():
        try:
            result = diagnose_run(member_dir)
        except FileNotFoundError:
            member_results[name] = {"error": "no telemetry stream"}
            continue
        member_results[name] = {
            k: result.get(k) for k in ("findings", "attribution", "counts", "json_path")
        }
        for finding in result.get("findings") or []:
            findings.append({**finding, "member": name})
    if all("error" in r for r in member_results.values()):
        raise FileNotFoundError(
            f"no telemetry*.jsonl stream found under any member of fleet {fleet_dir!r}"
        )
    findings.sort(key=lambda f: _SEVERITY_RANK.get(f["severity"], 3))
    aggregate = {
        "fleet": str(fleet_dir),
        "members": member_results,
        "findings": findings,
        "counts": {
            "members": len(members),
            "diagnosed": sum(1 for r in member_results.values() if "error" not in r),
        },
    }
    out = json_path or os.path.join(str(fleet_dir), "diagnosis.json")
    with open(out, "w") as fh:
        json.dump(aggregate, fh, indent=2, sort_keys=False)
        fh.write("\n")
    aggregate["json_path"] = out
    return aggregate


def format_fleet_report(result: Dict[str, Any]) -> str:
    """Human report for a fleet diagnosis: one block per member."""
    lines = [f"Fleet telemetry diagnosis — {result.get('fleet')}"]
    counts = result.get("counts") or {}
    lines.append(f"  members : {counts.get('diagnosed', 0)}/{counts.get('members', 0)} diagnosed")
    for name, member in (result.get("members") or {}).items():
        if "error" in member:
            lines.append(f"  [{name}] {member['error']}")
            continue
        member_findings = member.get("findings") or []
        att = member.get("attribution") or {}
        lines.append(
            f"  [{name}] {len(member_findings)} finding(s)"
            + (
                f", {att['named_fraction']:.0%} attributed over {att['windows']} window(s)"
                if att
                else ""
            )
        )
        for f in member_findings:
            lines.append(f"    [{f['severity'].upper()}] {f['detector']}: {f['summary']}")
    return "\n".join(lines)


def format_report(result: Dict[str, Any]) -> str:
    """Human bottleneck report for one diagnosis result."""
    lines: List[str] = []
    counts = result.get("counts") or {}
    lines.append(f"Telemetry diagnosis — {result.get('run_dir', '<events>')}")
    streams = result.get("streams")
    if streams:
        lines.append(f"  streams : {len(streams)} ({', '.join(streams)})")
    lines.append(
        "  events  : "
        f"{counts.get('events', 0)} across {counts.get('attempts', 1)} attempt(s), "
        f"{counts.get('windows', 0)} telemetry window(s)"
    )
    att = result.get("attribution")
    if att:
        lines.append(
            f"  phases  : {att['named_fraction']:.1%} of {att['wall_seconds']:.1f}s "
            f"steady wall time attributed to named phases over {att['windows']} window(s)"
        )
    findings = result.get("findings") or []
    if not findings:
        lines.append("  verdict : no findings — the run looks healthy")
        return "\n".join(lines)
    lines.append(f"  verdict : {len(findings)} finding(s)")
    for f in findings:
        lines.append("")
        lines.append(f"[{f['severity'].upper()}] {f['detector']}")
        lines.append(f"  {f['summary']}")
        if f.get("evidence"):
            refs = ", ".join(
                "#{seq}{step}".format(
                    seq=r.get("seq"),
                    step=f" (step {r['step']})" if r.get("step") is not None else "",
                )
                for r in f["evidence"][:4]
            )
            lines.append(f"  evidence: events {refs}")
        lines.append(f"  try: {f['suggestion']}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py diagnose <run_dir>`` entry: print the report, write
    ``diagnosis.json``, exit 0 (or 1 with ``--fail-on`` when findings reach the
    given severity — the CI/bench gating mode)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="sheeprl.py diagnose",
        description="Diagnose a run's telemetry.jsonl stream(s): phase attribution, "
        "bottleneck findings, suggested knobs.",
    )
    parser.add_argument("run_dir", help="run directory (searched recursively) or a telemetry*.jsonl file")
    parser.add_argument("--json", dest="json_path", default=None, help="where to write diagnosis.json")
    parser.add_argument("--quiet", action="store_true", help="suppress the human report")
    parser.add_argument(
        "--fail-on",
        choices=("warning", "critical"),
        default=None,
        help="exit 1 when any finding is at least this severe",
    )
    args = parser.parse_args(list(argv) if argv is not None else sys.argv[1:])
    from sheeprl_tpu.obs.streams import fleet_members

    members = fleet_members(args.run_dir)
    try:
        if members:
            # a fleet dir diagnoses as ONE unit: per-member reports + an
            # aggregate whose member-tagged findings drive --fail-on
            result = diagnose_fleet(args.run_dir, members, json_path=args.json_path)
        else:
            result = diagnose_run(args.run_dir, json_path=args.json_path)
    except FileNotFoundError as exc:
        print(f"diagnose: {exc}", file=sys.stderr)
        return 2
    if not args.quiet:
        print(format_fleet_report(result) if members else format_report(result))
        print(f"\nwrote {result['json_path']}")
    if args.fail_on:
        gate = _SEVERITY_RANK[args.fail_on]
        if any(_SEVERITY_RANK.get(f["severity"], 3) <= gate for f in result["findings"]):
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
