"""Run telemetry subsystem (TPU-first observability).

The reference framework's observability story is timer spans + TensorBoard
(sheeprl/utils/timer.py, logger.py); on TPU the failure modes that actually cost
throughput — silent recompilation from shape churn, HBM creep, a starved replay
prefetch pipeline, sub-peak MFU — are invisible to wall-clocks. This package adds
the step-level telemetry layer the Podracer-style throughput work calls for
(PAPERS.md: "Podracer architectures", "EnvPool"):

- :func:`build_telemetry` / :class:`RunTelemetry` — the per-run facade every
  training loop threads through its iteration, train and shutdown hooks;
- :mod:`~sheeprl_tpu.obs.compile_monitor` — process-global XLA compile
  count/seconds accounting via ``jax.monitoring``;
- :mod:`~sheeprl_tpu.obs.profiler` — windowed ``jax.profiler`` trace capture
  (``metric.profiler.mode=window``) bounded to a configured policy-step window;
- :mod:`~sheeprl_tpu.obs.jsonl` — the structured ``telemetry.jsonl`` event sink
  consumed by ``bench.py`` (``conditions.telemetry``) and offline tooling;
- :mod:`~sheeprl_tpu.obs.streams` — discovery + ordered merge of a run's
  per-process / per-attempt streams (decoupled topologies, supervisor restarts);
- :mod:`~sheeprl_tpu.obs.diagnose` — the rule-based diagnosis engine over merged
  streams (``python sheeprl.py diagnose <run_dir>``), also run in-loop at window
  cadence and by ``bench.py`` (``conditions.diagnosis``);
- :mod:`~sheeprl_tpu.obs.fingerprint` — the run fingerprint (algo, config hash,
  code version, device/mesh shape, key shapes) stamped into telemetry ``start``
  events and bench ``conditions``, making streams comparable-by-construction;
- :mod:`~sheeprl_tpu.obs.watch` — live terminal monitor
  (``python sheeprl.py watch <run_dir>``) over the follow-mode stream reader;
- :mod:`~sheeprl_tpu.obs.compare` — cross-run diff
  (``python sheeprl.py compare``) and the BENCH_*.json regression gate
  (``python sheeprl.py bench-diff`` / ``bench.py --against``);
- :mod:`~sheeprl_tpu.obs.trace` — Perfetto/Chrome-trace export of the merged
  streams (``python sheeprl.py trace``): phase spans per window, one track per
  member/rank/role, cross-process dataflow flow events;
- :mod:`~sheeprl_tpu.obs.schema` — the versioned JSON schema for every
  ``telemetry.jsonl`` event type (producer/consumer drift fails loudly in CI);
- :mod:`~sheeprl_tpu.obs.metrics_http` — the opt-in Prometheus text-exposition
  endpoint (``metric.telemetry.http_port``) the telemetry facades serve.

See ``howto/observability.md`` for the config keys, the JSONL schema and the
detector catalog.
"""

from sheeprl_tpu.obs.compare import bench_diff, compare_runs, profile_run
from sheeprl_tpu.obs.compile_monitor import compile_snapshot, install_compile_monitor
from sheeprl_tpu.obs.diagnose import diagnose_events, diagnose_run, run_detectors
from sheeprl_tpu.obs.fingerprint import fingerprint_compatible, run_fingerprint
from sheeprl_tpu.obs.jsonl import JsonlEventSink
from sheeprl_tpu.obs.metrics_http import MetricsEndpoint
from sheeprl_tpu.obs.profiler import ProfilerWindow, resolve_profiler_config
from sheeprl_tpu.obs.schema import SCHEMA_VERSION, validate_events, validate_stream
from sheeprl_tpu.obs.trace import build_trace, trace_run
from sheeprl_tpu.obs.streams import (
    RunFollower,
    StreamCursor,
    discover_streams,
    merge_streams,
    merged_events,
)
from sheeprl_tpu.obs.telemetry import (
    NullTelemetry,
    RunTelemetry,
    build_role_telemetry,
    build_telemetry,
)
from sheeprl_tpu.obs.watch import watch_run

__all__ = [
    "JsonlEventSink",
    "MetricsEndpoint",
    "NullTelemetry",
    "ProfilerWindow",
    "RunFollower",
    "RunTelemetry",
    "SCHEMA_VERSION",
    "StreamCursor",
    "bench_diff",
    "build_trace",
    "build_role_telemetry",
    "build_telemetry",
    "compare_runs",
    "compile_snapshot",
    "diagnose_events",
    "diagnose_run",
    "discover_streams",
    "fingerprint_compatible",
    "install_compile_monitor",
    "merge_streams",
    "merged_events",
    "profile_run",
    "resolve_profiler_config",
    "run_detectors",
    "run_fingerprint",
    "trace_run",
    "validate_events",
    "validate_stream",
    "watch_run",
]
