"""Opt-in live metrics endpoint: Prometheus text exposition over plain HTTP.

``metric.telemetry.http_port`` (default off) makes the telemetry facade serve
the gauges it ALREADY aggregates — the training window gauges of
:class:`~sheeprl_tpu.obs.telemetry.RunTelemetry`, the serving window gauges of
:class:`~sheeprl_tpu.serve.telemetry.ServingTelemetry`, and the fleet runner's
member board — at ``GET /metrics`` in Prometheus text-exposition format
(version 0.0.4), so a ``PolicyServer`` or a fleet runner is scrapeable in
place with a stock Prometheus/Grafana stack. There is deliberately NO second
bookkeeping path: the telemetry window emit pushes the same numbers it writes
to ``telemetry.jsonl`` into the endpoint's gauge map, and the endpoint only
renders that map on scrape. That single push point is how new gauge families
arrive for free — e.g. the device-ring storage gauges
(``Buffer/ring_fill``/``ring_occupancy``/``ring_overwritten``,
howto/device_replay.md) and the window-capture attribution gauges
(``Perf/xla_comm_fraction``/``xla_mxu_fraction``/``xla_idle_fraction``,
howto/observability.md "Profiling a fused program") are scrapeable on any run
that produces them, with no endpoint change.

Off (the default ``http_port: null``) constructs nothing: no socket, no
thread, no artifact. ``http_port: 0`` binds an ephemeral port (tests read it
back from :attr:`MetricsEndpoint.port`). The listener binds
``metric.telemetry.http_host`` (default ``127.0.0.1`` — scraping across hosts
is an explicit opt-in, not a default exposure).

The same listener also answers ``GET /healthz`` — the readiness/liveness probe
the serving tier's drain/overload lifecycle needs (howto/serving.md,
"Operating a server"): the owner pushes a health dict via
:meth:`MetricsEndpoint.set_health` (``{"ready": bool, "status": str, ...}``)
and the probe returns it as JSON with 200 when ready, 503 when not (a draining
or still-loading server is alive but must be pulled from rotation). With no
health dict set the probe reports ``{"ready": true, "status": "ok"}`` — a
process serving metrics is, at minimum, alive.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Mapping, Optional

__all__ = ["MetricsEndpoint", "prometheus_name", "render_prometheus"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def prometheus_name(name: str, namespace: str = "sheeprl") -> str:
    """Map a telemetry gauge name onto the Prometheus grammar:
    ``Perf/sps`` → ``sheeprl_perf_sps``, ``Serve/latency_p99_ms`` →
    ``sheeprl_serve_latency_p99_ms``."""
    flat = _NAME_RE.sub("_", str(name)).strip("_").lower()
    return f"{namespace}_{flat}" if namespace else flat


def render_prometheus(
    gauges: Mapping[str, float],
    labels: Optional[Mapping[str, str]] = None,
    namespace: str = "sheeprl",
) -> str:
    """One gauge family per entry, ``# TYPE`` annotated, deterministic order."""
    label_str = ""
    if labels:
        # label VALUES must escape \ " \n per the exposition grammar — a run
        # name with a quote would otherwise fail every scrape of the endpoint
        def esc(v: Any) -> str:
            return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

        inner = ",".join(
            f'{prometheus_name(k, namespace="")}="{esc(v)}"' for k, v in sorted(labels.items())
        )
        label_str = "{" + inner + "}"
    lines = []
    for name in sorted(gauges):
        value = gauges[name]
        if value is None:
            continue
        prom = prometheus_name(name, namespace)
        lines.append(f"# TYPE {prom} gauge")
        lines.append(f"{prom}{label_str} {float(value):g}")
    return "\n".join(lines) + "\n"


class MetricsEndpoint:
    """A daemon-threaded HTTP listener rendering the current gauge map.

    ``update(gauges)`` merges (``replace=True`` swaps the whole map — the
    window emit's contract, so a gauge that disappears from the stream does not
    linger forever); ``close()`` shuts the listener down. Construction raises
    ``OSError`` on an unbindable port — callers decide whether that is fatal
    (the CLI warns and runs without the endpoint)."""

    def __init__(
        self,
        port: int,
        host: str = "127.0.0.1",
        *,
        labels: Optional[Mapping[str, str]] = None,
        namespace: str = "sheeprl",
    ) -> None:
        self._lock = threading.Lock()
        self._gauges: Dict[str, float] = {}
        self._health: Dict[str, Any] = {}
        self._labels = dict(labels or {})
        self._namespace = namespace
        endpoint = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — http.server API
                route = self.path.split("?", 1)[0]
                if route == "/healthz":
                    ready, payload = endpoint.health()
                    body = (json.dumps(payload) + "\n").encode("utf-8")
                    # readiness semantics: 503 pulls a draining/booting server
                    # out of rotation while the process stays alive (liveness
                    # is the connection itself)
                    self.send_response(200 if ready else 503)
                    self.send_header("Content-Type", "application/json; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if route not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = endpoint.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args: Any) -> None:
                pass  # scrapes are not run events; keep stdout clean

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self.host = self._server.server_address[0]
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sheeprl-metrics-http", daemon=True
        )
        self._thread.start()

    def update(self, gauges: Mapping[str, Any], replace: bool = True) -> None:
        numeric = {
            k: float(v)
            for k, v in gauges.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        with self._lock:
            if replace:
                self._gauges = numeric
            else:
                self._gauges.update(numeric)

    def set_health(self, health: Mapping[str, Any]) -> None:
        """Replace the ``/healthz`` payload. ``{"ready": bool, "status": str,
        ...}`` — extras (weight version, active sessions) pass through as
        JSON. The owner pushes state transitions (loading → ok → draining);
        the probe only renders."""
        with self._lock:
            self._health = dict(health)

    def health(self) -> tuple:
        with self._lock:
            payload = dict(self._health) if self._health else {"ready": True, "status": "ok"}
        payload.setdefault("ready", True)
        payload.setdefault("status", "ok" if payload["ready"] else "not_ready")
        return bool(payload["ready"]), payload

    def render(self) -> str:
        with self._lock:
            gauges = dict(self._gauges)
            labels = dict(self._labels)
        return render_prometheus(gauges, labels, self._namespace)

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)


def build_endpoint(
    tcfg: Mapping[str, Any], labels: Optional[Mapping[str, str]] = None
) -> Optional[MetricsEndpoint]:
    """The config-gated constructor every telemetry facade shares: None when
    ``http_port`` is unset (the zero-socket default), a bound endpoint
    otherwise; an unbindable port degrades to a warning, never a crash."""
    port = tcfg.get("http_port")
    if port is None or (isinstance(port, str) and not port.strip()):
        return None
    import warnings

    try:
        # ValueError/TypeError: the port may arrive as a raw override string
        # (fleet specs pass base args verbatim) — a typo degrades like a bind
        # failure, it must not kill the run the telemetry is supposed to watch
        return MetricsEndpoint(
            int(port), str(tcfg.get("http_host") or "127.0.0.1"), labels=labels
        )
    except (OSError, ValueError, TypeError) as exc:
        warnings.warn(
            f"telemetry: could not bind the metrics endpoint on port {port!r}: {exc} "
            "— continuing without it"
        )
        return None
