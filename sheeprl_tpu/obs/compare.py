"""Cross-run comparison + bench regression gating: "is B slower than A, and why?".

``diagnose`` (obs/diagnose.py) explains ONE run; this module answers the
questions that span two:

- ``python sheeprl.py compare <run_a> <run_b>`` — fingerprint-aware diff of two
  run dirs' telemetry streams. Per-window distributions (median / p10 / p90) of
  throughput, MFU and the phase breakdown, plus compile totals, peak memory and
  env restarts, with deltas flagged only when they exceed the runs' own
  window-distribution spread (so ordinary run-to-run noise does not page
  anyone). Findings share the severity/evidence/suggestion shape of
  ``diagnose``; the verdict is printed human-readable and written to
  ``comparison.json`` (``--json`` / ``--fail-on warning|critical`` for CI).
- ``python sheeprl.py bench-diff <old.json> <new.json>`` (also
  ``bench.py --against``) — the BENCH_*.json regression gate: workloads matched
  by metric name + fingerprint-compatible conditions, per-metric relative
  thresholds (default 5%), regressions attached machine-readably and gateable
  with ``--fail-on regression``.

Both tools read the run fingerprint (``obs/fingerprint.py``) stamped into
telemetry ``start`` events and bench ``conditions`` — a mismatch (different
config hash, backend, device shape) downgrades the comparison to a warning
instead of silently diffing apples against oranges; ``code_version`` is exempt
(comparing two commits is the point).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from sheeprl_tpu.obs.fingerprint import fingerprint_compatible

_SEVERITY_RANK = {"critical": 0, "warning": 1, "info": 2}

# flagging thresholds (module constants, like obs/diagnose.py's)
REL_FLOOR = 0.02  # ignore sub-2% relative deltas even when beyond noise
CRITICAL_DROP = 0.25  # a ≥25% throughput/MFU drop escalates to critical
PHASE_SHIFT_ABS = 0.05  # a phase must grow ≥5 points of wall share to flag
XLA_SHIFT_ABS = 0.05  # an op category must grow ≥5 points of device time to flag
XLA_SHIFT_CRITICAL = 0.20  # ...and ≥20 points escalates to critical
MEMORY_GROWTH = 0.10  # ≥10% peak-memory growth flags
COMPILE_STORM_DELTA = 3  # ≥3 extra compiles escalates to critical
DEFAULT_BENCH_THRESHOLD = 0.05  # bench-diff per-metric relative threshold
DATAFLOW_GROWTH = 0.25  # ≥25% staleness/latency growth flags (lower-is-better)
WEIGHT_LAG_DELTA = 2  # absolute extra weight versions of actor lag that flag
LEARNING_LOSS_GROWTH = 0.25  # ≥25% median loss growth flags (lower-is-better)
SLO_BUDGET_DROP = 0.10  # ≥10 points less error budget remaining flags

_PHASE_KEYS = (
    "env",
    "rollout",
    "replay_wait",
    "train",
    "checkpoint",
    "logging",
    "eval",
    "analysis",
    "other",
)

_PHASE_SUGGESTIONS = {
    "replay_wait": "the replay pipeline got slower: check buffer.prefetch.depth and host "
    "sampling throughput (howto/replay_prefetch.md)",
    "checkpoint": "checkpoint writes got heavier: checkpoint.async_save=true or raise "
    "checkpoint.every",
    "logging": "logging got heavier: raise metric.log_every or drop metric.log_level",
    "other": "unattributed time grew: a loop phase may have lost its Time/* span "
    "(howto/observability.md §phase attribution)",
    "env": "env interaction got slower: check env worker health and vectorization",
    "rollout": "the fused on-device rollout got slower: check the jax env's step cost "
    "and the anakin program's FLOPs split (howto/jax_envs.md)",
}


def _f(value: Any) -> float:
    try:
        return float(value or 0.0)
    except (TypeError, ValueError):
        return 0.0


def _quantile(sorted_values: Sequence[float], q: float) -> float:
    """Deterministic linear-interpolation quantile over a pre-sorted list."""
    n = len(sorted_values)
    if n == 1:
        return sorted_values[0]
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_values[lo] * (1.0 - frac) + sorted_values[hi] * frac


def _dist(values: Sequence[float]) -> Optional[Dict[str, Any]]:
    """{n, median, p10, p90} of a window-metric sample (None when empty)."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return None
    return {
        "n": len(vals),
        "median": round(_quantile(vals, 0.5), 6),
        "p10": round(_quantile(vals, 0.1), 6),
        "p90": round(_quantile(vals, 0.9), 6),
    }


def _spread(dist: Optional[Mapping[str, Any]]) -> float:
    """Half the p10→p90 span: the run's own window-to-window noise scale."""
    if not dist:
        return 0.0
    return max((_f(dist.get("p90")) - _f(dist.get("p10"))) / 2.0, 0.0)


def _finding(
    detector: str, severity: str, summary: str, suggestion: str, **metrics: Any
) -> Dict[str, Any]:
    return {
        "detector": detector,
        "severity": severity,
        "summary": summary,
        "evidence": [],
        "suggestion": suggestion,
        "metrics": metrics,
    }


# ---------------------------------------------------------------------------------
# run profiling
# ---------------------------------------------------------------------------------
def learning_curves(events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Noise-banded learning-curve extraction: one point per steady window
    carrying a ``learning`` block — policy step, the window's episode-return
    median with its own p10/p90 band, and the per-group loss means. This is
    the per-step sample-efficiency trace ``compare`` judges (and writes into
    ``comparison.json`` so CI artifacts carry the curves, not just verdicts)."""
    from sheeprl_tpu.obs.streams import is_primary_event as _primary

    points: List[Dict[str, Any]] = []
    for e in events:
        if e.get("event") != "window" or e.get("final") or not _primary(e):
            continue
        learning = e.get("learning")
        if not isinstance(learning, dict):
            continue
        point: Dict[str, Any] = {"step": e.get("step")}
        episodes = learning.get("episodes") or {}
        for src, dst in (
            ("return_p50", "return_p50"),
            ("return_p10", "return_p10"),
            ("return_p90", "return_p90"),
            ("count", "episodes"),
        ):
            if isinstance(episodes.get(src), (int, float)):
                point[dst] = episodes[src]
        losses = {
            k.split("/", 1)[1]: v
            for k, v in (learning.get("stats") or {}).items()
            if k.startswith("loss/") and isinstance(v, (int, float))
        }
        if losses:
            point["loss"] = losses
        if isinstance((learning.get("stats") or {}).get("entropy"), (int, float)):
            point["entropy"] = learning["stats"]["entropy"]
        if len(point) > 1:
            points.append(point)
    return points


def _profile_learning(events: Sequence[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The learning half of a run profile: window distributions of the
    episode-return median, per-group losses and entropy, plus the raw curve."""
    curve = learning_curves(events)
    if not curve:
        return None
    loss_keys = sorted({k for p in curve for k in (p.get("loss") or {})})
    return {
        "ep_return": _dist([p["return_p50"] for p in curve if "return_p50" in p]),
        "entropy": _dist([p["entropy"] for p in curve if "entropy" in p]),
        "losses": {
            k: _dist([p["loss"][k] for p in curve if k in (p.get("loss") or {})])
            for k in loss_keys
        },
        "episodes": sum(int(p.get("episodes") or 0) for p in curve),
        "curve": curve,
    }



def profile_run(events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Distill one merged event stream into the comparison profile: fingerprint,
    per-window distributions, totals. Only the run's PRIMARY stream (rank-0
    ``telemetry.jsonl``) feeds the window distributions — per-role learner
    windows measure a different cadence and would pollute them."""
    from sheeprl_tpu.obs.streams import is_primary_event as _primary

    windows = [
        e
        for e in events
        if e.get("event") == "window" and not e.get("final") and _primary(e)
    ]
    starts = [e for e in events if e.get("event") == "start" and _primary(e)]
    summaries = [e for e in events if e.get("event") == "summary" and _primary(e)]
    summary = summaries[-1] if summaries else None

    phases: Dict[str, Optional[Dict[str, Any]]] = {}
    tiled = [w for w in windows if isinstance(w.get("phases"), dict) and _f(w.get("wall_seconds")) > 0]
    for key in _PHASE_KEYS:
        phases[key] = _dist(
            [_f(w["phases"].get(key)) / _f(w["wall_seconds"]) for w in tiled]
        )

    if summary and isinstance(summary.get("compile"), dict):
        compile_totals = {
            "count": int(_f(summary["compile"].get("count"))),
            "seconds": round(_f(summary["compile"].get("seconds")), 3),
        }
    elif windows and isinstance(windows[-1].get("compile"), dict):
        compile_totals = {
            "count": int(_f(windows[-1]["compile"].get("count"))),
            "seconds": round(_f(windows[-1]["compile"].get("seconds")), 3),
        }
    else:
        compile_totals = {"count": 0, "seconds": 0.0}

    hbm_peak = max(
        [_f((w.get("hbm") or {}).get("peak_bytes")) for w in windows]
        + [_f(summary.get("hbm_peak_bytes")) if summary else 0.0]
        + [0.0]
    )
    rss_peak = max(
        [_f(w.get("rss_peak_bytes")) for w in windows]
        + [_f(summary.get("rss_peak_bytes")) if summary else 0.0]
        + [0.0]
    )
    # experience-plane dataflow (buffer.backend=service runs): staleness and
    # latency distributions pulled from EVERY stream's dataflow blocks — the
    # actor windows carry weight lag, the learner windows row age / ingest
    # latency / queue depth; ordinary runs profile None here
    df_windows = [
        e
        for e in events
        if e.get("event") == "window" and not e.get("final") and isinstance(e.get("dataflow"), dict)
    ]
    dataflow = None
    if df_windows:
        actor = [w["dataflow"] for w in df_windows if w["dataflow"].get("role") == "actor"]
        learner = [w["dataflow"] for w in df_windows if w["dataflow"].get("role") == "learner"]
        learner_lag = [
            _f(d["weight_lag"].get("max")) for d in learner if isinstance(d.get("weight_lag"), dict)
        ]
        dataflow = {
            "weight_lag": _dist(
                [_f(d.get("weight_lag")) for d in actor if not isinstance(d.get("weight_lag"), dict)]
                + learner_lag
            ),
            "row_age_p50_s": _dist(
                [
                    _f(d["row_age"]["seconds"].get("p50"))
                    for d in learner
                    if isinstance((d.get("row_age") or {}).get("seconds"), dict)
                ]
            ),
            "ingest_latency_p99_ms": _dist(
                [
                    _f(d["ingest_latency_ms"].get("p99"))
                    for d in learner
                    if isinstance(d.get("ingest_latency_ms"), dict)
                ]
            ),
            "queue_depth": _dist([_f(d.get("queue_depth")) for d in learner if d.get("queue_depth") is not None]),
        }

    # execution-profile attribution (profile_analysis events — obs/xprof.py):
    # per-category device-time-share distributions across the run's window
    # captures, so a comm/copy/idle regression between commits gates like an
    # sps regression. None on runs that never captured a window.
    prof_events = [
        e
        for e in events
        if e.get("event") == "profile_analysis" and isinstance(e.get("categories"), dict)
    ]
    xla = None
    if prof_events:
        keys = sorted({k for e in prof_events for k in e["categories"]})
        xla = {
            "captures": len(prof_events),
            "categories": {
                k: _dist([_f(e["categories"].get(k)) for e in prof_events]) for k in keys
            },
        }

    # env restarts: the counter is a per-ATTEMPT running total (each restart
    # attempt's telemetry starts back at 0), so take the max within each attempt
    # and sum across attempts — max over the whole stream would under-report
    # supervised multi-attempt runs
    restarts_per_attempt: Dict[int, int] = {}
    for e in events:
        if e.get("event") == "health" and e.get("status") == "env_restart":
            total = int(_f(e.get("total")))
        elif e.get("event") == "summary" and _primary(e):
            total = int(_f(e.get("env_restarts")))
        else:
            continue
        att = int(e.get("attempt") or 0)
        restarts_per_attempt[att] = max(restarts_per_attempt.get(att, 0), total)
    env_restarts = sum(restarts_per_attempt.values())
    # SLO end-state (obs/slo.py): each summary's final `slo` block, worst
    # budget-remaining per objective across every stream that declared SLOs
    # (a live gang ends with one per role); runs without SLOs profile None
    slo_objectives: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e.get("event") != "summary" or not isinstance(e.get("slo"), dict):
            continue
        for name, obj in (e["slo"].get("objectives") or {}).items():
            if not isinstance(obj, Mapping):
                continue
            held = slo_objectives.get(name)
            if held is None or _f(obj.get("budget_remaining")) < _f(held.get("budget_remaining")):
                slo_objectives[str(name)] = dict(obj)

    return {
        "fingerprint": (starts[-1].get("fingerprint") if starts else None),
        "windows": len(windows),
        "attempts": 1 + max((int(e.get("attempt") or 0) for e in events), default=0),
        "clean_exit": bool(summary.get("clean_exit", True)) if summary else None,
        "sps": _dist([_f(w.get("sps")) for w in windows if w.get("sps") is not None]),
        "mfu": _dist([_f(w.get("mfu")) for w in windows if isinstance(w.get("mfu"), (int, float))]),
        "phases": phases,
        "compile": compile_totals,
        "hbm_peak_bytes": int(hbm_peak) or None,
        "rss_peak_bytes": int(rss_peak) or None,
        "env_restarts": env_restarts,
        "dataflow": dataflow,
        "xla": xla,
        "slo": slo_objectives or None,
        # training-health curves (windows carrying a `learning` block): the
        # sample-efficiency half of the comparison — None on old/serving runs
        "learning": _profile_learning(events),
        "summary_sps": _f(summary.get("sps")) if summary and summary.get("sps") is not None else None,
    }


# ---------------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------------
def _delta_metric(
    a: Optional[Mapping[str, Any]],
    b: Optional[Mapping[str, Any]],
) -> Optional[Dict[str, Any]]:
    """Median delta of one window-distribution metric with the noise verdict."""
    if not a or not b:
        return None
    ma, mb = _f(a.get("median")), _f(b.get("median"))
    delta = mb - ma
    noise = max(_spread(a), _spread(b))
    rel = (delta / ma) if ma else None
    return {
        "a": dict(a),
        "b": dict(b),
        "delta": round(delta, 6),
        "rel": round(rel, 4) if rel is not None else None,
        "noise": round(noise, 6),
        "beyond_noise": abs(delta) > noise,
    }


def compare_profiles(
    profile_a: Mapping[str, Any], profile_b: Mapping[str, Any]
) -> Dict[str, Any]:
    """The fingerprint check + noise-aware metric deltas + findings for two run
    profiles (A = the reference/older run, B = the candidate run)."""
    findings: List[Dict[str, Any]] = []
    fp_a, fp_b = profile_a.get("fingerprint"), profile_b.get("fingerprint")
    compatible, mismatches = fingerprint_compatible(fp_a, fp_b)
    if mismatches:
        findings.append(
            _finding(
                "fingerprint_mismatch",
                "warning",
                "the runs are not fingerprint-compatible — they differ in "
                + ", ".join(mismatches)
                + "; the deltas below compare different experiments/hardware",
                "compare runs of the same exp config on the same device shape, or "
                "read the deltas as apples-to-oranges",
                mismatches=mismatches,
                a={k: (fp_a or {}).get(k) for k in mismatches},
                b={k: (fp_b or {}).get(k) for k in mismatches},
            )
        )

    metrics: Dict[str, Any] = {}

    # throughput + MFU: regressions only when the median moved beyond the spread
    for key, label, unit in (("sps", "throughput", "env-steps/sec"), ("mfu", "MFU", "")):
        dm = _delta_metric(profile_a.get(key), profile_b.get(key))
        metrics[key] = dm
        if dm is None or dm["rel"] is None:
            continue
        if dm["beyond_noise"] and abs(dm["rel"]) >= REL_FLOOR:
            pct = abs(dm["rel"])
            if dm["delta"] < 0:
                findings.append(
                    _finding(
                        f"{key}_regression",
                        "critical" if pct >= CRITICAL_DROP else "warning",
                        f"run B's median window {label} is {pct:.1%} below run A "
                        f"({dm['b']['median']:g} vs {dm['a']['median']:g}"
                        + (f" {unit}" if unit else "")
                        + ") — beyond both runs' window spread",
                        "read the phase deltas below for where the time went, then "
                        "`sheeprl.py diagnose` run B for the causal finding",
                        **{k: dm[k] for k in ("delta", "rel", "noise")},
                    )
                )
            else:
                findings.append(
                    _finding(
                        f"{key}_improvement",
                        "info",
                        f"run B's median window {label} is {pct:.1%} above run A "
                        f"({dm['b']['median']:g} vs {dm['a']['median']:g})",
                        "nothing to fix — record it",
                        **{k: dm[k] for k in ("delta", "rel", "noise")},
                    )
                )

    # phase shifts: a cost phase that grew materially beyond noise
    metrics["phases"] = {}
    for phase in _PHASE_KEYS:
        dm = _delta_metric(
            (profile_a.get("phases") or {}).get(phase), (profile_b.get("phases") or {}).get(phase)
        )
        metrics["phases"][phase] = dm
        if dm is None or phase == "train":
            continue
        if dm["beyond_noise"] and dm["delta"] >= PHASE_SHIFT_ABS:
            findings.append(
                _finding(
                    "phase_shift",
                    "warning",
                    f"the `{phase}` phase grew from {dm['a']['median']:.1%} to "
                    f"{dm['b']['median']:.1%} of window wall time",
                    _PHASE_SUGGESTIONS.get(
                        phase, f"profile the `{phase}` phase of run B (metric.profiler.mode=window)"
                    ),
                    phase=phase,
                    **{k: dm[k] for k in ("delta", "noise")},
                )
            )

    # execution-profile category shifts (profile_analysis events): a cost
    # category (comm/copy/idle/host/loop) whose device-time share grew
    # materially beyond the captures' own spread gates like an sps regression;
    # the compute categories (mxu/elementwise) growing is work, not waste
    xla_a = (profile_a.get("xla") or {}).get("categories") or {}
    xla_b = (profile_b.get("xla") or {}).get("categories") or {}
    if xla_a and xla_b:
        metrics["xla"] = {}
        for category in sorted(set(xla_a) | set(xla_b)):
            dm = _delta_metric(xla_a.get(category), xla_b.get(category))
            metrics["xla"][category] = dm
            if dm is None or category in ("mxu", "elementwise"):
                continue
            if dm["beyond_noise"] and dm["delta"] >= XLA_SHIFT_ABS:
                findings.append(
                    _finding(
                        "xla_category_shift",
                        "critical" if dm["delta"] >= XLA_SHIFT_CRITICAL else "warning",
                        f"the `{category}` share of captured device time grew from "
                        f"{dm['a']['median']:.1%} to {dm['b']['median']:.1%} — "
                        "beyond both runs' capture spread",
                        "`sheeprl.py profile` run B for the per-program attribution "
                        "(the comm_bound/copy_bound/host_gap detectors name the worst "
                        "program and the knob)",
                        category=category,
                        **{k: dm[k] for k in ("delta", "noise")},
                    )
                )

    # compile totals: any extra steady compiles are shape churn, not noise
    ca, cb = profile_a.get("compile") or {}, profile_b.get("compile") or {}
    metrics["compile"] = {"a": dict(ca), "b": dict(cb)}
    extra = int(_f(cb.get("count"))) - int(_f(ca.get("count")))
    if extra > 0:
        findings.append(
            _finding(
                "compile_regression",
                "critical" if extra >= COMPILE_STORM_DELTA else "warning",
                f"run B compiled {extra} more XLA program(s) than run A "
                f"({int(_f(cb.get('count')))} vs {int(_f(ca.get('count')))}, "
                f"{_f(cb.get('seconds')):.1f}s vs {_f(ca.get('seconds')):.1f}s)",
                "hunt for new shape churn between the two code/config versions; "
                "`sheeprl.py diagnose` run B (recompile_storm) pinpoints the windows",
                extra_compiles=extra,
                seconds_a=round(_f(ca.get("seconds")), 3),
                seconds_b=round(_f(cb.get("seconds")), 3),
            )
        )

    # peak memory: prefer HBM when both runs report it, fall back to host RSS
    for key, label in (("hbm_peak_bytes", "HBM"), ("rss_peak_bytes", "host RSS")):
        pa, pb = profile_a.get(key), profile_b.get(key)
        if not pa or not pb:
            continue
        metrics["memory"] = {"metric": key, "a": int(pa), "b": int(pb)}
        growth = (pb - pa) / pa
        if growth >= MEMORY_GROWTH:
            findings.append(
                _finding(
                    "memory_regression",
                    "warning",
                    f"run B's peak {label} grew {growth:.0%} over run A "
                    f"({pb / 2**30:.2f} vs {pa / 2**30:.2f} GiB)",
                    "check for lost donation / new retained device arrays "
                    "(howto/performance.md); compare the runs' program events",
                    growth=round(growth, 4),
                )
            )
        break

    # experience-plane dataflow: staleness/latency regressions (all lower-is-
    # better). Weight lag gates on an absolute version delta (2 extra versions
    # of off-policy lag is material whatever the baseline); the wall-clock
    # metrics gate relatively, beyond the runs' own window spread.
    dfa, dfb = profile_a.get("dataflow") or {}, profile_b.get("dataflow") or {}
    if dfa and dfb:
        metrics["dataflow"] = {}
        for key, label, unit, absolute in (
            ("weight_lag", "actor weight lag", "versions", WEIGHT_LAG_DELTA),
            ("row_age_p50_s", "sampled-row age p50", "s", None),
            ("ingest_latency_p99_ms", "ingest latency p99", "ms", None),
            ("queue_depth", "ingest queue depth", "messages", None),
        ):
            dm = _delta_metric(dfa.get(key), dfb.get(key))
            metrics["dataflow"][key] = dm
            if dm is None or dm["delta"] <= 0 or not dm["beyond_noise"]:
                continue
            flagged = (
                dm["delta"] >= absolute
                if absolute is not None
                else dm["rel"] is not None and dm["rel"] >= DATAFLOW_GROWTH
            )
            if flagged:
                findings.append(
                    _finding(
                        "dataflow_regression",
                        "warning",
                        f"run B's median {label} grew to {dm['b']['median']:g} {unit} "
                        f"from {dm['a']['median']:g} — the experience plane got staler",
                        "`sheeprl.py diagnose` run B (weight_staleness / row_age_drift / "
                        "ingest_backpressure) names the role and the knob; "
                        "`sheeprl.py trace` shows where the rows' wall time goes",
                        metric=key,
                        **{k: dm[k] for k in ("delta", "rel", "noise")},
                    )
                )

    # learning curves: sample-efficiency regressions. Episode return gates
    # higher-is-better, the per-group losses lower-is-better; entropy is
    # REPORTED but never gated alone (a lower entropy with an equal-or-better
    # return is convergence, not a defect — the direction is ambiguous).
    la, lb = profile_a.get("learning") or {}, profile_b.get("learning") or {}
    if la and lb:
        metrics["learning"] = {}
        dm = _delta_metric(la.get("ep_return"), lb.get("ep_return"))
        metrics["learning"]["ep_return"] = dm
        if dm is not None and dm["beyond_noise"] and dm["delta"] < 0:
            scale = max(abs(_f((dm.get("a") or {}).get("median"))), 1.0)
            pct = abs(dm["delta"]) / scale
            if pct >= REL_FLOOR:
                findings.append(
                    _finding(
                        "learning_regression",
                        "critical" if pct >= CRITICAL_DROP else "warning",
                        f"run B's median per-window episode return is "
                        f"{dm['b']['median']:g} vs run A's {dm['a']['median']:g} — "
                        "beyond both runs' window spread: B learns less from the "
                        "same steps",
                        "`sheeprl.py diagnose` run B for the causal finding "
                        "(entropy_collapse / grad_explosion / value_overestimation); "
                        "the comparison.json learning curves localize where the "
                        "trajectories diverge",
                        metric="ep_return",
                        **{k: dm[k] for k in ("delta", "rel", "noise")},
                    )
                )
        for key in sorted(set(la.get("losses") or {}) & set(lb.get("losses") or {})):
            dm = _delta_metric((la.get("losses") or {}).get(key), (lb.get("losses") or {}).get(key))
            metrics["learning"][f"loss/{key}"] = dm
            if dm is None:
                continue
            # growth over |A's median| (floored): policy/actor/alpha losses are
            # routinely NEGATIVE, so the signed rel would never cross a positive
            # threshold for half the loss keys — same scaling as the ep_return
            # gate above
            loss_scale = max(abs(_f((dm.get("a") or {}).get("median"))), 1.0)
            if (
                dm["beyond_noise"]
                and dm["delta"] > 0
                and dm["delta"] / loss_scale >= LEARNING_LOSS_GROWTH
            ):
                findings.append(
                    _finding(
                        "learning_regression",
                        "warning",
                        f"run B's median {key} loss grew {dm['delta'] / loss_scale:.0%} "
                        f"of run A's scale ({dm['b']['median']:g} vs {dm['a']['median']:g}) "
                        "— beyond both runs' window spread",
                        "diff the two configs' optimizer/clip settings and diagnose "
                        "run B (grad_explosion / kl_balance_drift name the group)",
                        metric=f"loss/{key}",
                        **{k: dm[k] for k in ("delta", "rel", "noise")},
                    )
                )
        metrics["learning"]["entropy"] = _delta_metric(la.get("entropy"), lb.get("entropy"))

    # SLO error budgets (obs/slo.py): an objective that ended run B with
    # materially less budget than run A — or exhausted (negative) when A was
    # not — burned its error budget faster at the same declared targets. Gated
    # on the runs' FINAL budget state (the whole-run compliance verdict), not
    # a window distribution: budget_remaining is already window-integrated.
    slo_a, slo_b = profile_a.get("slo") or {}, profile_b.get("slo") or {}
    if slo_a and slo_b:
        metrics["slo"] = {}
        for name in sorted(set(slo_a) & set(slo_b)):
            oa, ob = slo_a.get(name) or {}, slo_b.get(name) or {}
            ba, bb = oa.get("budget_remaining"), ob.get("budget_remaining")
            if not isinstance(ba, (int, float)) or not isinstance(bb, (int, float)):
                continue
            drop = float(ba) - float(bb)
            metrics["slo"][name] = {
                "a": round(float(ba), 4),
                "b": round(float(bb), 4),
                "drop": round(drop, 4),
            }
            exhausted = float(bb) < 0.0 <= float(ba)
            if drop >= SLO_BUDGET_DROP or exhausted:
                findings.append(
                    _finding(
                        "slo_budget_regression",
                        "critical" if exhausted else "warning",
                        f"run B ended with {float(bb):+.0%} of the `{name}` error "
                        f"budget remaining vs run A's {float(ba):+.0%}"
                        + (" — the objective is EXHAUSTED in B" if exhausted else ""),
                        "`sheeprl.py slo` run B for the burn-rate report and which "
                        "windows breached; `sheeprl.py diagnose` names the cause",
                        objective=name,
                        drop=round(drop, 4),
                        value_a=oa.get("value"),
                        value_b=ob.get("value"),
                    )
                )

    # env stability
    ra, rb = int(_f(profile_a.get("env_restarts"))), int(_f(profile_b.get("env_restarts")))
    metrics["env_restarts"] = {"a": ra, "b": rb}
    if rb > ra:
        findings.append(
            _finding(
                "env_instability_regression",
                "warning",
                f"run B absorbed {rb} env crash-restart(s) vs run A's {ra}",
                "inspect run B's env worker logs (health events with status=env_restart)",
                a=ra,
                b=rb,
            )
        )

    findings.sort(key=lambda f: (_SEVERITY_RANK.get(f["severity"], 3), f["detector"]))
    return {
        "fingerprint": {
            "compatible": compatible,
            "mismatches": mismatches,
            "a": fp_a,
            "b": fp_b,
        },
        "metrics": metrics,
        "findings": findings,
    }


def compare_runs(
    run_a: str, run_b: str, json_path: Optional[str] = None
) -> Dict[str, Any]:
    """Merge each run dir's telemetry stream(s), profile, compare, and write
    ``comparison.json`` (to ``json_path``, or into run B's dir)."""
    from sheeprl_tpu.obs.streams import discover_streams, merged_events

    profiles = {}
    for label, run_dir in (("a", run_a), ("b", run_b)):
        if not discover_streams(run_dir):
            raise FileNotFoundError(f"no telemetry*.jsonl stream found under {run_dir!r}")
        profiles[label] = profile_run(merged_events(run_dir))
    result = compare_profiles(profiles["a"], profiles["b"])
    result["run_a"] = {"dir": str(run_a), **{k: profiles["a"][k] for k in ("windows", "attempts", "clean_exit")}}
    result["run_b"] = {"dir": str(run_b), **{k: profiles["b"][k] for k in ("windows", "attempts", "clean_exit")}}
    # the raw noise-banded learning curves ride the artifact (CI plots them;
    # the findings above only carry the verdict)
    curves = {
        label: (profiles[label].get("learning") or {}).get("curve")
        for label in ("a", "b")
    }
    if any(curves.values()):
        result["learning_curves"] = curves
    base = run_b if os.path.isdir(run_b) else os.path.dirname(run_b)
    out = json_path or os.path.join(base, "comparison.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=2)
        fh.write("\n")
    result["json_path"] = out
    return result


def format_comparison(result: Mapping[str, Any]) -> str:
    """Human report for one comparison result."""
    lines: List[str] = []
    ra, rb = result.get("run_a") or {}, result.get("run_b") or {}
    lines.append(f"Run comparison — A: {ra.get('dir', '<events>')}  vs  B: {rb.get('dir', '<events>')}")
    fp = result.get("fingerprint") or {}
    lines.append(
        "  fingerprint : "
        + ("compatible" if fp.get("compatible", True) else f"MISMATCH ({', '.join(fp.get('mismatches') or [])})")
    )
    code_a = ((fp.get("a") or {}).get("code_version")) or "?"
    code_b = ((fp.get("b") or {}).get("code_version")) or "?"
    if code_a != code_b:
        lines.append(f"  code        : {code_a} → {code_b}")
    metrics = result.get("metrics") or {}
    for key, label in (("sps", "throughput"), ("mfu", "mfu")):
        dm = metrics.get(key)
        if dm:
            rel = f" ({dm['rel']:+.1%})" if dm.get("rel") is not None else ""
            flag = "  ← beyond noise" if dm.get("beyond_noise") else ""
            lines.append(
                f"  {label:<11} : median {dm['a']['median']:g} → {dm['b']['median']:g}{rel}"
                f"  [p10–p90 A: {dm['a']['p10']:g}–{dm['a']['p90']:g}]{flag}"
            )
    compile_m = metrics.get("compile") or {}
    if compile_m:
        a, b = compile_m.get("a") or {}, compile_m.get("b") or {}
        lines.append(
            f"  compiles    : {int(_f(a.get('count')))} ({_f(a.get('seconds')):.1f}s) → "
            f"{int(_f(b.get('count')))} ({_f(b.get('seconds')):.1f}s)"
        )
    learning_m = metrics.get("learning") or {}
    dm = learning_m.get("ep_return")
    if dm:
        flag = "  ← beyond noise" if dm.get("beyond_noise") else ""
        lines.append(
            f"  ep return   : median {dm['a']['median']:g} → {dm['b']['median']:g}"
            f"  [p10–p90 A: {dm['a']['p10']:g}–{dm['a']['p90']:g}]{flag}"
        )
    findings = result.get("findings") or []
    if not findings:
        lines.append("  verdict     : no findings — the runs are statistically alike")
        return "\n".join(lines)
    lines.append(f"  verdict     : {len(findings)} finding(s)")
    for f in findings:
        lines.append("")
        lines.append(f"[{f['severity'].upper()}] {f['detector']}")
        lines.append(f"  {f['summary']}")
        lines.append(f"  try: {f['suggestion']}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py compare <run_a> <run_b>``: print the report, write
    ``comparison.json``, gate with ``--fail-on``. Exit codes: 0 ok, 1 gated,
    2 when a run has no stream."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="sheeprl.py compare",
        description="Fingerprint-aware diff of two run dirs' telemetry streams: "
        "per-window distributions, noise-aware deltas, findings.",
    )
    parser.add_argument("run_a", help="reference run dir (or telemetry*.jsonl file)")
    parser.add_argument("run_b", help="candidate run dir (or telemetry*.jsonl file)")
    parser.add_argument("--json", dest="json_path", default=None, help="where to write comparison.json")
    parser.add_argument("--quiet", action="store_true", help="suppress the human report")
    parser.add_argument(
        "--fail-on",
        choices=("warning", "critical"),
        default=None,
        help="exit 1 when any finding is at least this severe",
    )
    args = parser.parse_args(list(argv) if argv is not None else sys.argv[1:])
    try:
        result = compare_runs(args.run_a, args.run_b, json_path=args.json_path)
    except FileNotFoundError as exc:
        print(f"compare: {exc}", file=sys.stderr)
        return 2
    if not args.quiet:
        print(format_comparison(result))
        print(f"\nwrote {result['json_path']}")
    if args.fail_on:
        gate = _SEVERITY_RANK[args.fail_on]
        if any(_SEVERITY_RANK.get(f["severity"], 3) <= gate for f in result["findings"]):
            return 1
    return 0


# ---------------------------------------------------------------------------------
# bench regression gate (BENCH_*.json trajectory)
# ---------------------------------------------------------------------------------
def load_bench_workloads(source: Any) -> List[Dict[str, Any]]:
    """Flatten one bench output into its workload list (headline + extras).

    Accepts a path or an already-parsed object, in any of the shapes the bench
    trajectory contains: the raw JSON-lines stdout of ``bench.py`` (the last
    line is the cumulative result), a single combined result object, or the
    driver wrapper ``{"tail": "<json lines>"}`` the BENCH_r*.json files use.
    A directory picks its newest ``BENCH_*.json`` (name order).
    """
    obj = source
    if isinstance(source, (str, os.PathLike)):
        path = str(source)
        if os.path.isdir(path):
            import glob

            candidates = sorted(glob.glob(os.path.join(path, "BENCH_*.json")))
            if not candidates:
                raise FileNotFoundError(f"no BENCH_*.json under {path!r}")
            path = candidates[-1]
        with open(path) as fh:
            text = fh.read()
        try:
            obj = json.loads(text)  # one (possibly pretty-printed) JSON document
        except json.JSONDecodeError:
            obj = _last_json_line(text)  # raw bench stdout: JSON lines
    if isinstance(obj, Mapping) and "tail" in obj and "metric" not in obj:
        obj = _last_json_line(str(obj["tail"]))
    if not isinstance(obj, Mapping) or "metric" not in obj:
        raise ValueError(f"not a bench result: {str(source)[:120]!r}")

    # recursive extras flatten: a workload may itself carry companion metrics
    # (serve_load reports sessions/sec with the p99-latency workload riding in
    # its own extras) — every nested level gates independently
    workloads: List[Dict[str, Any]] = []

    def _collect(entry: Mapping) -> None:
        row = dict(entry)
        nested = row.pop("extras", None) or []
        workloads.append(row)
        for e in nested:
            if isinstance(e, Mapping):
                _collect(e)

    _collect(obj)
    return workloads


def _last_json_line(text: str) -> Any:
    last = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            last = json.loads(line)
        except json.JSONDecodeError:
            continue
    if last is None:
        raise ValueError("no JSON object line found in bench output")
    return last


def _lower_is_better(unit: str) -> bool:
    # latency-style (seconds/ms) and memory-style (bytes) units regress UP —
    # the serve_load p99 step-latency workload gates in "ms" and dv3_2d_mesh
    # gates per-device parameter bytes. The "_ms"/" ms" suffix forms cover
    # metric-style units ("latency_ms") without false-matching substrings in
    # rate units ("items/sec"). The learning metrics gate by unit too: "loss"
    # regresses UP, while "return" (episode return) and "nats" (policy
    # entropy) are higher-is-better — the default — so an entropy workload can
    # never be gated backwards (direction-pinned in tests/test_obs/test_compare.py).
    # "fraction" covers failure-share metrics (serve_load_shed_rate: sessions
    # shed / offered) — more shedding at the same offered load regresses UP.
    unit = (unit or "").lower()
    return (
        unit.startswith("seconds")
        or "seconds/" in unit
        or unit.startswith("bytes")
        or "bytes/" in unit
        or unit.startswith("ms")
        or unit.startswith("milliseconds")
        or unit.endswith("_ms")
        or "_ms " in unit
        or unit.startswith("loss")
        or unit.startswith("fraction")
    )


def bench_diff(
    old: Any,
    new: Any,
    *,
    threshold: float = DEFAULT_BENCH_THRESHOLD,
    per_metric: Optional[Mapping[str, float]] = None,
) -> Dict[str, Any]:
    """Diff two bench results workload-by-workload.

    Matching: by metric name, then a fingerprint-compatibility check over each
    side's ``conditions.fingerprint`` (``code_version`` exempt) — an
    incompatible pair is reported as a warning, never as a regression. A
    workload regresses when its value moved against its unit's direction
    ("seconds"-style units are lower-is-better, rates higher-is-better) by more
    than the metric's relative threshold (``per_metric`` overrides, default
    ``threshold``)."""
    old_by_name = {w["metric"]: w for w in load_bench_workloads(old)}
    new_workloads = load_bench_workloads(new)
    per_metric = dict(per_metric or {})

    rows: List[Dict[str, Any]] = []
    regressions: List[str] = []
    improvements: List[str] = []
    warnings_: List[str] = []
    for w in new_workloads:
        name = str(w["metric"])
        thr = float(per_metric.get(name, threshold))
        row: Dict[str, Any] = {"metric": name, "threshold": thr, "new": w.get("value")}
        prev = old_by_name.get(name)
        if prev is None:
            row["status"] = "new"
            rows.append(row)
            continue
        row["old"] = prev.get("value")
        fp_old = (prev.get("conditions") or {}).get("fingerprint")
        fp_new = (w.get("conditions") or {}).get("fingerprint")
        compatible, mismatches = fingerprint_compatible(fp_old, fp_new)
        if not compatible:
            row["status"] = "incomparable"
            row["fingerprint_mismatches"] = mismatches
            warnings_.append(
                f"{name}: conditions not fingerprint-compatible ({', '.join(mismatches)}) — "
                "delta not gated"
            )
            rows.append(row)
            continue
        try:
            old_v, new_v = float(prev["value"]), float(w["value"])
        except (KeyError, TypeError, ValueError):
            row["status"] = "unreadable"
            rows.append(row)
            continue
        # signed change over |old|: a negative baseline (differential entropy
        # in nats, negative episode returns) must not flip the direction —
        # (new-old)/old would call an entropy collapse an "improvement"
        rel = (new_v - old_v) / abs(old_v) if old_v else None
        row["rel_change"] = round(rel, 4) if rel is not None else None
        # an explicit per-workload direction pin beats the unit heuristic —
        # serve_load_budget_remaining gates in "fraction" (normally a failure
        # share, lower-is-better) but MORE budget remaining is better
        pinned = str(w.get("direction") or prev.get("direction") or "").lower()
        if pinned.startswith("lower"):
            lower_better = True
        elif pinned.startswith("higher"):
            lower_better = False
        else:
            lower_better = _lower_is_better(str(w.get("unit") or prev.get("unit") or ""))
        row["direction"] = "lower-is-better" if lower_better else "higher-is-better"
        if rel is None:
            row["status"] = "unreadable"
        elif (rel > thr) if lower_better else (rel < -thr):
            row["status"] = "regression"
            regressions.append(name)
        elif (rel < -thr) if lower_better else (rel > thr):
            row["status"] = "improvement"
            improvements.append(name)
        else:
            row["status"] = "ok"
        # steadier signal than sps alone: surface a compile-count increase of the
        # same workload as a warning even when throughput stayed inside threshold
        old_compiles = (((prev.get("conditions") or {}).get("telemetry") or {}).get("compile") or {}).get("count")
        new_compiles = (((w.get("conditions") or {}).get("telemetry") or {}).get("compile") or {}).get("count")
        if old_compiles is not None and new_compiles is not None and int(new_compiles) > int(old_compiles):
            row["compile_delta"] = int(new_compiles) - int(old_compiles)
            warnings_.append(
                f"{name}: compile count grew {int(old_compiles)} → {int(new_compiles)} "
                "(shape churn between versions?)"
            )
        rows.append(row)

    missing = sorted(set(old_by_name) - {w["metric"] for w in new_workloads})
    return {
        "threshold": threshold,
        "workloads": rows,
        "regressions": regressions,
        "improvements": improvements,
        "warnings": warnings_,
        "missing_workloads": missing,
    }


def format_bench_diff(diff: Mapping[str, Any]) -> str:
    lines = [f"Bench diff (default threshold {diff.get('threshold', 0):.0%})"]
    for row in diff.get("workloads") or []:
        status = row.get("status", "?")
        rel = row.get("rel_change")
        detail = f" {rel:+.1%}" if isinstance(rel, (int, float)) else ""
        old_v = row.get("old")
        arrow = f"{old_v} → {row.get('new')}" if old_v is not None else f"{row.get('new')} (new)"
        lines.append(f"  [{status.upper():<12}] {row['metric']}: {arrow}{detail}")
    for w in diff.get("warnings") or []:
        lines.append(f"  warning: {w}")
    if diff.get("missing_workloads"):
        lines.append(f"  missing vs old: {', '.join(diff['missing_workloads'])}")
    n = len(diff.get("regressions") or [])
    lines.append(f"  verdict: {n} regression(s)" if n else "  verdict: no regressions")
    return "\n".join(lines)


def parse_threshold_args(values: Sequence[str]) -> Tuple[float, Dict[str, float]]:
    """``--threshold`` grammar shared by bench-diff and ``bench.py --against``:
    a bare float sets the default, ``metric=float`` sets a per-metric override;
    repeatable."""
    default = DEFAULT_BENCH_THRESHOLD
    per_metric: Dict[str, float] = {}
    for raw in values:
        if "=" in raw:
            name, _, value = raw.partition("=")
            per_metric[name.strip()] = float(value)
        else:
            default = float(raw)
    return default, per_metric


def bench_diff_main(argv: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py bench-diff <old.json> <new.json>``: exit 0 clean,
    1 under ``--fail-on regression`` with regressions, 2 on unreadable input."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="sheeprl.py bench-diff",
        description="Regression-gate two bench JSONs (BENCH_*.json trajectory): "
        "workloads matched by metric + fingerprint, per-metric relative thresholds.",
    )
    parser.add_argument("old", help="previous bench JSON (file or dir of BENCH_*.json)")
    parser.add_argument("new", help="candidate bench JSON (file or dir of BENCH_*.json)")
    parser.add_argument(
        "--threshold",
        action="append",
        default=[],
        metavar="PCT|metric=PCT",
        help=f"relative regression threshold (default {DEFAULT_BENCH_THRESHOLD}); "
        "repeatable, metric=0.1 overrides one workload",
    )
    parser.add_argument("--json", dest="json_path", default=None, help="write the diff JSON here")
    parser.add_argument("--quiet", action="store_true", help="suppress the human report")
    parser.add_argument(
        "--fail-on",
        choices=("regression",),
        default=None,
        help="exit 1 when any workload regressed beyond its threshold",
    )
    args = parser.parse_args(list(argv) if argv is not None else sys.argv[1:])
    try:
        default_thr, per_metric = parse_threshold_args(args.threshold)
        diff = bench_diff(args.old, args.new, threshold=default_thr, per_metric=per_metric)
    except (OSError, ValueError) as exc:
        print(f"bench-diff: {exc}", file=sys.stderr)
        return 2
    if args.json_path:
        with open(args.json_path, "w") as fh:
            json.dump(diff, fh, indent=2)
            fh.write("\n")
    if not args.quiet:
        print(format_bench_diff(diff))
    if args.fail_on == "regression" and diff["regressions"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
