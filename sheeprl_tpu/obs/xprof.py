"""Op-level attribution over ``jax.profiler`` window captures: the consumer
that turns "this run is slow" into "this program spends 31% of device time in
all-gathers".

``metric.profiler.mode=window`` (PR 2) makes every run able to dump a bounded
steady-state ``jax.profiler`` capture — but until now nothing in the repo ever
*parsed* one; reading it meant manual Perfetto spelunking. This module parses
the trace-event JSON the capture contains (both the CPU and TPU backends write
``<dump_dir>/plugins/profile/<ts>/<host>.trace.json.gz``) into per-device op
timelines and attributes the time three ways:

- **categories** — every device op (trace events carrying ``args.hlo_op``) is
  classified by its HLO opcode into ``comm`` (collectives), ``mxu``
  (dot/convolution — the MXU class), ``elementwise`` (fusions, reductions,
  math), ``copy`` (layout/data movement), ``loop`` (while/call/tuple plumbing)
  or ``host`` (infeed/outfeed), plus the computed ``idle`` gaps between ops on
  each device track. Categories + idle tile the capture's device time exactly
  (the acceptance invariant), so the fractions are comparable across runs.
- **programs** — ops carry ``args.hlo_module`` = ``jit_<fn name>``, and the
  PR 13 program registry names its fused programs after the jitted python
  function (``anakin_step``, ``sac_anakin_step``, ``train_step``), so module
  time joins against the registry's cost-model analysis (``program`` events:
  flops / bytes_accessed per call) to give achieved FLOP/s and arithmetic
  intensity per registered program.
- **roofline** — achieved intensity against the chip ridge point
  (``peak_flops / hbm_bytes_per_s``, both from public spec sheets keyed by
  ``device_kind`` like :mod:`sheeprl_tpu.utils.mfu`) labels each program
  compute-bound or memory-bound; a dominant comm share labels it comm-bound
  regardless (scaling, not the chip, is the wall). Off-TPU there is no honest
  ridge, so the label falls back to the category mix and the achieved numbers
  stand alone.

Consumers: ``python sheeprl.py profile <run_dir>`` (this module's ``main``)
writes ``profile.json`` + a human report and gates with ``--fail-on`` exactly
like ``diagnose``; ``RunTelemetry`` calls :func:`analyze_capture` in-loop when
a window capture completes and emits the schema-registered
``profile_analysis`` event (fractions feed the ``Perf/xla_*`` gauges, the
``comm_bound`` / ``copy_bound`` / ``host_gap`` detectors, ``compare``'s
profile-category deltas and ``bench.py``'s ``SHEEPRL_BENCH_PROFILE=1``
attachments). See ``howto/observability.md`` ("Profiling a fused program").
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import sys
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "CATEGORIES",
    "analyze_capture",
    "analyze_run",
    "classify_op",
    "find_captures",
    "format_report",
    "hbm_bytes_per_s",
    "load_trace_events",
    "main",
    "profile_event_payload",
]

# op-time categories; "idle" (computed per device track, not classified) rides
# along in every fractions dict so the shares tile to 1.0 by construction
CATEGORIES = ("comm", "mxu", "elementwise", "copy", "loop", "host")
IDLE = "idle"

# HBM bandwidth (bytes/s per chip, public spec sheets), keyed by lowercase
# substrings of Device.device_kind — the memory roofline to mfu._TPU_PEAK_BF16's
# compute roofline. Ridge intensity = peak_flops / hbm_bytes_per_s.
_TPU_HBM_BYTES_PER_S: Dict[str, float] = {
    "v2": 700e9,
    "v3": 900e9,
    "v4": 1228e9,
    "v5 lite": 819e9,
    "v5e": 819e9,
    "v5p": 2765e9,
    "v6 lite": 1640e9,
    "v6e": 1640e9,
}

# a registered program whose own comm share reaches this is comm-bound before
# any roofline question even applies (mirrors diagnose.PROFILE_COMM_WARNING)
PROGRAM_COMM_BOUND = 0.25

_TRAILING_ID = re.compile(r"\.\d+$")

_COMM_PREFIXES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective",
    "send",
    "recv",
    "partition-id",
    "replica-id",
)
_MXU_PREFIXES = ("dot", "conv", "cholesky", "triangular-solve")
_COPY_PREFIXES = (
    "copy",
    "transpose",
    "bitcast",
    "reshape",
    "broadcast",
    "concatenate",
    "slice",
    "dynamic-slice",
    "dynamic-update-slice",
    "pad",
    "gather",
    "scatter",
    "reverse",
)
_LOOP_PREFIXES = (
    "while",
    "condition",
    "body",
    "call",
    "conditional",
    "tuple",
    "get-tuple-element",
    "parameter",
    "constant",
)
_HOST_PREFIXES = ("infeed", "outfeed", "host")


def classify_op(name: str) -> str:
    """HLO opcode → category. Names come in as HLO instruction names
    (``all-reduce.3``, ``dot.6``, ``loop_fusion.12``): the trailing ``.<id>``
    is stripped and the base matched by opcode prefix, comm first (a
    ``reduce-scatter`` must not fall into the generic-reduce bucket).
    Everything unmatched — fusions, reductions, pointwise math — is the
    ``elementwise`` default."""
    base = _TRAILING_ID.sub("", str(name).strip().lower())
    if base.startswith(_COMM_PREFIXES):
        return "comm"
    if base.startswith(_MXU_PREFIXES) or "gemm" in base or "conv" in base:
        return "mxu"
    if base.startswith(_COPY_PREFIXES):
        return "copy"
    if base.startswith(_LOOP_PREFIXES):
        return "loop"
    if base.startswith(_HOST_PREFIXES):
        return "host"
    return "elementwise"


def hbm_bytes_per_s(device_kind: Optional[str]) -> Optional[float]:
    """HBM bandwidth for a device kind, or None when unknown (host CPU)."""
    kind = (device_kind or "").lower()
    for tag, bw in sorted(_TPU_HBM_BYTES_PER_S.items(), key=lambda kv: -len(kv[0])):
        if tag in kind:
            return bw
    return None


# ---------------------------------------------------------------------------------
# capture discovery + trace parsing
# ---------------------------------------------------------------------------------
def _trace_files(capture_dir: str) -> List[str]:
    files: List[str] = []
    for pattern in ("*.trace.json.gz", "*.trace.json"):
        files.extend(glob.glob(os.path.join(capture_dir, pattern)))
    return sorted(files)


def find_captures(root: str) -> List[str]:
    """Every capture (one ``plugins/profile/<timestamp>`` dir holding trace
    files) under ``root``. ``root`` may be a run dir, a profiler dump dir, or a
    timestamp dir itself."""
    root = str(root)
    if not os.path.isdir(root):
        return []
    if _trace_files(root):
        return [root]
    candidates = glob.glob(os.path.join(root, "plugins", "profile", "*")) + glob.glob(
        os.path.join(root, "**", "plugins", "profile", "*"), recursive=True
    )
    seen: Dict[str, None] = {}
    for cand in sorted(candidates):
        real = os.path.realpath(cand)
        if real not in seen and os.path.isdir(cand) and _trace_files(cand):
            seen[real] = None
    return list(seen)


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """Parse one ``*.trace.json(.gz)`` file into its raw trace-event list."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:  # type: ignore[operator]
        payload = json.load(fh)
    events = payload.get("traceEvents") if isinstance(payload, Mapping) else None
    return [e for e in (events or []) if isinstance(e, dict)]


def _union_seconds(intervals: List[Tuple[float, float]]) -> float:
    """Total covered length of a set of (start, end) intervals."""
    total = 0.0
    end = -float("inf")
    for lo, hi in sorted(intervals):
        if hi <= end:
            continue
        total += hi - max(lo, end)
        end = hi
    return total


# ---------------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------------
def analyze_capture(
    capture: str,
    programs: Optional[Mapping[str, Mapping[str, Any]]] = None,
    *,
    peak_flops: Optional[float] = None,
    device_kind: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """Attribute one capture's device time. ``programs`` is the registry join
    input (``{name: {flops, bytes_accessed, units, ...}}`` — the ``program``
    telemetry events). Returns None when the capture holds no device op events
    (an empty or foreign trace) — the callers treat that as "no capture"."""
    captures = find_captures(capture)
    if not captures:
        return None
    capture_dir = captures[-1]  # latest timestamp dir when given an ancestor
    op_events: List[Dict[str, Any]] = []
    trace_files = _trace_files(capture_dir)
    for path in trace_files:
        try:
            raw = load_trace_events(path)
        except (OSError, ValueError):
            continue
        for ev in raw:
            args = ev.get("args")
            if (
                ev.get("ph") == "X"
                and isinstance(args, Mapping)
                and args.get("hlo_op")
                and ev.get("dur") is not None
            ):
                op_events.append(ev)
    if not op_events:
        return None

    categories = {c: 0.0 for c in CATEGORIES}
    tracks: Dict[Any, List[Tuple[float, float]]] = {}
    modules: Dict[str, Dict[str, Any]] = {}
    for ev in op_events:
        dur = max(float(ev.get("dur") or 0.0), 0.0) / 1e6  # trace events are µs
        ts = float(ev.get("ts") or 0.0) / 1e6
        op = str(ev["args"]["hlo_op"])
        category = classify_op(op)
        categories[category] += dur
        tracks.setdefault(ev.get("pid"), []).append((ts, ts + dur))
        module = str(ev["args"].get("hlo_module") or "")
        mod = modules.setdefault(
            module,
            {"seconds": 0.0, "categories": {c: 0.0 for c in CATEGORIES}, "op_counts": {}},
        )
        mod["seconds"] += dur
        mod["categories"][category] += dur
        mod["op_counts"][op] = mod["op_counts"].get(op, 0) + 1

    # idle = per-device-track span minus the union of its op intervals: the gaps
    # between fused calls where the device sat waiting on the host. busy + idle
    # is the capture's total device time, so categories + idle tile it exactly.
    idle = 0.0
    for intervals in tracks.values():
        span = max(hi for _, hi in intervals) - min(lo for lo, _ in intervals)
        idle += max(span - _union_seconds(intervals), 0.0)
    busy = sum(categories.values())
    total = busy + idle
    if total <= 0:
        return None
    fractions = {c: categories[c] / total for c in CATEGORIES}
    fractions[IDLE] = idle / total

    bandwidth = hbm_bytes_per_s(device_kind)
    ridge = (peak_flops / bandwidth) if (peak_flops and bandwidth) else None
    programs = programs or {}
    prog_out: Dict[str, Dict[str, Any]] = {}
    for module, mod in sorted(modules.items(), key=lambda kv: -kv[1]["seconds"]):
        if mod["seconds"] <= 0:
            continue
        name = module[len("jit_") :] if module.startswith("jit_") else module
        # every call executes each HLO instruction once, so the per-module call
        # count is the max multiplicity of any single op in the module
        calls = max(mod["op_counts"].values())
        comm_fraction = mod["categories"]["comm"] / mod["seconds"]
        entry: Dict[str, Any] = {
            "module": module,
            "device_seconds": round(mod["seconds"], 6),
            "fraction": round(mod["seconds"] / total, 4),
            "calls": int(calls),
            "comm_fraction": round(comm_fraction, 4),
            "categories": {
                c: round(s, 6) for c, s in mod["categories"].items() if s > 0
            },
        }
        info = programs.get(name) or {}
        flops = info.get("flops")
        bytes_accessed = info.get("bytes_accessed")
        intensity = None
        if flops:
            entry["flops_per_call"] = float(flops)
            entry["achieved_flops_per_s"] = float(flops) * calls / mod["seconds"]
            if peak_flops:
                entry["achieved_peak_fraction"] = round(
                    entry["achieved_flops_per_s"] / peak_flops, 4
                )
            if bytes_accessed:
                intensity = float(flops) / float(bytes_accessed)
                entry["arithmetic_intensity"] = round(intensity, 3)
        if comm_fraction >= PROGRAM_COMM_BOUND:
            entry["bound"] = "comm"
        elif intensity is not None and ridge is not None:
            entry["bound"] = "compute" if intensity >= ridge else "memory"
        else:
            # no honest ridge (CPU, or no cost model): fall back to the mix
            copy = mod["categories"]["copy"]
            compute = mod["categories"]["mxu"] + mod["categories"]["elementwise"]
            entry["bound"] = "memory" if copy > compute else ("compute" if compute > 0 else None)
        prog_out[name] = entry

    return {
        "capture": capture_dir,
        "trace_files": [os.path.basename(p) for p in trace_files],
        "devices": len(tracks),
        "op_count": len(op_events),
        "device_seconds": round(total, 6),
        "busy_seconds": round(busy, 6),
        "idle_seconds": round(idle, 6),
        "categories": {c: round(s, 6) for c, s in categories.items()},
        "fractions": {c: round(f, 4) for c, f in fractions.items()},
        "programs": prog_out,
        "peak_flops": peak_flops,
        "hbm_bytes_per_s": bandwidth,
        "ridge_intensity": round(ridge, 3) if ridge else None,
    }


def profile_event_payload(analysis: Mapping[str, Any]) -> Dict[str, Any]:
    """The ``profile_analysis`` telemetry-event projection of one capture
    analysis: the fractions and the per-program verdicts, without the raw
    per-category second tables (the stream stays compact; ``profile.json``
    keeps the full analysis)."""
    programs = {
        name: {
            k: p.get(k)
            for k in (
                "fraction",
                "calls",
                "comm_fraction",
                "achieved_flops_per_s",
                "arithmetic_intensity",
                "bound",
            )
            if p.get(k) is not None
        }
        for name, p in (analysis.get("programs") or {}).items()
    }
    return {
        "capture": analysis.get("capture"),
        "device_seconds": analysis.get("device_seconds"),
        "busy_seconds": analysis.get("busy_seconds"),
        "categories": dict(analysis.get("fractions") or {}),
        "programs": programs,
    }


# ---------------------------------------------------------------------------------
# run-level analysis (the `profile` verb)
# ---------------------------------------------------------------------------------
def _stream_context(run_dir: str) -> Tuple[List[Dict[str, Any]], List[str]]:
    """(merged events, capture dirs recorded in the profiler events). A run dir
    without any telemetry stream still profiles — captures are then discovered
    by globbing — so both halves tolerate absence."""
    try:
        from sheeprl_tpu.obs.streams import merged_events

        events = merged_events(run_dir)
    except (FileNotFoundError, OSError):
        events = []
    dirs: List[str] = []
    for ev in events:
        if ev.get("event") == "profiler" and ev.get("dir"):
            path = str(ev["dir"])
            if path not in dirs:
                dirs.append(path)
    return events, dirs


def analyze_run(run_dir: str, json_path: Optional[str] = None) -> Dict[str, Any]:
    """Profile every capture of a run: enumerate captures from the telemetry
    stream's ``profiler`` events (satellite: the events record their capture
    dir) with a recursive glob fallback, join against the stream's ``program``
    registry + ``start`` device facts, and write ``profile.json``. Raises
    FileNotFoundError when the run holds no parseable capture."""
    events, recorded_dirs = _stream_context(run_dir)
    base = run_dir if os.path.isdir(run_dir) else os.path.dirname(run_dir)

    captures: Dict[str, None] = {}
    for recorded in recorded_dirs:
        for cap in find_captures(recorded):
            captures.setdefault(os.path.realpath(cap))
    for cap in find_captures(base or "."):
        captures.setdefault(os.path.realpath(cap))

    programs = {
        str(e["name"]): e
        for e in events
        if e.get("event") == "program" and e.get("name") and not e.get("error")
    }
    start = next((e for e in events if e.get("event") == "start"), {})
    peak = start.get("peak_flops")
    device_kind = start.get("device_kind")

    analyses = [
        a
        for cap in captures
        if (a := analyze_capture(cap, programs, peak_flops=peak, device_kind=device_kind))
    ]
    if not analyses:
        raise FileNotFoundError(
            f"no parseable profiler capture found under {run_dir!r} — run with "
            "metric.profiler.mode=window to produce one"
        )

    # aggregate: capture-duration-weighted category fractions across captures
    total = sum(a["device_seconds"] for a in analyses)
    agg = {
        c: round(
            sum(a["categories"].get(c, 0.0) for a in analyses) / total if total else 0.0, 4
        )
        for c in CATEGORIES
    }
    agg[IDLE] = round(sum(a["idle_seconds"] for a in analyses) / total if total else 0.0, 4)

    # findings come from the SAME detectors diagnose runs in-loop, over the
    # event payloads these captures would have emitted — one threshold catalog
    from sheeprl_tpu.obs.diagnose import run_detectors

    pseudo = [
        {"event": "profile_analysis", "seq": i, **profile_event_payload(a)}
        for i, a in enumerate(analyses)
    ]
    findings = run_detectors(pseudo, detectors=("comm_bound", "copy_bound", "host_gap"))

    result: Dict[str, Any] = {
        "run_dir": str(run_dir),
        "captures": analyses,
        "device_seconds": round(total, 6),
        "categories": agg,
        "findings": findings,
    }
    out = json_path or os.path.join(base or ".", "profile.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=False)
        fh.write("\n")
    result["json_path"] = out
    return result


def format_report(result: Mapping[str, Any]) -> str:
    """Human report: category shares, per-program roofline verdicts, findings."""
    lines = [f"XLA execution profile — {result.get('run_dir', '<capture>')}"]
    analyses = result.get("captures") or []
    lines.append(
        f"  captures: {len(analyses)}, "
        f"{result.get('device_seconds', 0.0):.4f}s device time"
    )
    shares = ", ".join(
        f"{c} {f:.1%}" for c, f in (result.get("categories") or {}).items() if f > 0
    )
    lines.append(f"  op time : {shares}")
    for analysis in analyses:
        lines.append(f"  [{analysis['capture']}]")
        for name, prog in (analysis.get("programs") or {}).items():
            bits = [f"{prog['fraction']:.1%} of device time", f"{prog['calls']} call(s)"]
            if prog.get("achieved_flops_per_s"):
                bits.append(f"{prog['achieved_flops_per_s'] / 1e9:.2f} GFLOP/s")
            if prog.get("arithmetic_intensity") is not None:
                bits.append(f"intensity {prog['arithmetic_intensity']:.1f} FLOP/B")
            if prog.get("bound"):
                bits.append(f"{prog['bound']}-bound")
            lines.append(f"    {name}: " + ", ".join(bits))
    findings = result.get("findings") or []
    if not findings:
        lines.append("  verdict : no findings — the capture looks healthy")
        return "\n".join(lines)
    lines.append(f"  verdict : {len(findings)} finding(s)")
    for f in findings:
        lines.append("")
        lines.append(f"[{f['severity'].upper()}] {f['detector']}")
        lines.append(f"  {f['summary']}")
        lines.append(f"  try: {f['suggestion']}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py profile <run_dir>`` entry: print the report, write
    ``profile.json``, exit 0 (or 1 with ``--fail-on`` when findings reach the
    given severity, or 2 when the run holds no capture)."""
    import argparse

    from sheeprl_tpu.obs.diagnose import _SEVERITY_RANK

    parser = argparse.ArgumentParser(
        prog="sheeprl.py profile",
        description="Attribute a run's jax.profiler window capture(s): op-category "
        "shares, achieved FLOP/s + roofline position per registered program.",
    )
    parser.add_argument(
        "run_dir", help="run directory (searched recursively) or a profiler capture dir"
    )
    parser.add_argument("--json", dest="json_path", default=None, help="where to write profile.json")
    parser.add_argument("--quiet", action="store_true", help="suppress the human report")
    parser.add_argument(
        "--fail-on",
        choices=("warning", "critical"),
        default=None,
        help="exit 1 when any finding is at least this severe",
    )
    args = parser.parse_args(list(argv) if argv is not None else sys.argv[1:])
    try:
        result = analyze_run(args.run_dir, json_path=args.json_path)
    except FileNotFoundError as exc:
        print(f"profile: {exc}", file=sys.stderr)
        return 2
    if not args.quiet:
        print(format_report(result))
        print(f"\nwrote {result['json_path']}")
    if args.fail_on:
        gate = _SEVERITY_RANK[args.fail_on]
        if any(_SEVERITY_RANK.get(f["severity"], 3) <= gate for f in result["findings"]):
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
