"""Process-global XLA compile accounting via ``jax.monitoring``.

Every backend compile (first trace of a jitted program, or a RECOMPILE from shape
churn) fires the ``/jax/core/compile/backend_compile_duration`` monitoring event.
A single listener — installed once per process; ``jax.monitoring`` has no
per-listener removal — accumulates count and wall seconds into a module-global
struct, and :func:`compile_snapshot` reads it. :class:`RunTelemetry` diffs
snapshots per log window to drive the ``Compile/count`` / ``Compile/seconds``
gauges and the unexpected-recompile warning.

On remote TPU backends a compile is minutes, not milliseconds (TPU_PROBE_LOG.md:
>9 min cold for the Dreamer-V3 train program), so an unnoticed steady-state
recompile loop is the single most expensive silent failure this repo has; this
counter is what makes it visible.
"""

from __future__ import annotations

import threading
from typing import Dict

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# Fired by jax's compilation cache on a PERSISTENT-cache hit. The backend-compile
# duration event above wraps compile_or_get_cached, so a cache hit still counts
# there (with near-zero seconds) — `count - cache_hits` is the COLD compile count,
# the number the fleet runner's shared-compile-cache rollup gates on.
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_lock = threading.Lock()
_state: Dict[str, float] = {"count": 0, "seconds": 0.0, "cache_hits": 0}
_installed = False


def _listener(event: str, duration_secs: float, **_kwargs) -> None:
    if event != _BACKEND_COMPILE_EVENT:
        return
    with _lock:
        _state["count"] += 1
        _state["seconds"] += float(duration_secs)


def _event_listener(event: str, **_kwargs) -> None:
    if event != _CACHE_HIT_EVENT:
        return
    with _lock:
        _state["cache_hits"] += 1


def install_compile_monitor() -> None:
    """Idempotently register the backend-compile duration + cache-hit listeners."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_listener)
    jax.monitoring.register_event_listener(_event_listener)


def compile_snapshot() -> Dict[str, float]:
    """Cumulative ``{"count", "seconds", "cache_hits"}`` of backend compiles seen
    so far (``count`` includes persistent-cache hits — their compile seconds are
    the cache *lookup*; ``count - cache_hits`` is the cold compiles)."""
    with _lock:
        return {
            "count": int(_state["count"]),
            "seconds": float(_state["seconds"]),
            "cache_hits": int(_state["cache_hits"]),
        }
