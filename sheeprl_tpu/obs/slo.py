"""SLO spec + error-budget accounting over telemetry windows.

Every observability tier so far is retrospective — ``diagnose`` explains a run
after the fact, ``compare`` judges it against a baseline. This module is the
*prospective* layer: operators DECLARE objectives over the stats the telemetry
windows already carry, and a burn-rate evaluator turns each window into budget
accounting the moment it is emitted — the same math in-loop (``ServingTelemetry``
/ ``RunTelemetry`` feed their own windows at window cadence) and offline
(``python sheeprl.py slo <run_dir>`` replays the recorded/merged stream), so CI
verdicts and live alerts cannot drift.

The spec
--------
An *objective* names a signal extracted from each window, a target with a
direction (``le``: value must stay at or below target — latency, staleness;
``ge``: value must stay at or above — availability, step rate), a compliance
``window`` measured in telemetry windows, and an error ``budget``: the fraction
of windows inside the compliance window allowed to breach the target. The
built-in catalog (:data:`OBJECTIVE_CATALOG`) covers the planes the windows
carry:

==================  =============================================  ====
serving_latency_p99 ``serve.latency_ms.p99`` ≤ target ms            le
availability        ``1 - serve.shed_rate`` ≥ target                ge
weight_staleness    actor ``dataflow.weight_lag`` (fallback:        le
                    ``serve.weights.available - version``) ≤ N
deadline_miss       ``serve.deadline_missed / steps`` ≤ fraction    le
step_rate           window ``sps`` ≥ floor                          ge
mfu                 window ``mfu`` ≥ floor                          ge
episode_return      ``learning.episodes.return_mean`` (fallback:    ge
                    ``serve.returns.mean``) ≥ floor
==================  =============================================  ====

Serving objectives carry usable defaults; training floors (step_rate / mfu /
episode_return) default to ``target: null`` = disabled, because a universal
floor for those is meaningless — declare them per experiment via the
``metric.telemetry.slo.objectives`` config group or a per-run ``slo.yaml``
dropped into the run dir (the highest-precedence override, read at load time).

Burn rates
----------
Budget consumed is the breach fraction over the compliance window divided by
the budget; 1.0 = the budget is exactly spent. Two burn rates are derived the
multi-window way (fast window = ``max(window // 6, 1)`` most recent telemetry
windows, slow = the full compliance window): an alert condition requires BOTH
to burn ≥ 1 — the fast window catches an active breach quickly, the slow
window keeps a brief blip from paging (it ages out before the slow rate
reaches 1). Windows that do not carry an objective's signal (a training stream
has no ``serve`` block) contribute nothing — every objective is a structural
no-op on streams without its plane.

The stateful pending → firing → resolved lifecycle on top of these snapshots
lives in ``obs/alerts.py``; this module stays pure accounting.
"""

from __future__ import annotations

import json
import math
import os
import sys
from collections import deque
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

__all__ = [
    "OBJECTIVE_CATALOG",
    "Objective",
    "SloEvaluator",
    "evaluate_events",
    "load_objectives",
    "main",
    "slo_events",
    "slo_run",
]

_SEVERITY_RANK = {"critical": 0, "warning": 1, "info": 2}

# fast burn window = compliance window // FAST_DIVISOR (min 1 telemetry window)
FAST_DIVISOR = 6


def _f(value: Any) -> Optional[float]:
    try:
        if value is None:
            return None
        out = float(value)
    except (TypeError, ValueError):
        return None
    return out if math.isfinite(out) else None


# ---------------------------------------------------------------------------------
# signal extractors: window event -> Optional[float]
# ---------------------------------------------------------------------------------
def _sig_latency_p99(window: Mapping[str, Any]) -> Optional[float]:
    serve = window.get("serve")
    if not isinstance(serve, dict):
        return None
    return _f((serve.get("latency_ms") or {}).get("p99"))


def _sig_availability(window: Mapping[str, Any]) -> Optional[float]:
    serve = window.get("serve")
    if not isinstance(serve, dict):
        return None
    shed = _f(serve.get("shed_rate"))
    return None if shed is None else 1.0 - shed


def _sig_weight_staleness(window: Mapping[str, Any]) -> Optional[float]:
    # the actor-side dataflow lag is the honest signal (peek_latest keeps it
    # fresh even when the reloader is absent); a bare serve stream without a
    # dataflow provider still exposes available - serving version
    dataflow = window.get("dataflow")
    if isinstance(dataflow, dict):
        lag = dataflow.get("weight_lag")
        if isinstance(lag, dict):  # learner view: per-actor lags
            return _f(lag.get("max"))
        value = _f(lag)
        if value is not None:
            return value
    serve = window.get("serve")
    if isinstance(serve, dict):
        weights = serve.get("weights") or {}
        version = _f(weights.get("version"))
        available = _f(weights.get("available"))
        if version is not None and available is not None:
            return max(available - version, 0.0)
    return None


def _sig_deadline_miss(window: Mapping[str, Any]) -> Optional[float]:
    serve = window.get("serve")
    if not isinstance(serve, dict):
        return None
    missed = _f(serve.get("deadline_missed"))
    steps = _f(window.get("steps"))
    if missed is None or steps is None:
        return None
    return missed / max(steps + missed, 1.0)


def _sig_step_rate(window: Mapping[str, Any]) -> Optional[float]:
    return _f(window.get("sps"))


def _sig_mfu(window: Mapping[str, Any]) -> Optional[float]:
    return _f(window.get("mfu"))


def _sig_episode_return(window: Mapping[str, Any]) -> Optional[float]:
    learning = window.get("learning")
    if isinstance(learning, dict):
        value = _f((learning.get("episodes") or {}).get("return_mean"))
        if value is not None:
            return value
    serve = window.get("serve")
    if isinstance(serve, dict):
        return _f((serve.get("returns") or {}).get("mean"))
    return None


# name -> (extractor, kind, unit, defaults). ``target: None`` = disabled until
# configured; serving objectives ship enabled because their planes carry
# universal meaning (a latency SLO needs a number, but 250 ms is a sane one for
# a continuous-batching policy server; override per deployment).
OBJECTIVE_CATALOG: Dict[str, Dict[str, Any]] = {
    "serving_latency_p99": {
        "signal": _sig_latency_p99,
        "kind": "le",
        "unit": "ms",
        "defaults": {"target": 250.0, "budget": 0.05, "window": 24, "for": 2, "severity": "warning"},
    },
    "availability": {
        "signal": _sig_availability,
        "kind": "ge",
        "unit": "fraction",
        "defaults": {"target": 0.99, "budget": 0.05, "window": 24, "for": 2, "severity": "critical"},
    },
    "weight_staleness": {
        "signal": _sig_weight_staleness,
        "kind": "le",
        "unit": "versions",
        "defaults": {"target": 2.0, "budget": 0.25, "window": 12, "for": 2, "severity": "warning"},
    },
    "deadline_miss": {
        "signal": _sig_deadline_miss,
        "kind": "le",
        "unit": "fraction",
        "defaults": {"target": 0.01, "budget": 0.1, "window": 24, "for": 2, "severity": "warning"},
    },
    "step_rate": {
        "signal": _sig_step_rate,
        "kind": "ge",
        "unit": "steps/s",
        "defaults": {"target": None, "budget": 0.1, "window": 24, "for": 3, "severity": "warning"},
    },
    "mfu": {
        "signal": _sig_mfu,
        "kind": "ge",
        "unit": "fraction",
        "defaults": {"target": None, "budget": 0.1, "window": 24, "for": 3, "severity": "warning"},
    },
    "episode_return": {
        "signal": _sig_episode_return,
        "kind": "ge",
        "unit": "return",
        "defaults": {"target": None, "budget": 0.25, "window": 24, "for": 3, "severity": "warning"},
    },
}


class Objective:
    """One declared objective: a signal, a target with a direction, an error
    budget over a compliance window, and the alert hysteresis/severity the
    engine in ``obs/alerts.py`` consumes."""

    def __init__(
        self,
        name: str,
        *,
        signal: Callable[[Mapping[str, Any]], Optional[float]],
        kind: str,
        target: float,
        budget: float,
        window: int,
        for_windows: int = 2,
        severity: str = "warning",
        unit: str = "",
    ) -> None:
        if kind not in ("le", "ge"):
            raise ValueError(f"objective {name!r}: kind must be 'le' or 'ge', got {kind!r}")
        self.name = str(name)
        self.signal = signal
        self.kind = kind
        self.target = float(target)
        self.budget = min(max(float(budget), 1e-6), 1.0)
        self.window = max(int(window), 1)
        self.for_windows = max(int(for_windows), 1)
        self.severity = severity if severity in _SEVERITY_RANK else "warning"
        self.unit = str(unit)

    def breached(self, value: float) -> bool:
        return value > self.target if self.kind == "le" else value < self.target


def load_objectives(
    slo_cfg: Optional[Mapping[str, Any]] = None,
    run_dir: Optional[str] = None,
) -> List[Objective]:
    """Resolve the active objective set: catalog defaults, overlaid by the
    ``metric.telemetry.slo.objectives`` config group, overlaid by a per-run
    ``slo.yaml`` dropped into ``run_dir`` (the operator's highest-precedence
    override — edit the file, rerun ``sheeprl.py slo``, no retrain). Objectives
    whose resolved ``target`` is None are disabled; unknown names are ignored
    (a forward-compat spec must not take the evaluator down)."""
    cfg = dict(slo_cfg or {})
    if not bool(cfg.get("enabled", True)):
        return []
    overrides: Dict[str, Any] = {}
    raw = cfg.get("objectives")
    if isinstance(raw, Mapping):
        for name, spec in raw.items():
            if isinstance(spec, Mapping):
                overrides[str(name)] = dict(spec)
    override_path = cfg.get("path")
    candidates = []
    if run_dir and os.path.isdir(str(run_dir)):
        candidates.append(os.path.join(str(run_dir), "slo.yaml"))
    if override_path:
        candidates.insert(0, str(override_path))
    for path in candidates:
        if not os.path.isfile(path):
            continue
        try:
            import yaml

            with open(path) as fh:
                loaded = yaml.safe_load(fh) or {}
        except Exception:
            continue
        spec = loaded.get("objectives") if isinstance(loaded, Mapping) else None
        if isinstance(spec, Mapping):
            for name, entry in spec.items():
                if isinstance(entry, Mapping):
                    overrides.setdefault(str(name), {}).update(dict(entry))
        break  # first readable override wins (explicit path beats run-dir file)
    objectives: List[Objective] = []
    for name, meta in OBJECTIVE_CATALOG.items():
        spec = {**meta["defaults"], **overrides.get(name, {})}
        target = _f(spec.get("target"))
        if target is None:
            continue
        objectives.append(
            Objective(
                name,
                signal=meta["signal"],
                kind=meta["kind"],
                unit=meta["unit"],
                target=target,
                budget=_f(spec.get("budget")) or meta["defaults"]["budget"],
                window=int(spec.get("window") or meta["defaults"]["window"]),
                for_windows=int(spec.get("for") or meta["defaults"]["for"]),
                severity=str(spec.get("severity") or meta["defaults"]["severity"]),
            )
        )
    return objectives


class SloEvaluator:
    """Feed window events in stream order; read budget accounting back out.

    Per objective a bounded deque of (breached, value) pairs — one entry per
    window that carried the signal — yields the slow (full compliance window)
    and fast (``window // 6``) breach fractions, each divided by the budget to
    a burn rate. Pure and deterministic: replaying a recorded stream offline
    reproduces exactly the accounting the in-loop evaluator computed live.
    """

    def __init__(self, objectives: Sequence[Objective]) -> None:
        self.objectives = list(objectives)
        self._samples: Dict[str, deque] = {
            o.name: deque(maxlen=o.window) for o in self.objectives
        }

    def __bool__(self) -> bool:
        return bool(self.objectives)

    def observe_window(self, window: Mapping[str, Any]) -> None:
        for objective in self.objectives:
            value = objective.signal(window)
            if value is None:
                continue
            self._samples[objective.name].append((objective.breached(value), value))

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-objective accounting over the samples seen so far; objectives
        whose signal never appeared report ``samples: 0`` and burn 0."""
        out: Dict[str, Dict[str, Any]] = {}
        for objective in self.objectives:
            samples = self._samples[objective.name]
            n = len(samples)
            bad = sum(1 for breached, _ in samples if breached)
            slow = (bad / n) / objective.budget if n else 0.0
            fast_n = max(objective.window // FAST_DIVISOR, 1)
            recent = list(samples)[-fast_n:]
            fast = (
                (sum(1 for breached, _ in recent if breached) / len(recent))
                / objective.budget
                if recent
                else 0.0
            )
            out[objective.name] = {
                "value": round(samples[-1][1], 4) if n else None,
                "target": objective.target,
                "kind": objective.kind,
                "unit": objective.unit,
                "window": objective.window,
                "samples": n,
                "breaches": bad,
                "budget": objective.budget,
                "burn_fast": round(fast, 4),
                "burn_slow": round(slow, 4),
                "budget_remaining": round(1.0 - slow, 4),
                "severity": objective.severity,
                "for": objective.for_windows,
            }
        return out

    def slo_block(self) -> Optional[Dict[str, Any]]:
        """The compact per-window block windows/summaries carry: every
        objective's budget remaining + burn rates, and the worst objective by
        remaining budget (the number ``watch`` renders). None when no objective
        has seen its signal yet — windows before the plane materializes stay
        clean."""
        snap = self.snapshot()
        seen = {name: s for name, s in snap.items() if s["samples"]}
        if not seen:
            return None
        worst = min(seen.items(), key=lambda kv: kv[1]["budget_remaining"])
        return {
            "worst": {"objective": worst[0], "budget_remaining": worst[1]["budget_remaining"]},
            "objectives": {
                name: {
                    "value": s["value"],
                    "target": s["target"],
                    "budget_remaining": s["budget_remaining"],
                    "burn_fast": s["burn_fast"],
                    "burn_slow": s["burn_slow"],
                    "samples": s["samples"],
                }
                for name, s in seen.items()
            },
        }


# ---------------------------------------------------------------------------------
# offline replay: `python sheeprl.py slo <run_dir|fleet_dir|live_dir>`
# ---------------------------------------------------------------------------------
def evaluate_events(
    events: Sequence[Mapping[str, Any]],
    objectives: Optional[Sequence[Objective]] = None,
) -> Dict[str, Any]:
    """Replay an ordered event stream through the evaluator + alert engine —
    the exact in-loop machinery — and report final budgets, the computed alert
    states, and the alert events the run recorded in-loop (so drift between
    the two would be visible, not silent)."""
    from sheeprl_tpu.obs.alerts import AlertEngine

    objs = list(objectives) if objectives is not None else load_objectives()
    evaluator = SloEvaluator(objs)
    engine = AlertEngine(objs)
    transitions: List[Dict[str, Any]] = []
    for event in events:
        if event.get("event") != "window":
            continue
        evaluator.observe_window(event)
        transitions.extend(engine.evaluate(evaluator.snapshot()))
    recorded = [dict(e) for e in events if e.get("event") == "alert"]
    recorded_firing = sorted(
        {
            str(e.get("name"))
            for e in _last_state_by_name(recorded).values()
            if e.get("status") == "firing"
        }
    )
    firing = engine.firing()
    # the gate judges the union of computed and recorded firing alerts: a
    # truncated stream (crash before resolution) must not slip past --fail-on
    # just because the replay saw one window fewer than the in-loop engine
    worst_severity = None
    gate_severities = [alert.get("severity", "warning") for alert in firing.values()]
    gate_severities.extend(
        str(e.get("severity") or "warning")
        for e in _last_state_by_name(recorded).values()
        if e.get("status") == "firing"
    )
    for sev in gate_severities:
        if worst_severity is None or _SEVERITY_RANK.get(sev, 3) < _SEVERITY_RANK.get(
            worst_severity, 3
        ):
            worst_severity = sev
    return {
        "objectives": evaluator.snapshot(),
        "slo": evaluator.slo_block(),
        "alerts": {
            "firing": sorted(firing),
            "states": {name: dict(state) for name, state in engine.states().items()},
            "transitions": transitions,
            "recorded_events": len(recorded),
            "recorded_firing": recorded_firing,
        },
        "worst_firing_severity": worst_severity,
        "windows": sum(1 for e in events if e.get("event") == "window"),
    }


def _last_state_by_name(alert_events: Sequence[Mapping[str, Any]]) -> Dict[str, Mapping[str, Any]]:
    last: Dict[str, Mapping[str, Any]] = {}
    for event in alert_events:
        name = str(event.get("name") or event.get("objective") or "?")
        last[name] = event
    return last


def slo_events(
    events: Sequence[Mapping[str, Any]],
    slo_cfg: Optional[Mapping[str, Any]] = None,
    run_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Evaluate one ordered stream against the objectives resolved for this
    run (config group defaults + per-run ``slo.yaml``)."""
    objectives = load_objectives(slo_cfg, run_dir=run_dir)
    result = evaluate_events(events, objectives)
    result["declared"] = [o.name for o in objectives]
    return result


def slo_run(run_dir: str, json_path: Optional[str] = None) -> Dict[str, Any]:
    """Merge every telemetry stream under ``run_dir``, evaluate, and write
    ``slo.json`` next to the stream (or to ``json_path``)."""
    from sheeprl_tpu.obs.streams import discover_streams, load_stream, merge_streams

    streams = discover_streams(run_dir)
    if not streams:
        raise FileNotFoundError(f"no telemetry*.jsonl stream found under {run_dir!r}")
    base = run_dir if os.path.isdir(run_dir) else os.path.dirname(run_dir)
    events = merge_streams([load_stream(p, base_dir=base) for p in streams])
    result = slo_events(events, run_dir=base)
    result["run_dir"] = str(run_dir)
    result["streams"] = [os.path.relpath(p, base) for p in streams]
    out = json_path or os.path.join(base, "slo.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=False)
        fh.write("\n")
    result["json_path"] = out
    return result


def slo_fleet(
    fleet_dir: str, members: Dict[str, str], json_path: Optional[str] = None
) -> Dict[str, Any]:
    """Evaluate every member run of a fleet dir as ONE unit (mirrors
    ``diagnose_fleet``): per-member ``slo.json`` + an aggregate at the fleet
    root whose firing set is the member-tagged union."""
    member_results: Dict[str, Any] = {}
    firing: List[str] = []
    worst_severity = None
    for name, member_dir in members.items():
        try:
            result = slo_run(member_dir)
        except FileNotFoundError:
            member_results[name] = {"error": "no telemetry stream"}
            continue
        member_results[name] = {
            k: result.get(k)
            for k in ("objectives", "slo", "alerts", "worst_firing_severity", "json_path")
        }
        for alert in (result.get("alerts") or {}).get("firing") or []:
            firing.append(f"{name}:{alert}")
        sev = result.get("worst_firing_severity")
        if sev and (
            worst_severity is None
            or _SEVERITY_RANK.get(sev, 3) < _SEVERITY_RANK.get(worst_severity, 3)
        ):
            worst_severity = sev
    if all("error" in r for r in member_results.values()):
        raise FileNotFoundError(
            f"no telemetry*.jsonl stream found under any member of fleet {fleet_dir!r}"
        )
    aggregate = {
        "fleet": str(fleet_dir),
        "members": member_results,
        "alerts": {"firing": sorted(firing)},
        "worst_firing_severity": worst_severity,
        "counts": {
            "members": len(members),
            "evaluated": sum(1 for r in member_results.values() if "error" not in r),
        },
    }
    out = json_path or os.path.join(str(fleet_dir), "slo.json")
    with open(out, "w") as fh:
        json.dump(aggregate, fh, indent=2, sort_keys=False)
        fh.write("\n")
    aggregate["json_path"] = out
    return aggregate


def format_report(result: Dict[str, Any]) -> str:
    """Human compliance report for one run's SLO evaluation."""
    lines = [f"SLO compliance — {result.get('run_dir', '<events>')}"]
    declared = result.get("declared")
    lines.append(
        f"  objectives : {len(declared or result.get('objectives') or {})} declared, "
        f"{result.get('windows', 0)} window(s) evaluated"
    )
    objectives = result.get("objectives") or {}
    seen = {n: s for n, s in objectives.items() if s.get("samples")}
    if not seen:
        lines.append("  verdict    : no objective saw its signal — nothing to judge")
        return "\n".join(lines)
    for name, s in sorted(seen.items(), key=lambda kv: kv[1]["budget_remaining"]):
        cmp = "≤" if s.get("kind") == "le" else "≥"
        unit = f" {s['unit']}" if s.get("unit") else ""
        lines.append(
            f"  {name:<20s} value {s['value']}{unit} {cmp} {s['target']}{unit}"
            f" | budget remaining {s['budget_remaining']:+.2f}"
            f" (burn fast {s['burn_fast']:.2f} / slow {s['burn_slow']:.2f},"
            f" {s['breaches']}/{s['samples']} breached)"
        )
    alerts = result.get("alerts") or {}
    firing = alerts.get("firing") or []
    if firing:
        lines.append(f"  alerts     : FIRING {', '.join(firing)}")
    else:
        lines.append("  alerts     : none firing")
    recorded = alerts.get("recorded_firing") or []
    if sorted(recorded) != sorted(firing):
        lines.append(
            f"  in-loop    : recorded stream ended with firing={recorded or 'none'}"
            " (offline replay disagrees — check for a truncated stream)"
        )
    elif alerts.get("recorded_events"):
        lines.append(
            f"  in-loop    : {alerts['recorded_events']} alert event(s) recorded — "
            "in agreement with this replay"
        )
    return "\n".join(lines)


def format_fleet_report(result: Dict[str, Any]) -> str:
    lines = [f"Fleet SLO compliance — {result.get('fleet')}"]
    counts = result.get("counts") or {}
    lines.append(
        f"  members : {counts.get('evaluated', 0)}/{counts.get('members', 0)} evaluated"
    )
    for name, member in (result.get("members") or {}).items():
        if "error" in member:
            lines.append(f"  [{name}] {member['error']}")
            continue
        slo = member.get("slo") or {}
        worst = slo.get("worst") or {}
        firing = (member.get("alerts") or {}).get("firing") or []
        bits = []
        if worst:
            bits.append(
                f"worst {worst.get('objective')} budget {worst.get('budget_remaining'):+.2f}"
            )
        bits.append(f"firing: {', '.join(firing) if firing else 'none'}")
        lines.append(f"  [{name}] " + " | ".join(bits))
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py slo <run_dir>`` entry: print the compliance report,
    write ``slo.json``, exit 0 (or 1 with ``--fail-on`` when a computed OR
    recorded alert fires at that severity; 2 when no stream exists) — the same
    exit taxonomy ``diagnose`` uses, so CI recipes compose."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="sheeprl.py slo",
        description="SLO compliance over a run's telemetry stream(s): error budgets, "
        "burn rates, and alert verdicts (in-loop events cross-checked by replay).",
    )
    parser.add_argument(
        "run_dir", help="run directory (searched recursively) or a telemetry*.jsonl file"
    )
    parser.add_argument("--json", dest="json_path", default=None, help="where to write slo.json")
    parser.add_argument("--quiet", action="store_true", help="suppress the human report")
    parser.add_argument(
        "--fail-on",
        choices=("warning", "critical"),
        default=None,
        help="exit 1 when any alert at least this severe is firing",
    )
    args = parser.parse_args(list(argv) if argv is not None else sys.argv[1:])
    from sheeprl_tpu.obs.streams import fleet_members

    members = fleet_members(args.run_dir)
    try:
        if members:
            result = slo_fleet(args.run_dir, members, json_path=args.json_path)
        else:
            result = slo_run(args.run_dir, json_path=args.json_path)
    except FileNotFoundError as exc:
        print(f"slo: {exc}", file=sys.stderr)
        return 2
    if not args.quiet:
        print(format_fleet_report(result) if members else format_report(result))
        print(f"\nwrote {result['json_path']}")
    if args.fail_on:
        gate = _SEVERITY_RANK[args.fail_on]
        sev = result.get("worst_firing_severity")
        if sev is not None and _SEVERITY_RANK.get(sev, 3) <= gate:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
