"""``python sheeprl.py watch <run_dir>`` — live terminal monitor for a running run.

The telemetry stream (``telemetry.jsonl``, howto/observability.md) already
carries everything an operator tails raw JSONL for; this module renders it as a
compact refreshing status instead. Built on ``obs/streams.py`` follow mode
(``tail -F`` semantics: torn final lines retried, late per-role streams and
supervisor restart attempts picked up automatically), so ``watch`` can be
started before, alongside, or long after the launch — it follows whatever run
dir materializes.

Per refresh the monitor shows: policy step + throughput (window sps), MFU,
the phase-attribution bar (env / replay wait / train / checkpoint / logging /
eval / other shares of the last window), device memory (HBM when the backend
reports it, host RSS otherwise), prefetch pipeline occupancy/staleness, the
experience plane's dataflow line on ``buffer.backend=service`` runs (worst
actor weight lag, learner row age p50/p99, ingest latency, queue depth — from
the windows' ``dataflow`` blocks, whatever stream they ride), the latest
health verdict and in-loop diagnosis findings, the training-health line
(episode-return p50, policy entropy, worst gradient norm, KL — from the
windows' ``learning`` blocks), and the attempt/restart state
of supervised runs. Fleet watch adds per-member staleness to the member lines. Multi-process (gang) runs additionally get a per-rank
liveness board: every stream's rank identity marks its writer alive, a
``health`` ``status=rank_dead`` event (heartbeat failure detection,
``resilience/distributed.py``) marks the named peer DEAD, and the gang
supervisor's exit codes annotate the rest — so a gang teardown reads as "rank 1
DEAD (heartbeat timeout)", not an unexplained crash.

Exit protocol: when the run's ``summary`` event lands (flushed even on crash or
preemption — see ``obs/telemetry.py``), ``watch`` exits with the run's status —
``0`` for a clean exit, ``1`` otherwise. Because a *supervised* run writes an
end-of-attempt summary before every restart, a summary only ends the watch
after a short grace window with no ``restart``/``resume`` following it (a
supervisor ``giveup`` ends it immediately). ``--timeout`` bounds the whole
watch and exits ``2`` when it expires (also when no stream ever appeared).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, TextIO

from sheeprl_tpu.obs.streams import (
    RunFollower,
    fleet_members as _fleet_members,
    is_primary_event as _is_primary,
    member_of as _member_of,
)

__all__ = ["FleetWatchState", "WatchState", "main", "watch_run"]

# phase → (bar glyph, short label); order matches the loop's own wall-time layout
_PHASE_GLYPHS = (
    ("env", "E", "env"),
    ("rollout", "r", "rollout"),
    ("replay_wait", "R", "replay"),
    ("train", "T", "train"),
    ("serve_step", "S", "serve"),
    ("serve_wait", "w", "wait"),
    ("checkpoint", "C", "ckpt"),
    ("logging", "L", "log"),
    ("eval", "V", "eval"),
    ("analysis", "A", "analysis"),
    ("other", "·", "other"),
)
_BAR_WIDTH = 32


def _fmt_bytes(n: Optional[float]) -> str:
    if not n:
        return "?"
    return f"{float(n) / 2**30:.2f}G"


class WatchState:
    """Accumulates the followed event stream into the rendered status. Pure
    state machine (no IO, no clock) so unit tests can drive it event-by-event."""

    def __init__(self) -> None:
        self.start: Optional[Dict[str, Any]] = None
        self.window: Optional[Dict[str, Any]] = None
        self.attempt = 0
        self.restarts = 0
        self.last_restart: Optional[Dict[str, Any]] = None
        self.last_restart_dead: List[int] = []
        self.env_restarts = 0
        self.health = "unknown"
        self.findings: List[Dict[str, Any]] = []
        self.preempted = False
        # serving robustness plane: drain lifecycle + last reload event
        self.draining = False
        self.last_reload: Optional[Dict[str, Any]] = None
        self.summary: Optional[Dict[str, Any]] = None  # primary-stream summary
        # latest window-capture attribution (obs/xprof.py profile_analysis)
        self.profile: Optional[Dict[str, Any]] = None
        self.gave_up = False
        self.events_seen = 0
        # experience-plane dataflow state by role (buffer.backend=service runs):
        # the actor view tracks each actor STREAM's latest block (the render
        # shows the currently-worst lag — latest per stream, not worst-ever,
        # so a recovered actor stops being reported stale), the learner view
        # the learner stream's latest — neither is primary-gated, the whole
        # point is cross-process visibility
        self.dataflow: Dict[str, Dict[str, Any]] = {}
        self._actor_dataflow: Dict[Any, Dict[str, Any]] = {}  # stream -> latest block
        # SLO plane: each stream's latest window `slo` block (a live gang has
        # one per role stream — render the worst), plus the firing-alert board
        # driven by the stateful `alert` events (firing adds, resolved clears)
        self._slo_by_stream: Dict[Any, Dict[str, Any]] = {}
        self.alerts: Dict[str, Dict[str, Any]] = {}
        # per-rank liveness of a multi-process (gang) run: every event's rank
        # identity marks its writer alive; a health status=rank_dead names the
        # dead peer; the gang supervisor's attempt_exit carries exit codes. A
        # restart resets the board — the whole gang comes back as one unit.
        self.ranks: Dict[int, str] = {}

    # -- event intake ------------------------------------------------------------

    def consume(self, events: Sequence[Dict[str, Any]]) -> None:
        for event in events:
            self.events_seen += 1
            self.attempt = max(self.attempt, int(event.get("attempt") or 0))
            kind = event.get("event")
            writer = event.get("rank")
            if writer is not None and kind not in ("restart", "giveup", "gang", "supervisor"):
                try:
                    self.ranks.setdefault(int(writer), "alive")
                except (TypeError, ValueError):
                    pass
            if kind == "window" and isinstance(event.get("dataflow"), dict):
                self._consume_dataflow(
                    event["dataflow"], event.get("stream") or f"rank{event.get('rank', 0)}"
                )
            if kind == "window" and isinstance(event.get("slo"), dict):
                self._slo_by_stream[
                    event.get("stream") or f"rank{event.get('rank', 0)}"
                ] = event["slo"]
            if kind == "start" and _is_primary(event):
                self.start = event
            elif kind == "window" and _is_primary(event):
                self.window = event
            elif kind == "health":
                self._consume_health(event)
            elif kind == "preempt":
                self.preempted = True
            elif kind == "drain":
                # a drain never un-begins: the server is winding down
                self.draining = True
            elif kind == "reload":
                self.last_reload = event
            elif kind in ("restart", "resume"):
                self.restarts += int(kind == "restart")
                # only the restart carries the reason — the resume event that
                # follows it must not erase the "(rank N died)" attribution
                if kind == "restart":
                    self.last_restart = event
                # the attempt is being restarted: the pending summary was
                # end-of-attempt state, not the end of the run — and the gang
                # comes back as one unit, so the liveness board resets too;
                # the heartbeat-declared dead set is captured first so the
                # restart line can keep attributing THIS restart after the board
                # is alive again (peers exiting nonzero BECAUSE a rank died are
                # collateral, not the cause — only DEAD ranks are named)
                if kind == "restart":
                    self.last_restart_dead = sorted(
                        r for r, s in self.ranks.items() if str(s).startswith("DEAD")
                    )
                self.summary = None
                self.ranks = {r: "alive" for r in self.ranks}
            elif kind == "gang" and event.get("status") == "attempt_exit":
                for r, rc in (event.get("exit_codes") or {}).items():
                    try:
                        rank, code = int(r), int(rc)
                    except (TypeError, ValueError):
                        continue
                    if not str(self.ranks.get(rank, "")).startswith("DEAD"):
                        self.ranks[rank] = "exited 0" if code == 0 else f"EXITED {code}"
            elif kind == "alert":
                self._consume_alert(event)
            elif kind == "profile_analysis":
                self.profile = event
            elif kind == "giveup":
                self.gave_up = True
            elif kind == "summary" and _is_primary(event):
                self.summary = event

    def _consume_dataflow(self, dataflow: Dict[str, Any], stream: Any) -> None:
        role = str(dataflow.get("role") or "")
        if role == "actor":
            # several actor streams feed one board: keep each stream's LATEST
            # block and render the one with the currently-worst lag
            self._actor_dataflow[stream] = dataflow
            self.dataflow["actor"] = max(
                self._actor_dataflow.values(),
                key=lambda d: float(d.get("weight_lag") or 0.0)
                if isinstance(d.get("weight_lag"), (int, float))
                else 0.0,
            )
        elif role == "learner":
            self.dataflow["learner"] = dataflow

    def _consume_alert(self, event: Dict[str, Any]) -> None:
        name = str(event.get("name") or event.get("objective") or "?")
        status = event.get("status")
        if status == "firing":
            self.alerts[name] = event
        elif status == "resolved":
            self.alerts.pop(name, None)

    @property
    def slo_worst(self) -> Optional[Dict[str, Any]]:
        """The worst objective (by budget remaining) across every stream's
        latest window `slo` block."""
        worsts = [
            block.get("worst")
            for block in self._slo_by_stream.values()
            if isinstance(block.get("worst"), dict)
            and isinstance(block["worst"].get("budget_remaining"), (int, float))
        ]
        if not worsts:
            return None
        return min(worsts, key=lambda w: float(w["budget_remaining"]))

    @property
    def weight_lag(self) -> Optional[float]:
        """Worst known actor weight lag (versions behind the publisher) — the
        per-member staleness number the fleet watch renders."""
        actor = (self.dataflow.get("actor") or {}).get("weight_lag")
        learner = (self.dataflow.get("learner") or {}).get("weight_lag")
        candidates = []
        if isinstance(actor, (int, float)):
            candidates.append(float(actor))
        if isinstance(learner, dict) and isinstance(learner.get("max"), (int, float)):
            candidates.append(float(learner["max"]))
        return max(candidates) if candidates else None

    def _consume_health(self, event: Dict[str, Any]) -> None:
        status = event.get("status")
        if status == "diagnosis":
            self.findings = list(event.get("findings") or [])
        elif status == "env_restart":
            self.env_restarts = max(self.env_restarts, int(event.get("total") or 0))
        elif status in ("ok", "nonfinite", "no-train"):
            self.health = str(status)
        elif status == "stalled":
            self.health = "stalled"
        elif status == "rank_dead":
            # the heartbeat monitor named a dead peer: a gang teardown is about
            # to follow — attribute it instead of rendering an unexplained crash
            try:
                self.ranks[int(event.get("rank"))] = f"DEAD ({event.get('reason') or 'heartbeat timeout'})"
            except (TypeError, ValueError):
                pass

    # -- exit protocol -----------------------------------------------------------

    @property
    def finished(self) -> bool:
        """A definitive end: supervisor giveup, or a primary summary that no
        restart has superseded (the caller applies the grace window)."""
        return self.gave_up or self.summary is not None

    @property
    def exit_code(self) -> int:
        if self.gave_up:
            return 1
        if self.summary is not None:
            return 0 if self.summary.get("clean_exit", True) else 1
        return 2  # still running / never finished — the timeout path

    @property
    def status_line(self) -> str:
        if self.gave_up:
            return "FAILED — supervisor exhausted its restart budget"
        if self.summary is not None:
            clean = bool(self.summary.get("clean_exit", True))
            sps = self.summary.get("sps")
            return (
                ("clean exit" if clean else "UNCLEAN exit (crash/preempt)")
                + (f" — overall {sps:.1f} sps" if isinstance(sps, (int, float)) else "")
                + f", {self.summary.get('windows', 0)} window(s)"
                + (f", {self.restarts} restart(s)" if self.restarts else "")
            )
        return "running"

    # -- rendering ---------------------------------------------------------------

    def _phase_bar(self, phases: Dict[str, Any], wall: float) -> str:
        cells: List[str] = []
        labels: List[str] = []
        for key, glyph, label in _PHASE_GLYPHS:
            try:
                frac = max(float(phases.get(key) or 0.0), 0.0) / wall if wall > 0 else 0.0
            except (TypeError, ValueError):
                frac = 0.0
            cells.extend(glyph * int(round(frac * _BAR_WIDTH)))
            if frac >= 0.005 and key != "analysis" or frac >= 0.05:
                labels.append(f"{label} {frac:.0%}")
        bar = "".join(cells)[:_BAR_WIDTH].ljust(_BAR_WIDTH, " ")
        return f"[{bar}] {'  '.join(labels)}"

    def render(self, run_dir: str, elapsed: float, streams: Sequence[str]) -> str:
        lines = [
            f"watch {run_dir} · {elapsed:.0f}s · {len(streams)} stream(s) · "
            f"attempt {self.attempt} · {self.status_line}"
        ]
        if self.window is None:
            lines.append(
                "  waiting for the first telemetry window"
                + ("" if streams else " (no telemetry*.jsonl yet — is telemetry enabled?)")
            )
        else:
            w = self.window
            mfu = w.get("mfu")
            hbm = w.get("hbm") or {}
            mem = (
                f"hbm {_fmt_bytes(hbm.get('bytes_in_use'))}"
                + (f"/{_fmt_bytes(hbm.get('bytes_limit'))}" if hbm.get("bytes_limit") else "")
                if hbm.get("bytes_in_use")
                else f"rss {_fmt_bytes(w.get('rss_bytes'))}"
            )
            prefetch = w.get("prefetch") or {}
            pipe = (
                f"   pipeline occ {prefetch.get('occupancy', 0.0):.1f}"
                f" stale {prefetch.get('staleness', 0.0):.1f}"
                if prefetch.get("is_async")
                else ""
            )
            ring = prefetch.get("ring") or {}
            if ring.get("capacity"):
                # device-ring storage (buffer.backend=device): fill/capacity
                # plus slots already lost to wraparound
                pipe += (
                    f"   ring {float(ring.get('occupancy') or 0.0):.0%}"
                    f" of {int(ring['capacity'])} rows"
                )
                if ring.get("overwritten"):
                    pipe += f" ({int(ring['overwritten'])} overwritten)"
            compile_ = w.get("compile") or {}
            lines.append(
                f"  step {w.get('step')}   {w.get('sps', 0.0):.1f} sps   "
                + (f"mfu {float(mfu):.1%}   " if isinstance(mfu, (int, float)) else "")
                + f"{mem}   compiles {compile_.get('count', 0)}"
                + pipe
            )
            if self.profile is not None:
                # the window capture's op-category attribution, once a
                # profile_analysis event has landed (metric.profiler.mode=window)
                cats = self.profile.get("categories") or {}
                shares = "  ".join(
                    f"{c} {float(f):.0%}"
                    for c, f in cats.items()
                    if isinstance(f, (int, float)) and f >= 0.005
                )
                if shares:
                    lines.append(f"  xla   {shares}")
            serve = w.get("serve")
            if isinstance(serve, dict):
                # a SERVING run's window (sheeprl_tpu/serve): sessions + latency
                # + the robustness plane's state (weight version, shed/deadline
                # pressure, degraded/draining flags)
                lat = serve.get("latency_ms") or {}
                sessions = serve.get("sessions") or {}
                bits = [
                    f"sessions {sessions.get('active', 0)}",
                    f"occupancy {float(serve.get('occupancy') or 0.0):.0%}",
                ]
                weights = serve.get("weights") or {}
                if weights.get("version") is not None:
                    version_bit = f"weights v{int(weights['version'])}"
                    if float(weights.get("available") or 0) > float(weights["version"]):
                        version_bit += f" (v{int(weights['available'])} avail)"
                    if weights.get("failures"):
                        version_bit += f" · {int(weights['failures'])} reload failure(s)"
                    bits.append(version_bit)
                if lat.get("p50") is not None:
                    bits.append(f"latency p50 {lat['p50']:.1f}ms p99 {lat.get('p99', 0):.1f}ms")
                if serve.get("queue_depth"):
                    bits.append(f"queue {float(serve['queue_depth']):.1f}")
                if sessions.get("shed"):
                    bits.append(f"SHED {int(sessions['shed'])}")
                if serve.get("deadline_missed"):
                    bits.append(f"deadline missed {int(serve['deadline_missed'])}")
                traj = serve.get("trajectories") or {}
                if traj.get("ingested") or traj.get("dropped"):
                    # the live flywheel's serve-side ingest: trajectories this
                    # window shipped to the learner, and the ones the bounded
                    # queue shed (the explicit overflow policy — data lost,
                    # latency protected)
                    traj_bit = f"traj {int(traj.get('ingested') or 0)}"
                    if traj.get("rows"):
                        traj_bit += f" ({int(traj['rows'])} rows)"
                    if traj.get("dropped"):
                        traj_bit += f" · SHED {int(traj['dropped'])}"
                    bits.append(traj_bit)
                if serve.get("degraded"):
                    bits.append("DEGRADED")
                if self.draining:
                    bits.append("DRAINING")
                lines.append("  serve: " + " · ".join(bits))
                versions = serve.get("versions")
                if isinstance(versions, dict) and versions:
                    # the per-weight-version split: this window's traffic keyed
                    # by the policy version that served it — the promotion
                    # question ("is the new version worse?") at a glance
                    vbits = []
                    for key in sorted(versions, key=lambda k: int(k)):
                        vb = versions[key] or {}
                        vlat = vb.get("latency_ms") or {}
                        bit = f"v{int(key)} {int(vb.get('steps') or 0)} steps"
                        if vlat.get("p50") is not None:
                            bit += f" p50 {float(vlat['p50']):.1f}ms"
                        returns = vb.get("returns") or {}
                        if isinstance(returns.get("mean"), (int, float)):
                            bit += f" ret {float(returns['mean']):g}"
                        vbits.append(bit)
                    lines.append("  versions: " + " · ".join(vbits))
            learning = w.get("learning")
            if isinstance(learning, dict):
                # the training-health line: is the run actually LEARNING?
                stats = learning.get("stats") or {}
                episodes = learning.get("episodes") or {}
                bits = []
                if isinstance(episodes.get("return_p50"), (int, float)):
                    bits.append(
                        f"ret p50 {episodes['return_p50']:g}"
                        + (f" ({episodes.get('count')} eps)" if episodes.get("count") else "")
                    )
                if isinstance(stats.get("entropy"), (int, float)):
                    bits.append(f"H {stats['entropy']:.3g}")
                grad_norms = [
                    v for k, v in stats.items()
                    if k.startswith("grad_norm/") and isinstance(v, (int, float))
                ]
                if grad_norms:
                    bits.append(f"|g| {max(grad_norms):.3g}")
                if isinstance(stats.get("kl"), (int, float)):
                    bits.append(f"kl {stats['kl']:.3g}")
                if learning.get("nonfinite"):
                    bits.append(f"NONFINITE {','.join(learning['nonfinite'][:3])}")
                if bits:
                    lines.append("  learning: " + " · ".join(bits))
            phases = w.get("phases")
            if isinstance(phases, dict):
                wall = float(w.get("wall_seconds") or 0.0)
                lines.append(f"  {self._phase_bar(phases, wall)}")
        if self.dataflow:
            # the experience plane's staleness line (service-backend runs):
            # worst actor weight lag, learner-side row ages and ingest state
            bits = []
            lag = self.weight_lag
            if lag is not None:
                bits.append(f"weight lag {lag:.0f}")
            learner = self.dataflow.get("learner") or {}
            age = (learner.get("row_age") or {}).get("seconds") or {}
            if age.get("p50") is not None:
                bits.append(f"row age p50 {float(age['p50']):.1f}s p99 {float(age.get('p99') or 0):.1f}s")
            lat = learner.get("ingest_latency_ms") or {}
            if lat.get("p99") is not None:
                bits.append(f"ingest p99 {float(lat['p99']):.0f}ms")
            if learner.get("queue_depth") is not None:
                bits.append(f"queue {float(learner['queue_depth']):.1f}")
            actor = self.dataflow.get("actor") or {}
            if actor.get("rows") is not None and not learner:
                bits.append(f"rows {int(actor['rows'])}")
            if bits:
                lines.append("  dataflow: " + " · ".join(bits))
        worst = self.slo_worst
        if worst is not None or self.alerts:
            # the SLO line: the objective closest to (or past) budget
            # exhaustion, plus the firing-alert board
            bits = []
            if worst is not None:
                bits.append(
                    f"worst {worst.get('objective')} "
                    f"budget {float(worst.get('budget_remaining') or 0.0):+.2f}"
                )
            if self.alerts:
                names = ", ".join(
                    f"{n}[{str((a or {}).get('severity') or '?')}]"
                    for n, a in sorted(self.alerts.items())
                )
                bits.append(f"FIRING {names}")
            else:
                bits.append("alerts none")
            lines.append("  slo: " + " · ".join(bits))
        health_bits = [f"health {self.health}"]
        if self.env_restarts:
            health_bits.append(f"{self.env_restarts} env restart(s)")
        if self.restarts:
            reason = (self.last_restart or {}).get("reason")
            dead = self.last_restart_dead
            health_bits.append(
                f"{self.restarts} attempt restart(s)"
                + (
                    f" (rank {', '.join(map(str, dead))} died)"
                    if dead and reason == "crash"
                    else (f" ({reason})" if reason else "")
                )
            )
        if self.preempted:
            health_bits.append("preempt requested")
        if self.draining:
            health_bits.append("draining")
        if self.last_reload is not None and self.last_reload.get("status") == "applied":
            health_bits.append(f"reloaded v{self.last_reload.get('version')}")
        lines.append("  " + " · ".join(health_bits))
        # multi-process runs: per-rank liveness, so a gang teardown reads as
        # "rank 1 DEAD (heartbeat timeout)" instead of an unexplained crash
        if len(self.ranks) > 1 or any(str(s) != "alive" for s in self.ranks.values()):
            lines.append(
                "  ranks: "
                + " · ".join(f"{r} {self.ranks[r]}" for r in sorted(self.ranks))
            )
        for f in self.findings[:4]:
            lines.append(
                f"  [{str(f.get('severity', '?')).upper()}] {f.get('detector')}: {f.get('summary')}"
            )
        return "\n".join(lines)


class FleetWatchState:
    """Watch a FLEET dir (``sheeprl.py fleet``) as one unit: one
    :class:`WatchState` per member (events routed by their ``members/<name>/``
    stream prefix), plus the runner's own ``telemetry.fleet.jsonl`` events
    (member spawn/exit, restarts, the terminal ``fleet`` ``status=done`` with
    the gate verdict). The watch ends when the runner publishes its done event
    — or, if the runner died, when every member's summary landed — and exits
    with the GATE's verdict when available."""

    def __init__(self, members: Sequence[str]) -> None:
        self.members: Dict[str, WatchState] = {name: WatchState() for name in members}
        self.outcomes: Dict[str, str] = {}
        self.fleet_done: Optional[Dict[str, Any]] = None
        self.events_seen = 0
        self.gave_up = False  # a member giveup is a member verdict, not a fleet end

    def consume(self, events: Sequence[Dict[str, Any]]) -> None:
        for event in events:
            self.events_seen += 1
            member = _member_of(event.get("stream") or "")
            if member is not None:
                state = self.members.setdefault(member, WatchState())
                state.consume([event])
                continue
            kind = event.get("event")
            if kind == "fleet" and event.get("status") == "done":
                self.fleet_done = event
                self.outcomes.update(event.get("outcomes") or {})
            elif kind == "member" and event.get("status") == "exit":
                name = str(event.get("member"))
                self.outcomes[name] = str(event.get("outcome"))
            elif kind in ("restart", "giveup") and event.get("member") is not None:
                name = str(event.get("member"))
                state = self.members.get(name)
                if state is not None:
                    state.consume([event])

    @property
    def finished(self) -> bool:
        if self.fleet_done is not None:
            return True
        return bool(self.members) and all(s.finished for s in self.members.values())

    @property
    def exit_code(self) -> int:
        if self.fleet_done is not None:
            gate = self.fleet_done.get("gate") or {}
            return 1 if gate.get("failed") else 0
        codes = [s.exit_code for s in self.members.values()]
        return max(codes, default=2)

    @property
    def status_line(self) -> str:
        done = sum(1 for s in self.members.values() if s.finished)
        if self.fleet_done is not None:
            gate = self.fleet_done.get("gate") or {}
            return f"fleet done — gate {'FAILED' if gate.get('failed') else 'green'}"
        return f"fleet running — {done}/{len(self.members)} member(s) finished"

    def render(self, run_dir: str, elapsed: float, streams: Sequence[str]) -> str:
        lines = [
            f"watch {run_dir} · {elapsed:.0f}s · {len(streams)} stream(s) · "
            f"{len(self.members)} member(s) · {self.status_line}"
        ]
        for name in sorted(self.members):
            state = self.members[name]
            window = state.window or {}
            outcome = self.outcomes.get(name)
            bits = [
                f"step {window.get('step', '—')}",
                f"{window.get('sps', 0.0):.1f} sps" if window else "no window yet",
                state.status_line if outcome is None else f"exit: {outcome}",
            ]
            if state.restarts:
                bits.append(f"{state.restarts} restart(s)")
            # per-member staleness: worst actor weight lag + learner row age of
            # service-backend members (plain members contribute nothing)
            lag = state.weight_lag
            if lag is not None and lag > 0:
                bits.append(f"lag {lag:.0f}")
            age = ((state.dataflow.get("learner") or {}).get("row_age") or {}).get("seconds") or {}
            if age.get("p50") is not None:
                bits.append(f"row age {float(age['p50']):.1f}s")
            if state.alerts:
                bits.append(f"{len(state.alerts)} alert(s) FIRING")
            findings = [f for f in state.findings if f.get("severity") in ("warning", "critical")]
            if findings:
                bits.append(f"{len(findings)} finding(s)")
            lines.append(f"  [{name}] " + " · ".join(bits))
        return "\n".join(lines)


def watch_run(
    run_dir: str,
    *,
    interval: float = 0.5,
    timeout: Optional[float] = None,
    grace: Optional[float] = None,
    plain: Optional[bool] = None,
    out: Optional[TextIO] = None,
) -> int:
    """Follow ``run_dir`` until its summary lands (exit 0/1 per the run's
    status) or ``timeout`` seconds pass (exit 2). ``grace`` is how long a
    summary must stand un-superseded by a restart before it ends the watch
    (default ``max(2*interval, 2s)``); ``plain`` forces append-only output
    (auto-detected from tty otherwise)."""
    out = out if out is not None else sys.stdout
    if plain is None:
        plain = not (hasattr(out, "isatty") and out.isatty())
    grace = grace if grace is not None else max(2.0 * interval, 2.0)
    follower = RunFollower(run_dir)
    state: Any = WatchState()
    fleet = _fleet_members(run_dir)
    if fleet:
        state = FleetWatchState(list(fleet))
    began = time.monotonic()
    finished_at: Optional[float] = None
    last_frame = ""
    while True:
        # a fleet marker can land moments after the watch starts (watch is
        # typically launched alongside `sheeprl.py fleet`): until the first
        # event arrives, keep probing and upgrade to the fleet view
        if not isinstance(state, FleetWatchState) and state.events_seen == 0:
            fleet = _fleet_members(run_dir)
            if fleet:
                state = FleetWatchState(list(fleet))
        batch = follower.poll()
        state.consume(batch)
        now = time.monotonic()
        if state.gave_up:
            break
        if state.finished:
            if finished_at is None:
                finished_at = now
            elif now - finished_at >= grace:
                # the grace window expired with the summary standing — but drain
                # once more before committing to the verdict: a supervisor
                # restart flushed between the last poll and now supersedes the
                # end-of-attempt summary and the watch keeps following
                state.consume(follower.poll())
                if state.finished:
                    break
                finished_at = None
        else:
            finished_at = None
        frame = state.render(run_dir, now - began, follower.streams)
        if plain:
            if frame != last_frame:
                out.write(frame + "\n\n")
                out.flush()
                last_frame = frame
        else:
            out.write("\x1b[H\x1b[2J" + frame + "\n")
            out.flush()
        if timeout is not None and now - began >= timeout:
            out.write(f"watch: timed out after {timeout:.0f}s ({state.status_line})\n")
            out.flush()
            return 2 if not state.finished else state.exit_code
        time.sleep(interval)
    # the verdict is committed (the pre-break drain already ran); render it
    out.write(
        state.render(run_dir, time.monotonic() - began, follower.streams)
        + f"\nwatch: run finished — {state.status_line}\n"
    )
    out.flush()
    return state.exit_code


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py watch <run_dir>`` entry point."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="sheeprl.py watch",
        description="Live terminal monitor over a run's telemetry.jsonl stream(s): "
        "step/sps/MFU, phase-attribution bar, memory, pipeline occupancy, health "
        "and diagnosis findings, attempt/restart state. Exits with the run's "
        "status when its summary event lands.",
    )
    parser.add_argument("run_dir", help="run directory (may not exist yet) or a telemetry*.jsonl file")
    parser.add_argument("--interval", type=float, default=0.5, help="poll/refresh period in seconds")
    parser.add_argument(
        "--timeout", type=float, default=None, help="give up (exit 2) after this many seconds"
    )
    parser.add_argument(
        "--grace",
        type=float,
        default=None,
        help="seconds a summary must stand un-superseded by a supervisor restart "
        "before the watch ends (default: max(2*interval, 2))",
    )
    parser.add_argument(
        "--plain",
        action="store_true",
        help="append-only output (no screen clearing); auto when stdout is not a tty",
    )
    args = parser.parse_args(list(argv) if argv is not None else sys.argv[1:])
    return watch_run(
        args.run_dir,
        interval=args.interval,
        timeout=args.timeout,
        grace=args.grace,
        plain=True if args.plain else None,
    )


if __name__ == "__main__":
    sys.exit(main())
