"""Telemetry stream discovery + merge: one ordered event stream per run.

A run can scatter its telemetry over several JSONL files: decoupled MPMD
topologies (sac_decoupled / ppo_decoupled / dv3_decoupled) write one file per
role process (the player's ``telemetry.jsonl`` plus ``telemetry.<role>.jsonl``
for the learner slice), and the supervisor pins all restart *attempts* of a run
onto one shared run-base file while each attempt may also leave per-version
artifacts. The diagnosis engine (``obs/diagnose.py``) wants ONE ordered stream.

Merging key: every modern event carries ``(rank, attempt, seq)`` (see
``obs/jsonl.py``); within one file that triple is append-ordered, so a k-way
merge that pops the earliest head by wall-clock ``time`` — with
``(attempt, seq)`` as the tiebreak — yields a globally time-ordered stream that
never reorders any single writer's events. All writers of one run share the
host clock (the topologies here are single-host; multi-host pods write per-host
run dirs), so wall-clock alignment is exact up to NTP skew; per-stream order is
preserved regardless, which is the invariant the detectors rely on.

Old streams written before the identity fields existed still merge: missing
``rank``/``attempt`` default to 0 and ``seq`` to the line index.

Besides the offline merge, this module provides the *follow mode* ``watch``
builds on (``tail -F`` semantics): :class:`StreamCursor` incrementally reads one
growing file — a torn final line (a write in flight, or a crashed writer's
unfinished tail) is held back and retried on the next poll, never dropped — and
:class:`RunFollower` re-discovers streams every poll (the learner's per-role
file appears seconds after the player's; supervisor attempts append to the same
run-base file) and yields each poll's new events in merge order.
"""

from __future__ import annotations

import heapq
import os
from typing import Any, Dict, List, Optional, Sequence

from sheeprl_tpu.obs.jsonl import parse_stream_line, read_events

__all__ = [
    "RunFollower",
    "StreamCursor",
    "discover_streams",
    "fleet_members",
    "is_primary_event",
    "load_stream",
    "member_of",
    "merge_streams",
    "merged_events",
]


def is_primary_event(event: Dict[str, Any]) -> bool:
    """Whether an (annotated) event belongs to the run's PRIMARY stream: the
    rank-0 ``telemetry.jsonl`` — the player's/controller's own file, also the
    run-base path the supervisor pins across attempts. Per-role learner streams
    are ``telemetry.<role>.jsonl`` siblings with their own cadence and summary;
    both ``watch``'s exit protocol and ``compare``'s window distributions key on
    this predicate, which is why it lives here and not in either consumer."""
    stream = str(event.get("stream") or "telemetry.jsonl")
    return int(event.get("rank") or 0) == 0 and os.path.basename(stream) == "telemetry.jsonl"


def fleet_members(run_dir: str) -> Optional[Dict[str, str]]:
    """When ``run_dir`` is a FLEET directory (``sheeprl.py fleet`` writes a
    ``fleet.json`` marker), the member-name → member-run-dir mapping; None for
    an ordinary run dir. Flat stream discovery would merge every member's
    rank-0 ``telemetry.jsonl`` into one confused "run" (N start events, N
    summaries); consumers that want per-run semantics (``diagnose``, ``watch``)
    use this to treat the fleet as one unit of N member runs instead."""
    if not os.path.isdir(str(run_dir)):
        return None
    from sheeprl_tpu.fleet.spec import read_marker

    marker = read_marker(str(run_dir))
    if marker is None:
        return None
    members = marker.get("members") or {}
    return {
        str(name): os.path.join(str(run_dir), str(rel)) for name, rel in sorted(members.items())
    }


def member_of(stream_label: str) -> Optional[str]:
    """The fleet member a (relative) stream label belongs to — labels of member
    streams start with ``members/<name>/`` under a fleet dir — or None for the
    fleet's own stream (``telemetry.fleet.jsonl``) / a non-fleet label."""
    parts = str(stream_label).replace(os.sep, "/").split("/")
    if len(parts) >= 3 and parts[0] == "members":
        return parts[1]
    return None


def discover_streams(run_dir: str) -> List[str]:
    """Every ``telemetry*.jsonl`` under ``run_dir`` (recursively — per-version
    subdirs and per-role siblings included), sorted for determinism. Accepts a
    direct file path too, so ``diagnose`` can be pointed at a single stream."""
    if os.path.isfile(run_dir):
        return [run_dir]
    found: List[str] = []
    for root, _dirs, files in os.walk(run_dir):
        for name in files:
            if name.startswith("telemetry") and name.endswith(".jsonl"):
                found.append(os.path.join(root, name))
    return sorted(found)


def load_stream(path: str, base_dir: Optional[str] = None) -> List[Dict[str, Any]]:
    """Parse one JSONL stream, annotating each event with its source ``stream``
    (path relative to ``base_dir`` when given) and defaulting the identity
    fields of pre-identity events (rank/attempt 0, seq = line index) so old
    recordings merge alongside new ones."""
    stream = os.path.relpath(path, base_dir) if base_dir else path
    events = read_events(path)
    for idx, event in enumerate(events):
        event["stream"] = stream
        event.setdefault("rank", 0)
        event.setdefault("attempt", 0)
        event.setdefault("seq", idx)
    return events


def merge_streams(
    streams: Sequence[Sequence[Dict[str, Any]]],
) -> List[Dict[str, Any]]:
    """K-way merge of per-file event lists into one stream ordered by wall-clock
    ``time`` (tiebreak: attempt, then seq, then stream index), preserving each
    input stream's own order even across clock anomalies."""
    heads: List[tuple] = []
    for sidx, events in enumerate(streams):
        if events:
            heads.append((_key(events[0], sidx), sidx, 0))
    heapq.heapify(heads)
    merged: List[Dict[str, Any]] = []
    while heads:
        _, sidx, pos = heapq.heappop(heads)
        merged.append(streams[sidx][pos])
        nxt = pos + 1
        if nxt < len(streams[sidx]):
            heapq.heappush(heads, (_key(streams[sidx][nxt], sidx), sidx, nxt))
    return merged


def _key(event: Dict[str, Any], stream_idx: int) -> tuple:
    return (
        float(event.get("time") or 0.0),
        int(event.get("attempt") or 0),
        int(event.get("seq") or 0),
        stream_idx,
    )


def merged_events(run_dir: str) -> List[Dict[str, Any]]:
    """Discover + load + merge every telemetry stream of ``run_dir`` into one
    ordered list (empty when the run left no stream)."""
    base = run_dir if os.path.isdir(run_dir) else os.path.dirname(run_dir)
    paths = discover_streams(run_dir)
    return merge_streams([load_stream(p, base_dir=base) for p in paths])


# ---------------------------------------------------------------------------------
# follow mode (tail -F semantics for live runs)
# ---------------------------------------------------------------------------------
class StreamCursor:
    """Incremental reader over one growing JSONL stream.

    Each :meth:`poll` reads the bytes appended since the last poll and returns
    the newly completed events, annotated like :func:`load_stream` (``stream``
    label, identity defaults). Two invariants make this safe against a live
    writer:

    - only newline-terminated lines are consumed; a torn final line (the sink's
      write may be in flight) stays in the pending buffer and is RETRIED on the
      next poll — it is never dropped and never an error;
    - a completed line that still fails to parse (a crashed writer's torn
      fragment with a later attempt's event appended behind it) goes through
      :func:`~sheeprl_tpu.obs.jsonl.parse_stream_line` recovery, so the
      follow-on event survives.

    A not-yet-existing file is a valid cursor target (polls return nothing until
    it appears) — the learner's per-role stream is created seconds after the
    player's.
    """

    def __init__(self, path: str, stream: Optional[str] = None) -> None:
        self.path = str(path)
        self.stream = stream if stream is not None else self.path
        self._offset = 0
        self._pending = b""
        self._events_read = 0  # seq default for pre-identity events, as in load_stream

    def poll(self) -> List[Dict[str, Any]]:
        try:
            with open(self.path, "rb") as fh:
                fh.seek(self._offset)
                data = fh.read()
        except OSError:
            return []
        if not data:
            return []
        self._offset += len(data)
        buf = self._pending + data
        *complete, self._pending = buf.split(b"\n")
        events: List[Dict[str, Any]] = []
        for raw in complete:
            for event in parse_stream_line(raw.decode("utf-8", errors="replace")):
                event["stream"] = self.stream
                event.setdefault("rank", 0)
                event.setdefault("attempt", 0)
                event.setdefault("seq", self._events_read)
                self._events_read += 1
                events.append(event)
        return events


class RunFollower:
    """Follow every telemetry stream of a (possibly still-materializing) run dir.

    Each :meth:`poll` re-discovers ``telemetry*.jsonl`` files (streams appear
    over a run's lifetime: versioned subdirs, late per-role files), drains every
    cursor, and returns the batch ordered by the same key the offline merge
    uses — so per-stream order is preserved and cross-stream order is wall-clock
    within the batch. The run dir itself may not exist yet (``watch`` is
    typically started alongside the launch)."""

    def __init__(self, run_dir: str) -> None:
        self.run_dir = str(run_dir)
        self._cursors: Dict[str, StreamCursor] = {}

    @property
    def streams(self) -> List[str]:
        """Relative labels of every stream discovered so far."""
        return sorted(c.stream for c in self._cursors.values())

    def poll(self) -> List[Dict[str, Any]]:
        if os.path.exists(self.run_dir):
            base = self.run_dir if os.path.isdir(self.run_dir) else os.path.dirname(self.run_dir)
            for path in discover_streams(self.run_dir):
                if path not in self._cursors:
                    label = os.path.relpath(path, base) if base else path
                    self._cursors[path] = StreamCursor(path, stream=label)
        # the batch goes through the same k-way merge as the offline path, so a
        # stream whose clock jumped backwards is still never reordered against
        # itself (batch sort by time alone would break that invariant)
        per_stream = [self._cursors[path].poll() for path in sorted(self._cursors)]
        return merge_streams([events for events in per_stream if events])
