"""Telemetry stream discovery + merge: one ordered event stream per run.

A run can scatter its telemetry over several JSONL files: decoupled MPMD
topologies (sac_decoupled / ppo_decoupled / dv3_decoupled) write one file per
role process (the player's ``telemetry.jsonl`` plus ``telemetry.<role>.jsonl``
for the learner slice), and the supervisor pins all restart *attempts* of a run
onto one shared run-base file while each attempt may also leave per-version
artifacts. The diagnosis engine (``obs/diagnose.py``) wants ONE ordered stream.

Merging key: every modern event carries ``(rank, attempt, seq)`` (see
``obs/jsonl.py``); within one file that triple is append-ordered, so a k-way
merge that pops the earliest head by wall-clock ``time`` — with
``(attempt, seq)`` as the tiebreak — yields a globally time-ordered stream that
never reorders any single writer's events. All writers of one run share the
host clock (the topologies here are single-host; multi-host pods write per-host
run dirs), so wall-clock alignment is exact up to NTP skew; per-stream order is
preserved regardless, which is the invariant the detectors rely on.

Old streams written before the identity fields existed still merge: missing
``rank``/``attempt`` default to 0 and ``seq`` to the line index.
"""

from __future__ import annotations

import heapq
import os
from typing import Any, Dict, List, Optional, Sequence

from sheeprl_tpu.obs.jsonl import read_events

__all__ = ["discover_streams", "load_stream", "merge_streams", "merged_events"]


def discover_streams(run_dir: str) -> List[str]:
    """Every ``telemetry*.jsonl`` under ``run_dir`` (recursively — per-version
    subdirs and per-role siblings included), sorted for determinism. Accepts a
    direct file path too, so ``diagnose`` can be pointed at a single stream."""
    if os.path.isfile(run_dir):
        return [run_dir]
    found: List[str] = []
    for root, _dirs, files in os.walk(run_dir):
        for name in files:
            if name.startswith("telemetry") and name.endswith(".jsonl"):
                found.append(os.path.join(root, name))
    return sorted(found)


def load_stream(path: str, base_dir: Optional[str] = None) -> List[Dict[str, Any]]:
    """Parse one JSONL stream, annotating each event with its source ``stream``
    (path relative to ``base_dir`` when given) and defaulting the identity
    fields of pre-identity events (rank/attempt 0, seq = line index) so old
    recordings merge alongside new ones."""
    stream = os.path.relpath(path, base_dir) if base_dir else path
    events = read_events(path)
    for idx, event in enumerate(events):
        event["stream"] = stream
        event.setdefault("rank", 0)
        event.setdefault("attempt", 0)
        event.setdefault("seq", idx)
    return events


def merge_streams(
    streams: Sequence[Sequence[Dict[str, Any]]],
) -> List[Dict[str, Any]]:
    """K-way merge of per-file event lists into one stream ordered by wall-clock
    ``time`` (tiebreak: attempt, then seq, then stream index), preserving each
    input stream's own order even across clock anomalies."""
    heads: List[tuple] = []
    for sidx, events in enumerate(streams):
        if events:
            heads.append((_key(events[0], sidx), sidx, 0))
    heapq.heapify(heads)
    merged: List[Dict[str, Any]] = []
    while heads:
        _, sidx, pos = heapq.heappop(heads)
        merged.append(streams[sidx][pos])
        nxt = pos + 1
        if nxt < len(streams[sidx]):
            heapq.heappush(heads, (_key(streams[sidx][nxt], sidx), sidx, nxt))
    return merged


def _key(event: Dict[str, Any], stream_idx: int) -> tuple:
    return (
        float(event.get("time") or 0.0),
        int(event.get("attempt") or 0),
        int(event.get("seq") or 0),
        stream_idx,
    )


def merged_events(run_dir: str) -> List[Dict[str, Any]]:
    """Discover + load + merge every telemetry stream of ``run_dir`` into one
    ordered list (empty when the run left no stream)."""
    base = run_dir if os.path.isdir(run_dir) else os.path.dirname(run_dir)
    paths = discover_streams(run_dir)
    return merge_streams([load_stream(p, base_dir=base) for p in paths])
