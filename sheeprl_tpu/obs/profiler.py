"""Windowed ``jax.profiler`` trace capture.

The whole-run trace wrapper (now ``metric.profiler.mode=run``, sheeprl_tpu/cli.py)
is unusable on long runs — traces of a full training run are huge. ``mode=window``
instead starts the trace at the first loop iteration whose policy step reaches
``start_step`` and stops it once ``num_steps`` policy steps have elapsed, so a
production-length run can capture a bounded steady-state window (past compile and
warmup) and nothing else. The dump lands under the run's log tree (or
``metric.profiler.dir``), viewable in TensorBoard's profile plugin / Perfetto.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Mapping, Optional

_MODES = ("off", "run", "window")


def _normalize_mode(value: Any) -> str:
    """Map config spellings onto {off, run, window}. YAML 1.1 parses a bare
    ``off`` as False and legacy configs used ``profiler: True`` for the
    whole-run wrapper, so booleans are accepted."""
    if value is None or value is False:
        return "off"
    if value is True:
        return "run"
    mode = str(value).strip().lower()
    if mode in ("false", "none", ""):
        return "off"
    if mode == "true":
        return "run"
    if mode not in _MODES:
        raise ValueError(f"metric.profiler.mode must be one of {_MODES}, got {value!r}")
    return mode


def resolve_profiler_config(metric_cfg: Mapping[str, Any]) -> Dict[str, Any]:
    """Normalize ``metric.profiler`` into ``{mode, start_step, num_steps, dir}``.

    Accepts the current group form (``profiler.mode/start_step/num_steps/dir``)
    and the legacy scalar form (``profiler: true`` + ``profiler_dir``), which maps
    onto ``mode=run``.
    """
    raw = metric_cfg.get("profiler", None)
    legacy_dir = metric_cfg.get("profiler_dir", None)
    if isinstance(raw, Mapping):
        return {
            "mode": _normalize_mode(raw.get("mode", "off")),
            "start_step": int(raw.get("start_step") or 0),
            "num_steps": int(raw.get("num_steps") or 0),
            "dir": raw.get("dir") or legacy_dir,
        }
    return {
        "mode": _normalize_mode(raw),
        "start_step": 0,
        "num_steps": 0,
        "dir": legacy_dir,
    }


class ProfilerWindow:
    """Policy-step-driven trace window. ``on_step(policy_step)`` is called once
    per loop iteration (two int compares when idle); the trace starts at the
    first call with ``policy_step >= start_step`` and stops at the first call at
    least ``num_steps`` policy steps later (``num_steps <= 0`` captures a single
    iteration). ``close()`` stops a window left open at loop exit so the dump is
    always finalized."""

    def __init__(self, mode: str, start_step: int, num_steps: int, dump_dir: str) -> None:
        self.mode = mode
        self.start_step = int(start_step)
        self.num_steps = int(num_steps)
        self.dump_dir = str(dump_dir)
        self.started_at: Optional[int] = None
        self.stopped_at: Optional[int] = None

    @property
    def active(self) -> bool:
        return self.started_at is not None and self.stopped_at is None

    def on_step(self, policy_step: int) -> None:
        if self.mode != "window" or self.stopped_at is not None:
            return
        if self.started_at is None:
            if policy_step >= self.start_step:
                self._start(policy_step)
            return
        if policy_step - self.started_at >= self.num_steps:
            self._stop(policy_step)

    def _start(self, policy_step: int) -> None:
        import jax

        os.makedirs(self.dump_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(self.dump_dir)
        except Exception as exc:  # a failed trace must never kill the run
            warnings.warn(f"jax.profiler.start_trace failed: {exc!r}; window capture disabled")
            self.stopped_at = policy_step
            return
        self.started_at = policy_step

    def _stop(self, policy_step: int) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as exc:
            warnings.warn(f"jax.profiler.stop_trace failed: {exc!r}")
        self.stopped_at = policy_step

    def close(self, policy_step: Optional[int] = None) -> None:
        if self.active:
            self._stop(policy_step if policy_step is not None else self.started_at)
