"""Stateful alert engine over SLO burn-rate snapshots.

``obs/slo.py`` turns telemetry windows into per-objective budget accounting;
this module adds the operational state machine on top: each objective owns one
alert that moves ``inactive → pending → firing → resolved`` as its burn rates
cross and clear the alert condition. The same engine runs in two places —
in-loop (``ServingTelemetry``/``RunTelemetry`` call :meth:`AlertEngine.evaluate`
once per emitted window and turn the returned transitions into schema-registered
``alert`` events on the telemetry stream) and offline (``sheeprl.py slo``
replays a recorded stream through an identical engine) — one shared catalog, so
the two can never drift apart.

Alert condition and hysteresis
------------------------------
An objective breaches when BOTH burn rates reach 1.0: the fast window
(``window // 6`` most recent telemetry windows) proves the breach is happening
*now*, the slow window (the full compliance window) proves enough budget is
actually being consumed to matter — the standard multi-window burn-rate rule,
scaled to telemetry-window cadence instead of wall time because that is the
unit the producers emit at. A breached objective enters ``pending`` and must
stay breached for ``for`` consecutive evaluations (the objective's
``for_windows`` hysteresis) before it escalates to ``firing`` — one bad window
pages nobody. When the condition clears: a pending alert silently deactivates
(it never fired), a firing alert emits ``resolved`` and deactivates.

Transitions are plain dicts shaped like the ``alert`` event payload
(status/name/objective/severity/burn rates/budget); the caller owns emission
so the engine stays side-effect free and replayable.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Sequence

__all__ = ["AlertEngine", "BURN_THRESHOLD"]

# both burn rates must reach this for the alert condition; 1.0 = consuming
# budget exactly as fast as the objective allows
BURN_THRESHOLD = 1.0


class AlertEngine:
    """One alert per objective, evaluated against successive snapshots."""

    def __init__(self, objectives: Sequence[Any]) -> None:
        self._spec = {o.name: o for o in objectives}
        # name -> {"state": inactive|pending|firing, "streak": consecutive
        # breached evaluations, "since_samples": snapshot samples at entry}
        self._states: Dict[str, Dict[str, Any]] = {
            name: {"state": "inactive", "streak": 0} for name in self._spec
        }

    def __bool__(self) -> bool:
        return bool(self._spec)

    def states(self) -> Dict[str, Dict[str, Any]]:
        return {name: dict(state) for name, state in self._states.items()}

    def firing(self) -> Dict[str, Dict[str, Any]]:
        """Currently-firing alerts: name -> {severity, streak}."""
        out: Dict[str, Dict[str, Any]] = {}
        for name, state in self._states.items():
            if state["state"] == "firing":
                out[name] = {
                    "severity": self._spec[name].severity,
                    "streak": state["streak"],
                }
        return out

    def evaluate(self, snapshot: Mapping[str, Mapping[str, Any]]) -> List[Dict[str, Any]]:
        """Advance every alert one evaluation using a ``SloEvaluator.snapshot()``
        and return the transitions (alert-event payloads) this step produced.
        Objectives absent from the snapshot, or present without samples, hold
        their state — a window without the signal is no evidence either way."""
        transitions: List[Dict[str, Any]] = []
        for name, objective in self._spec.items():
            stats = snapshot.get(name)
            if not stats or not stats.get("samples"):
                continue
            breached = (
                float(stats.get("burn_fast") or 0.0) >= BURN_THRESHOLD
                and float(stats.get("burn_slow") or 0.0) >= BURN_THRESHOLD
            )
            state = self._states[name]
            payload = {
                "name": name,
                "objective": name,
                "severity": objective.severity,
                "value": stats.get("value"),
                "target": objective.target,
                "budget_remaining": stats.get("budget_remaining"),
                "burn_fast": stats.get("burn_fast"),
                "burn_slow": stats.get("burn_slow"),
                "for_windows": objective.for_windows,
            }
            if breached:
                state["streak"] += 1
                if state["state"] == "inactive":
                    state["state"] = "pending"
                    state["streak"] = 1
                    transitions.append({"status": "pending", **payload})
                if state["state"] == "pending" and state["streak"] >= objective.for_windows:
                    state["state"] = "firing"
                    transitions.append({"status": "firing", **payload})
            else:
                if state["state"] == "firing":
                    transitions.append({"status": "resolved", **payload})
                state["state"] = "inactive"
                state["streak"] = 0
        return transitions
