"""Run fingerprinting: the comparability key of telemetry streams and bench JSONs.

Two event streams (or two BENCH workloads) are only worth diffing when they ran
the *same experiment* on the *same hardware shape*. The fingerprint makes that
check mechanical instead of tribal knowledge: every telemetry ``start`` event
(``obs/telemetry.py``) and every bench workload's ``conditions``
(``bench.py``) carries

- ``algo`` — the registered algorithm name;
- ``config_hash`` — a stable hash over the RESOLVED config with the volatile
  keys dropped (run/exp names carry timestamps, ``metric``/``checkpoint``/
  ``resilience``/``hydra`` are operational knobs that do not change what the
  run computes — the same exclusion set as resume-merge's non-resumable keys);
- ``code_version`` — the git sha of the working tree (plus ``-dirty`` when the
  tree has uncommitted changes), so a regression can be pinned to a commit;
- ``backend`` / ``device_kind`` / ``device_count`` / ``mesh_shape`` — the
  hardware the programs compiled for;
- ``env_backend`` — which environment plane stepped the run (``host``
  gymnasium vs the on-device ``jax`` plane, ``env.backend``);
- ``key_shapes`` — the config values that directly set compiled program shapes
  (num_envs, per-rank batch/sequence, rollout steps).

``fingerprint_compatible`` is what ``compare``/``bench-diff`` gate matching on:
``code_version`` deliberately does NOT count against compatibility (comparing
two commits is the whole point of a regression gate), everything else does.
Every field is best-effort ``None``-tolerant: a missing field never blocks a
comparison, it just cannot veto one.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
from typing import Any, Dict, List, Mapping, Optional, Tuple

from sheeprl_tpu.obs.jsonl import _jsonable

__all__ = [
    "COMPARE_KEYS",
    "code_version",
    "config_hash",
    "fingerprint_compatible",
    "run_fingerprint",
]

# dropped from the config hash: run/exp names embed timestamps, and the
# operational groups (logging, checkpoint cadence, resilience, run-dir layout)
# do not change what the run computes — mirrors cli._NON_RESUMABLE_KEYS
_VOLATILE_TOP_KEYS = (
    "exp_name",
    "run_name",
    "root_dir",
    "checkpoint",
    "metric",
    "hydra",
    "resilience",
    "model_manager",
)

# fingerprint fields that veto comparability when BOTH sides carry a value and
# the values differ; code_version is deliberately absent (cross-commit diffs
# are the point of the regression gate). env_backend is its own top-level field
# (not a key_shapes entry) so pre-PR-7 recordings — whose key_shapes dict
# predates it — stay comparable under the None-tolerant rule while a host-env
# run can never silently diff against a jax-env run. axis_names (None-tolerant
# the same way for pre-2-D-mesh recordings) keeps a [2, 4] data x model run
# from ever silently diffing against a [2, 4]... data-only one.
COMPARE_KEYS = (
    "algo",
    "config_hash",
    "backend",
    "device_kind",
    "device_count",
    "mesh_shape",
    "axis_names",
    "env_backend",
    "buffer_backend",
    "key_shapes",
)


def canonical_mesh_shape(mesh_shape: Any) -> Optional[List[int]]:
    """One serialized form for a mesh shape no matter which container carried
    it — tuple, list, Hydra ListConfig, numpy shape, or a bare int — so two
    identical runs can never false-mismatch on ``(2, 4)`` vs ``[2, 4]``, while
    ``[8]`` vs ``[2, 4]`` stays a real veto. Returns None (fingerprint =
    unknown, never vetoes) for unresolvable values, INCLUDING shapes that still
    carry a ``-1`` wildcard: the wildcard's extent depends on the device count,
    and stamping it raw would false-mismatch against the resolved shape."""
    if mesh_shape is None:
        return None
    if isinstance(mesh_shape, (int,)) or (
        hasattr(mesh_shape, "__int__") and not hasattr(mesh_shape, "__iter__")
    ):
        mesh_shape = [mesh_shape]
    try:
        shape = [int(s) for s in mesh_shape]
    except (TypeError, ValueError):
        return None
    if any(s < 1 for s in shape):
        return None
    return shape

_CODE_VERSION_CACHE: Dict[str, Optional[str]] = {}


def config_hash(cfg: Mapping[str, Any]) -> Optional[str]:
    """Stable 12-hex-char hash over the resolved config minus the volatile keys.
    Canonical form: JSON with sorted keys over :func:`_jsonable` leaves, so dict
    ordering, numpy scalars and dotdict wrappers cannot perturb the digest."""
    try:
        pruned = {
            str(k): _jsonable(v)
            for k, v in dict(cfg).items()
            if str(k) not in _VOLATILE_TOP_KEYS
        }
        canonical = json.dumps(pruned, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(canonical.encode()).hexdigest()[:12]
    except Exception:
        return None


def code_version() -> Optional[str]:
    """Git sha of the source tree this process imported (``-dirty`` suffixed when
    the tree has uncommitted changes); ``SHEEPRL_CODE_VERSION`` overrides for
    deployments without a .git dir. Cached per process — the sha cannot change
    under a running process that already imported its code."""
    override = os.environ.get("SHEEPRL_CODE_VERSION")
    if override:
        return override
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if repo in _CODE_VERSION_CACHE:
        return _CODE_VERSION_CACHE[repo]
    sha: Optional[str] = None
    try:
        out = subprocess.run(
            ["git", "-C", repo, "rev-parse", "--short=12", "HEAD"],
            capture_output=True,
            text=True,
            timeout=10,
        )
        if out.returncode == 0 and out.stdout.strip():
            sha = out.stdout.strip()
            dirty = subprocess.run(
                ["git", "-C", repo, "status", "--porcelain", "--untracked-files=no"],
                capture_output=True,
                text=True,
                timeout=10,
            )
            if dirty.returncode == 0 and dirty.stdout.strip():
                sha += "-dirty"
    except Exception:
        sha = None
    _CODE_VERSION_CACHE[repo] = sha
    return sha


def _key_shapes(cfg: Mapping[str, Any]) -> Dict[str, Any]:
    """The config values that directly determine compiled program shapes."""
    shapes: Dict[str, Any] = {}
    env = cfg.get("env") or {}
    algo = cfg.get("algo") or {}
    for source, key in (
        (env, "num_envs"),
        (algo, "per_rank_batch_size"),
        (algo, "per_rank_sequence_length"),
        (algo, "rollout_steps"),
    ):
        value = source.get(key) if hasattr(source, "get") else None
        if value is not None:
            try:
                shapes[key] = int(value)
            except (TypeError, ValueError):
                shapes[key] = value
    return shapes


def run_fingerprint(cfg: Mapping[str, Any], fabric: Any = None) -> Dict[str, Any]:
    """Build the run's fingerprint from its resolved config plus (optionally) the
    live fabric's device/mesh view. Every field is best-effort: unknowns are
    ``None``/absent rather than an exception — the fingerprint must never be the
    thing that takes a run down."""
    algo_cfg = cfg.get("algo") or {}
    env_cfg = cfg.get("env") or {}
    buffer_cfg = cfg.get("buffer") or {}
    fabric_cfg = cfg.get("fabric") or {}
    fp: Dict[str, Any] = {
        "algo": algo_cfg.get("name") if hasattr(algo_cfg, "get") else None,
        "config_hash": config_hash(cfg),
        "code_version": code_version(),
        "backend": None,
        "device_kind": None,
        "device_count": None,
        "mesh_shape": None,
        "axis_names": None,
        # which environment plane stepped the run (host gymnasium vs the
        # on-device jax plane): throughput across planes lives on different
        # scales, so compare/bench-diff must refuse to silently diff them
        "env_backend": str(env_cfg.get("backend") or "host")
        if hasattr(env_cfg, "get")
        else None,
        # which replay plane fed training (host local/service buffer vs the
        # on-mesh device ring): same refusal rationale as env_backend — a
        # device-ring run's throughput must never silently diff against a
        # host-replay one. None-tolerant for pre-ring recordings.
        "buffer_backend": str(buffer_cfg.get("backend") or "local")
        if hasattr(buffer_cfg, "get")
        else None,
        "key_shapes": _key_shapes(cfg),
    }
    if hasattr(fabric_cfg, "get"):
        # cfg-only route (no live fabric — bench wall-clock workloads): the
        # canonical form only sticks when fully explicit; a -1 wildcard stays
        # None so it cannot false-mismatch the resolved shape a live run stamps
        fp["mesh_shape"] = canonical_mesh_shape(fabric_cfg.get("mesh_shape"))
        axes = fabric_cfg.get("axis_names")
        if axes is not None:
            if isinstance(axes, str):
                # a scalar override (fabric.axis_names=data) arrives as a bare
                # string — wrap it like normalize_mesh_spec does, or iterating
                # would char-split it into a fingerprint that vetoes the live
                # run's ["data"]
                axes = [axes]
            try:
                fp["axis_names"] = [str(a) for a in axes]
            except TypeError:
                pass
    if fabric is not None:
        device = getattr(fabric, "device", None)
        fp["backend"] = getattr(device, "platform", None)
        fp["device_kind"] = getattr(device, "device_kind", None)
        try:
            # TOTAL mesh devices (= world_size on a 1-D mesh; on a 2-D mesh
            # world_size is only the data extent and mesh_shape carries the split)
            fp["device_count"] = int(fabric.mesh.devices.size)
        except Exception:
            try:
                fp["device_count"] = int(getattr(fabric, "world_size", None))
            except (TypeError, ValueError):
                pass
        try:
            fp["mesh_shape"] = canonical_mesh_shape(fabric.mesh.devices.shape)
        except Exception:
            pass
        try:
            fp["axis_names"] = [str(a) for a in fabric.mesh.axis_names]
        except Exception:
            pass
    return fp


def fingerprint_compatible(
    a: Optional[Mapping[str, Any]], b: Optional[Mapping[str, Any]]
) -> Tuple[bool, List[str]]:
    """Whether two fingerprints describe comparable runs: every
    :data:`COMPARE_KEYS` field where BOTH sides carry a value must match
    (missing/None fields never veto — old recordings stay comparable).
    Returns ``(compatible, mismatched_keys)``."""
    if not a or not b:
        return True, []
    mismatches: List[str] = []
    for key in COMPARE_KEYS:
        va, vb = a.get(key), b.get(key)
        if va is None or vb is None:
            continue
        if _jsonable(va) != _jsonable(vb):
            mismatches.append(key)
    return not mismatches, mismatches
