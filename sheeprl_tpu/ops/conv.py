"""Fast-gradient stride-2 VALID convolution (the Dreamer encoder's hot op).

XLA:CPU's lowering of small-channel convolutions is pathological at Dreamer
replay-batch scale (T*B ≈ thousands of 64x64 frames, 2-32 channels): the
forward runs at ~1 GFLOP/s on a core whose sgemm peak is >100, the input
gradient lowers to a slow input-dilated convolution, and the weight gradient
first PERMUTES the whole activation tensor to [C, H, W, N] — measured 652 ms
for the first encoder layer alone at the DV1 benchmark shapes (see
PERF_ANALYSIS.md). None of this is FLOP-bound; it is layout and loop overhead.

For stride-2 VALID convolutions (the reference Dreamer encoders:
sheeprl/algos/dreamer_v1/agent.py k=4 s=2, dreamer_v2 the same) every piece
decomposes into bandwidth-friendly primitives:

- space-to-depth once: x[N,H,W,C] -> [N,H/2,W/2,4C] (one cheap rearrange), so
  the stride-2 k x k conv becomes a STRIDE-1 (k/2) x (k/2) conv with 4x the
  input channels — a shape XLA:CPU executes near bandwidth;
- forward and input grad: plain stride-1 VALID convs (the input grad is the
  full conv with the flipped, io-swapped kernel — no input dilation);
- weight grad: (k/2)^2 CONTIGUOUS tap slices of the s2d tensor, each one
  tall-skinny matmul [4Cin, N*H'*W'] x [N*H'*W', Cout] — the CHWN permute
  never materializes.

The trick is packaged as a ``jax.custom_vjp`` and — like the fused deconv and
the Pallas GRU — selected per lowering platform: CPU gets the decomposition,
every other backend (TPU lowers all three conv forms onto the MXU natively)
keeps ``lax.conv_general_dilated``. ``SHEEPRL_DISABLE_FAST_CONV=1`` forces the
native form everywhere. Values and gradients are parity-tested against
``nn.Conv`` (tests/test_ops/test_fast_conv.py); ``FastConv2x`` keeps
``nn.Conv``'s exact parameter tree so checkpoints are drop-in compatible.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def _fast_conv_enabled() -> bool:
    # the custom-vjp decomposition is single-device-only: under a partitioned
    # mesh its packing reshapes make the SPMD partitioner mis-scale fused
    # loss/grad reductions (see sheeprl_tpu/ops/__init__.py)
    from sheeprl_tpu import ops

    if ops.partitioned_mesh_active():
        return False
    return os.environ.get("SHEEPRL_DISABLE_FAST_CONV", "0") != "1"


def _native_conv(x, w):
    return lax.conv_general_dilated(
        x, w, (2, 2), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


def _space_to_depth(x):
    """[N, H, W, C] -> [N, H/2, W/2, 4C], 2x2 blocks into channels (r, c, ci)."""
    n, h, w, c = x.shape
    return (
        x.reshape(n, h // 2, 2, w // 2, 2, c).transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
    )


def _pack_kernel(w):
    """[k, k, Cin, Cout] -> [k/2, k/2, 4*Cin, Cout] matching _space_to_depth's
    (r, c, ci) channel order; exact for even k, stride 2."""
    k = w.shape[0]
    return jnp.stack([w[r::2, c::2] for r in range(2) for c in range(2)], axis=2).reshape(
        k // 2, k // 2, 4 * w.shape[2], w.shape[3]
    )


def _conv_s1_valid(x, w):
    return lax.conv_general_dilated(
        x, w, (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


# custom vjp over the SPACE-TO-DEPTH-domain stride-1 convolution: fwd and input
# grad are plain stride-1 VALID convs (fast on CPU); the weight grad replaces
# XLA's CHWN-permute-plus-conv with k2*k2 CONTIGUOUS tap slices, each one
# tall-skinny matmul.
@jax.custom_vjp
def _s1_conv(xs, w2):
    return _conv_s1_valid(xs, w2)


def _s1_conv_fwd(xs, w2):
    return _conv_s1_valid(xs, w2), (xs, w2)


def _s1_conv_bwd(res, g):
    """Both gradients from ONE shared tensor G of the k2*k2 zero-padded shifts
    of g at xs's spatial extent (g is the SMALL tensor — Cout channels at output
    resolution — so shifting it beats slicing xs k2^2 times by ~an order of
    magnitude of traffic):

        G[n, H, W, (a, b, d)] = g[n, H-a, W-b, d]   (zero outside)
        dxs[n, H, W, c] = G[n, H, W] . w2[a, b, c, d]  over (a, b, d)
        dw2[a, b, c, d] = xs[:, :, :, c] . G[:, :, :, (a, b, d)]  over (n, H, W)

    — two tall-skinny matmuls, no CHWN permute, no input-dilated conv."""
    xs, w2 = res
    k2, _, c2, cout = w2.shape
    n, h2, w2_sp, _ = xs.shape
    ho, wo = g.shape[1], g.shape[2]

    shifts = []
    for a in range(k2):
        for b in range(k2):
            shifts.append(jnp.pad(g, ((0, 0), (a, h2 - ho - a), (b, w2_sp - wo - b), (0, 0))))
    G = jnp.concatenate(shifts, axis=-1).reshape(-1, k2 * k2 * cout)  # [n*h2*w2_sp, k2*k2*Cout]

    # dxs: [n*h2*w2_sp, k2*k2*Cout] x [k2*k2*Cout, Cin']
    w_flat = w2.transpose(0, 1, 3, 2).reshape(k2 * k2 * cout, c2)
    dxs = jnp.dot(G, w_flat).reshape(n, h2, w2_sp, c2)

    # dw2: [Cin', n*h2*w2_sp] x [n*h2*w2_sp, k2*k2*Cout]
    dw_flat = jnp.dot(xs.reshape(-1, c2).T, G)  # [Cin', k2*k2*Cout]
    dw2 = dw_flat.reshape(c2, k2, k2, cout).transpose(1, 2, 0, 3)
    return dxs, dw2


_s1_conv.defvjp(_s1_conv_fwd, _s1_conv_bwd)


def _fast_conv(x, w):
    """Stride-2 VALID conv of NHWC x with HWIO w (even k) in s2d form. The s2d
    rearranges and the final slice are plain jax ops (autodiff handles them);
    only the inner stride-1 conv carries the custom vjp."""
    k = w.shape[0]
    n, h, w_sp, _ = x.shape
    ho, wo = (h - k) // 2 + 1, (w_sp - k) // 2 + 1
    # pad odd extents to even for the 2x2 blocking; the padded tail only feeds
    # conv outputs beyond (ho, wo), which the final slice drops
    xe = jnp.pad(x, ((0, 0), (0, h % 2), (0, w_sp % 2), (0, 0)))
    xs = _space_to_depth(xe)
    y = _s1_conv(xs, _pack_kernel(w))
    return y[:, :ho, :wo, :]


class FastConv2x(nn.Module):
    """Drop-in for ``nn.Conv(features, (k, k), strides=(2, 2), padding="VALID")``
    on NHWC inputs, with the CPU fast-gradient decomposition. Identical parameter
    tree ('kernel' [k, k, Cin, features], optional 'bias' [features]).

    ``padding`` adds symmetric spatial zero-padding BEFORE the VALID conv —
    i.e. ``nn.Conv(..., padding=[(p, p), (p, p)])`` semantics (the Dreamer-V3
    encoder's p=1 configuration)."""

    features: int
    kernel_size: int
    padding: int = 0
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()
    dtype: Any = jnp.float32
    # The decomposition wins where XLA:CPU's conv is layout/overhead bound:
    # SMALL input channels over LARGE spatial maps (Dreamer encoder stages,
    # measured 2.2x). At compute-bound shapes it LOSES (NatureCNN's 32->64
    # k4-s2 layer measured ~0.5x) — those stay on the native lowering.
    max_fast_cin: int = 8

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if x.ndim < 4:
            raise ValueError(f"expected [..., H, W, C] input, got shape {x.shape}")
        # nn.Conv semantics: arbitrary leading batch dims flatten to one
        lead = x.shape[:-3]
        x = x.reshape(-1, *x.shape[-3:])
        k = int(self.kernel_size)
        c_in = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init, (k, k, c_in, self.features), jnp.float32)
        kernel = kernel.astype(self.dtype)
        x = x.astype(self.dtype)
        if self.padding:
            p = int(self.padding)
            x = jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)))
        if _fast_conv_enabled() and k % 2 == 0 and c_in <= self.max_fast_cin:
            out = jax.lax.platform_dependent(x, kernel, cpu=_fast_conv, default=_native_conv)
        else:
            out = _native_conv(x, kernel)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,), jnp.float32)
            out = out + bias.astype(self.dtype)
        return out.reshape(*lead, *out.shape[-3:])
