"""Fused LayerNorm-GRU step — the RSSM's hot op — as a Pallas TPU kernel.

The recurrent core of every Dreamer world model is a LayerNorm-GRU cell stepped
sequentially (reference LayerNormGRUCell, sheeprl/models/models.py:331-411, called
per timestep in dreamer_v3.py:86-97). One step is:

    gates = LN(concat(x, h) @ W + b)         # [B, 3H]
    r, c, u = split(gates)
    h' = sigmoid(u - 1) * tanh(sigmoid(r) * c) + (1 - sigmoid(u - 1)) * h

XLA compiles this as matmul + a chain of elementwise/reduce ops; the Pallas kernel
runs the whole step in ONE VMEM-resident pass — the [B, 3H] gates tensor never
round-trips to HBM between the matmul, the layernorm reduction, and the gating —
which is exactly the fusion the memory-bound sequential scan wants. The kernel tiles
the batch over a grid and keeps W resident in VMEM, so it applies when
``K * 3H * 4B`` fits on-chip (all Dreamer sizes up to L; XL falls back to XLA).

``interpret=True`` runs the same kernel on CPU for tests (numerical-parity suite in
tests/test_ops/test_gru_kernel.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# VMEM budget for the weight block (bytes); above this the caller should fall back
# to the XLA path. ~8 MB leaves room for the activation tiles in 16 MB VMEM.
PALLAS_GRU_VMEM_WEIGHT_BUDGET = 8 * 1024 * 1024


def _ln_gru_kernel(inp_ref, hx_ref, w_ref, b_ref, scale_ref, bias_ref, out_ref, *, eps: float):
    # operands keep their storage dtype (bf16 inputs feed the MXU natively);
    # accumulation and the layernorm/gating chain run in f32. The per-feature
    # vectors arrive as (1, 3H) blocks — TPU tiling wants >=2-D operands.
    # The dot precision is pinned explicitly: Mosaic only lowers DEFAULT/HIGHEST,
    # so inheriting the repo's global jax_default_matmul_precision="high"
    # (bf16_3x) makes the WHOLE kernel fail to lower for TPU — caught by the AOT
    # suite (tests/test_ops/test_tpu_lowering.py). DEFAULT is the MXU-native
    # pass the kernel was designed around (bf16 multiply, f32 accumulate); the
    # fused win is VMEM locality, not multiply precision.
    gates = jnp.dot(
        inp_ref[...],
        w_ref[...],
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT,
    )
    gates = gates + b_ref[...].astype(jnp.float32)
    # LayerNorm over the full 3H feature axis (reference norms the stacked
    # projection before splitting into gates)
    mean = jnp.mean(gates, axis=-1, keepdims=True)
    centered = gates - mean
    var = jnp.mean(jnp.square(centered), axis=-1, keepdims=True)
    normed = centered * jax.lax.rsqrt(var + eps)
    normed = normed * scale_ref[...].astype(jnp.float32) + bias_ref[...].astype(jnp.float32)
    hidden = hx_ref[...].astype(jnp.float32)
    H = hidden.shape[-1]
    reset = jax.nn.sigmoid(normed[:, :H])
    cand = jnp.tanh(reset * normed[:, H : 2 * H])
    update = jax.nn.sigmoid(normed[:, 2 * H :] - 1.0)
    out_ref[...] = (update * cand + (1.0 - update) * hidden).astype(out_ref.dtype)


def _pallas_forward(eps, block_b, interpret, inp, hx, w, b, scale, bias) -> jax.Array:
    from jax.experimental import pallas as pl

    B, K = inp.shape
    H = hx.shape[-1]
    block_b = min(block_b, B)
    grid = ((B + block_b - 1) // block_b,)
    # feature vectors ride as (1, 3H): TPU memory tiling is defined over the last
    # two dims, so every operand is kept >=2-D
    return pl.pallas_call(
        functools.partial(_ln_gru_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((B, H), hx.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, K), lambda i: (i, 0)),
            pl.BlockSpec((block_b, H), lambda i: (i, 0)),
            pl.BlockSpec((K, 3 * H), lambda i: (0, 0)),
            pl.BlockSpec((1, 3 * H), lambda i: (0, 0)),
            pl.BlockSpec((1, 3 * H), lambda i: (0, 0)),
            pl.BlockSpec((1, 3 * H), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_b, H), lambda i: (i, 0)),
        interpret=interpret,
    )(inp, hx, w, b.reshape(1, -1), scale.reshape(1, -1), bias.reshape(1, -1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _fused_ln_gru(eps, block_b, interpret, inp, hx, w, b, scale, bias):
    return _pallas_forward(eps, block_b, interpret, inp, hx, w, b, scale, bias)


def _fused_fwd(eps, block_b, interpret, inp, hx, w, b, scale, bias):
    out = _pallas_forward(eps, block_b, interpret, inp, hx, w, b, scale, bias)
    return out, (inp, hx, w, b, scale, bias)


def _fused_bwd(eps, block_b, interpret, residuals, g):
    # backward through the mathematically-identical XLA path: the forward keeps the
    # fused VMEM kernel, the (train-only) backward re-derives gradients with XLA's
    # autodiff — pallas_call itself has no reverse rule
    inp, hx, w, b, scale, bias = residuals
    _, vjp = jax.vjp(
        lambda *args: ln_gru_step_reference(*args, eps=eps), inp, hx, w, b, scale, bias
    )
    return vjp(g)


_fused_ln_gru.defvjp(_fused_fwd, _fused_bwd)


def fused_ln_gru_step(
    inp: jax.Array,
    hx: jax.Array,
    w: jax.Array,
    b: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    eps: float = 1e-3,
    block_b: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """One fused LayerNorm-GRU step (differentiable: custom VJP via the XLA math).

    Args: ``inp`` [B, K] (already ``concat([x, h], -1)``), ``hx`` [B, H], ``w``
    [K, 3H], ``b``/``scale``/``bias`` [3H]. Returns the new hidden state [B, H].
    """
    return _fused_ln_gru(float(eps), int(block_b), bool(interpret), inp, hx, w, b, scale, bias)


def ln_gru_step_reference(
    inp: jax.Array,
    hx: jax.Array,
    w: jax.Array,
    b: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    eps: float = 1e-3,
) -> jax.Array:
    """Pure-XLA reference implementation (same math, used for parity tests and as
    the fallback path when the weight block exceeds the VMEM budget)."""
    # same dtype policy as the kernel: native-dtype matmul operands, f32 accumulate
    gates = (
        jax.lax.dot_general(
            inp, w, (((inp.ndim - 1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        + b.astype(jnp.float32)
    )
    mean = jnp.mean(gates, axis=-1, keepdims=True)
    centered = gates - mean
    var = jnp.mean(jnp.square(centered), axis=-1, keepdims=True)
    normed = centered * jax.lax.rsqrt(var + eps)
    normed = normed * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    hidden = hx.astype(jnp.float32)
    H = hidden.shape[-1]
    reset = jax.nn.sigmoid(normed[..., :H])
    cand = jnp.tanh(reset * normed[..., H : 2 * H])
    update = jax.nn.sigmoid(normed[..., 2 * H :] - 1.0)
    return (update * cand + (1.0 - update) * hidden).astype(hx.dtype)


def pallas_gru_applicable(K: int, H: int, itemsize: int = 4) -> bool:
    """Whether the fused kernel's weight block fits the VMEM budget. (Platform
    selection is NOT decided here: LayerNormGRUCell dispatches per lowering
    platform via jax.lax.platform_dependent.)"""
    return K * 3 * H * itemsize <= PALLAS_GRU_VMEM_WEIGHT_BUDGET
