"""Pallas TPU kernels for the hot ops (SURVEY §2.4: the reference's native-speed
layer is external libtorch/cuDNN kernels; here the custom-kernel layer is Pallas)."""

from sheeprl_tpu.ops.deconv import FusedConvTranspose4x4S2, FusedConvTransposeS2Valid
from sheeprl_tpu.ops.gru import (
    fused_ln_gru_step,
    ln_gru_step_reference,
    pallas_gru_applicable,
)

__all__ = [
    "FusedConvTranspose4x4S2",
    "FusedConvTransposeS2Valid",
    "fused_ln_gru_step",
    "ln_gru_step_reference",
    "pallas_gru_applicable",
    "partitioned_mesh_active",
    "set_partitioned_mesh",
]

# Whether this process traces programs for a PARTITIONED (>1 device) mesh.
# The custom-gradient kernels (fast conv, fused deconv, Pallas GRU step) are
# single-device decompositions: their packing reshapes mix the batch axis with
# spatial/channel dims, and the SPMD partitioner mis-scales the resulting fused
# reductions once the batch is sharded over >2 devices (measured on the DV3
# world loss: x2.1 at 4 CPU devices, x7.7 at 8; updated params survived only
# because clip+adam absorb a uniform gradient scale). The gate fires at >1
# device even though 2-way was measured exact: 2-way exactness is a partitioner
# CHOICE, not a contract, and the cost is confined to CPU-simulated meshes —
# on TPU the conv/deconv fast paths are CPU-only `platform_dependent` branches
# (native MXU convs run either way) and a Pallas kernel under ANY partitioning
# is a correctness hazard, not a win. ``Fabric._setup`` sets the flag sticky
# upward; single-device runs keep the fast paths.
_PARTITIONED_MESH = {"active": False}


def set_partitioned_mesh(active: bool) -> None:
    """Record whether programs are being built for a multi-device mesh (called
    by ``Fabric._setup``); disables the custom-kernel fast paths when True."""
    _PARTITIONED_MESH["active"] = bool(active)


def partitioned_mesh_active() -> bool:
    return _PARTITIONED_MESH["active"]
