"""Pallas TPU kernels for the hot ops (SURVEY §2.4: the reference's native-speed
layer is external libtorch/cuDNN kernels; here the custom-kernel layer is Pallas)."""

from sheeprl_tpu.ops.deconv import FusedConvTranspose4x4S2, FusedConvTransposeS2Valid
from sheeprl_tpu.ops.gru import (
    fused_ln_gru_step,
    ln_gru_step_reference,
    pallas_gru_applicable,
)

__all__ = [
    "FusedConvTranspose4x4S2",
    "FusedConvTransposeS2Valid",
    "fused_ln_gru_step",
    "ln_gru_step_reference",
    "pallas_gru_applicable",
]
