"""Fused stride-2 transposed convolution (the Dreamer decoder's hot op).

``lax.conv_transpose`` lowers to an input-dilated convolution; XLA:CPU executes
that form ~3x slower than a plain convolution of the same FLOPs (measured on the
decoder stage shapes), and the backward pays ~2x. For kernel 4 / stride 2 /
``SAME`` padding — the Dreamer-V3 decoder configuration (reference
sheeprl/algos/dreamer_v3/agent.py:154-228 uses k=4, s=2 throughout) — the
transposed convolution decomposes EXACTLY into one regular 2x2 VALID convolution
producing the four output phases, followed by a depth-to-space interleave:

    y[n, 2i+r, 2j+c, o] = sum_{a,b} x[n, i+r-1+a, j+c-1+b] * w[r+2a, c+2b, :, o]

(derived from jax's ``conv_transpose(..., padding="SAME")`` = input dilation 2
with padding (2, 2); parity-tested against ``nn.ConvTranspose`` to fp32 rounding,
values and gradients). The module keeps ``nn.ConvTranspose``'s exact parameter
tree ('kernel' of shape (4, 4, Cin, features), optional 'bias'), so it is a
checkpoint-compatible drop-in when given the same submodule ``name``.

The phase form is an XLA:CPU-lowering workaround, so — like the Pallas GRU's
platform dispatch — it is selected per lowering platform via
``jax.lax.platform_dependent``: CPU gets the phase form, every other backend
(TPU lowers input-dilated convolutions onto the MXU natively) gets
``lax.conv_transpose``. ``SHEEPRL_DISABLE_FUSED_DECONV=1`` forces the native
form everywhere.
"""

from __future__ import annotations

import os
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _fused_deconv_enabled() -> bool:
    # single-device-only decomposition — see ops/conv.py's matching gate
    from sheeprl_tpu import ops

    if ops.partitioned_mesh_active():
        return False
    return os.environ.get("SHEEPRL_DISABLE_FUSED_DECONV", "0") != "1"


# XLA:CPU's convolution is pathological at SMALL input-channel counts in any
# form (see ops/conv.py's module header) — which is exactly the late Dreamer
# decoder stages (2-4 channels at 32x32+ spatial, the most expensive maps). For
# 2x2 phase kernels (the k=4 SAME deconv — Dreamer-V3's decoder) those shapes
# run ~2.8x faster as an explicit im2col matmul whose AUTODIFF backward is also
# pure matmuls + slice-adds (last stage fwd+bwd 186 -> 68 ms, second-to-last
# 27 -> 15 ms; at cin >= 8 the native conv is at parity, so the cin gate). For
# 3x3 phase kernels (the k=5/6 VALID deconvs — DV1/DV2; SAC-AE's k=4 deconv
# yields t_max=2 but sits above the cin gate) the 9-slice
# cols concat dominates and im2col measured 1.2-1.6x SLOWER than the native
# conv at both benchmark batch sizes — every matmul reformulation tried
# (shift-accumulate, conv_general_dilated_patches, custom tap-matmul vjp)
# landed at or behind the native lowering, so large-map t=3 keeps it. The
# EARLY decoder stages are the opposite regime: at tiny spatial extents the
# cols concat is cheap and the matmul dominates regardless of t or cin
# (4x4 extent, cin=32, t=3: native 71 ms -> im2col 15 ms fwd+bwd), so a small
# spatial area also takes the path.
_IM2COL_MAX_CIN = 4
_IM2COL_MAX_AREA = 36  # padded-extent H*W; 6x6 measured at parity, 4x4 a 4.8x win


def _im2col_conv_s1(xp: jax.Array, k2: jax.Array) -> jax.Array:
    """Stride-1 VALID convolution as an im2col matmul ([t*t*Cin] patch rows x
    flattened kernel). Exact same math as ``lax.conv_general_dilated`` with
    stride 1; faster on XLA:CPU for tiny Cin at t=2, with a matmul-only
    backward."""
    t = k2.shape[0]
    n, hp, wp, c_in = xp.shape
    c_out = k2.shape[-1]
    ho, wo = hp - t + 1, wp - t + 1
    cols = jnp.concatenate(
        [xp[:, a : a + ho, b : b + wo, :] for a in range(t) for b in range(t)], axis=-1
    )
    w_flat = k2.reshape(t * t * c_in, c_out)
    # cols channel order is (a, b, ci) — matches k2's (kh, kw, ci) row order
    return jnp.dot(cols.reshape(-1, t * t * c_in), w_flat).reshape(n, ho, wo, c_out)


def _phase_conv(xp: jax.Array, k2: jax.Array) -> jax.Array:
    """The phase convolution with the im2col fast path (tiny channels at t=2,
    or tiny spatial extent at any t — see the gate notes above)."""
    if (k2.shape[0] == 2 and xp.shape[-1] <= _IM2COL_MAX_CIN) or (
        xp.shape[1] * xp.shape[2] <= _IM2COL_MAX_AREA
    ):
        return _im2col_conv_s1(xp, k2)
    return lax.conv_general_dilated(
        xp, k2, (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


class FusedConvTranspose4x4S2(nn.Module):
    """Drop-in for ``nn.ConvTranspose(features, (4, 4), strides=(2, 2),
    padding="SAME")`` on NHWC inputs, computed in phase-decomposed form."""

    features: int
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if x.ndim != 4:
            raise ValueError(f"expected NHWC input, got shape {x.shape}")
        n, h, w_sp, c_in = x.shape
        c_out = self.features
        kernel = self.param("kernel", self.kernel_init, (4, 4, c_in, c_out), jnp.float32)
        kernel = kernel.astype(self.dtype)
        x = x.astype(self.dtype)

        def _native(x, kernel):
            return lax.conv_transpose(
                x, kernel, strides=(2, 2), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )

        def _phase(x, kernel):
            # one conv for all four phases:
            # K2[a, b, :, phase(r,c)*Cout + o] = w[r+2a, c+2b, :, o]
            k2 = jnp.concatenate(
                [kernel[r::2, c::2] for r in range(2) for c in range(2)], axis=-1
            )  # [2, 2, Cin, 4*Cout]
            xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
            y = _phase_conv(xp, k2)  # [N, H+1, W+1, 4*Cout]
            # phase (r, c) reads y at spatial offset (r, c); depth-to-space interleave
            phases = [
                y[:, r : h + r, c : w_sp + c, i * c_out : (i + 1) * c_out]
                for i, (r, c) in enumerate((r, c) for r in range(2) for c in range(2))
            ]
            return (
                jnp.stack(phases, axis=3)
                .reshape(n, h, w_sp, 2, 2, c_out)
                .transpose(0, 1, 3, 2, 4, 5)
                .reshape(n, 2 * h, 2 * w_sp, c_out)
            )

        if _fused_deconv_enabled():
            out = jax.lax.platform_dependent(x, kernel, cpu=_phase, default=_native)
        else:
            out = _native(x, kernel)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (c_out,), jnp.float32)
            out = out + bias.astype(self.dtype)
        return out


class FusedConvTransposeS2Valid(nn.Module):
    """Drop-in for ``nn.ConvTranspose(features, (k, k), strides=(2, 2),
    padding="VALID")`` for any k >= 2 — the Dreamer-V1/V2 decoder stages
    (reference dreamer_v2 ObservationModel: k=5, 5, 6, 6) and SAC-AE's final
    k=4 deconv. Same phase
    decomposition as the SAME/k4 variant, with VALID's ``(k-1, k-1)`` dilated-form
    padding: per output phase r the taps are ``w[m0_r::2]`` (``m0_r = (k-1+r) % 2``)
    read at base offset ``(r + m0_r - (k-1)) / 2``; all four 2-D phases come out of
    ONE regular VALID convolution over the padded input, and the ragged odd-k
    interleave pads each phase to equal length and slices the junk tail off after
    the reshape (exact — the junk lands past the output)."""

    features: int
    kernel_size: int = 5
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if x.ndim != 4:
            raise ValueError(f"expected NHWC input, got shape {x.shape}")
        k = int(self.kernel_size)
        if k < 2:
            raise ValueError(f"kernel_size must be >= 2 for stride-2 phases, got {k}")
        n, h, w_sp, c_in = x.shape
        c_out = self.features
        kernel = self.param("kernel", self.kernel_init, (k, k, c_in, c_out), jnp.float32)
        kernel = kernel.astype(self.dtype)
        x = x.astype(self.dtype)

        def _native(x, kernel):
            return lax.conv_transpose(
                x, kernel, strides=(2, 2), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )

        # per-axis phase structure (stride 2, dilated-form pad k-1)
        m0 = [(k - 1 + r) % 2 for r in range(2)]
        taps = [int(np.ceil((k - m0[r]) / 2)) for r in range(2)]
        delta = [(r + m0[r] - (k - 1)) // 2 for r in range(2)]
        t_max = max(taps)
        out_len = 2 * (h - 1) + k  # per the transposed-conv VALID formula
        n_rows = [int(np.ceil((out_len - r) / 2)) for r in range(2)]
        out_len_w = 2 * (w_sp - 1) + k
        n_cols = [int(np.ceil((out_len_w - r) / 2)) for r in range(2)]

        def _phase(x, kernel):
            # one conv for all four phases; shorter phase kernels are zero-extended
            def axis_slice(r):
                sl = kernel[m0[r] :: 2]  # [taps[r], k, Cin, Cout] on the H axis
                if sl.shape[0] < t_max:
                    pad = jnp.zeros((t_max - sl.shape[0], *sl.shape[1:]), sl.dtype)
                    sl = jnp.concatenate([sl, pad], axis=0)
                return sl

            phase_kernels = []
            for r in range(2):
                kh = axis_slice(r)
                for c in range(2):
                    sl = kh[:, m0[c] :: 2]  # [t_max, taps[c], Cin, Cout]
                    if sl.shape[1] < t_max:
                        pad = jnp.zeros(
                            (sl.shape[0], t_max - sl.shape[1], *sl.shape[2:]), sl.dtype
                        )
                        sl = jnp.concatenate([sl, pad], axis=1)
                    phase_kernels.append(sl)
            k2 = jnp.concatenate(phase_kernels, axis=-1)  # [t_max, t_max, Cin, 4*Cout]

            # padding must cover the zero-extended kernels' full t_max reach (the
            # extra taps carry zero weights but still index the array)
            pad_l = max(-d for d in delta)
            pad_r_h = max(n_rows[r] - 1 + delta[r] + t_max - 1 for r in range(2)) - (h - 1)
            pad_r_w = max(n_cols[c] - 1 + delta[c] + t_max - 1 for c in range(2)) - (w_sp - 1)
            xp = jnp.pad(x, ((0, 0), (pad_l, pad_r_h), (pad_l, pad_r_w), (0, 0)))
            y = _phase_conv(xp, k2)

            # read each phase at its offset, pad ragged phases by one junk row/col so
            # a plain reshape interleaves, then slice the junk off
            h_even = max(n_rows)
            w_even = max(n_cols)
            phases = []
            i = 0
            for r in range(2):
                for c in range(2):
                    o_r, o_c = delta[r] + pad_l, delta[c] + pad_l
                    p = y[
                        :, o_r : o_r + n_rows[r], o_c : o_c + n_cols[c], i * c_out : (i + 1) * c_out
                    ]
                    p = jnp.pad(
                        p, ((0, 0), (0, h_even - n_rows[r]), (0, w_even - n_cols[c]), (0, 0))
                    )
                    phases.append(p)
                    i += 1
            return (
                jnp.stack(phases, axis=3)
                .reshape(n, h_even, w_even, 2, 2, c_out)
                .transpose(0, 1, 3, 2, 4, 5)
                .reshape(n, 2 * h_even, 2 * w_even, c_out)
            )[:, :out_len, :out_len_w, :]

        if _fused_deconv_enabled():
            out = jax.lax.platform_dependent(x, kernel, cpu=_phase, default=_native)
        else:
            out = _native(x, kernel)
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (c_out,), jnp.float32)
            out = out + bias.astype(self.dtype)
        return out
