"""AOT registry entries for the lowering-sensitive custom ops (ROADMAP item 5).

Every ``jax.lax.platform_dependent`` branch in the tree must produce a VALID
TPU lowering path — verified off-chip by the fused-program contract sweep
(``sheeprl_tpu/analysis/programs.py``): ``.trace(...).lower(lowering_platforms=
("tpu",))`` runs the full jaxpr→StableHLO pipeline for the TPU platform on the
CPU mesh (the Pallas GRU lowers through Mosaic to a ``tpu_custom_call``). A
branch that only ever lowered on CPU could hide a TPU-side trace error until
the first paid chip window. These registrations generalize
``tests/test_ops/test_tpu_lowering.py``'s hand-written programs:

- the fused Pallas LayerNorm-GRU step and the ``platform_dependent`` dispatch
  the models build (tpu=Pallas / default=XLA reference) lower for TPU with the
  Mosaic custom call present — and gradients THROUGH the dispatch lower too
  (the train programs differentiate these ops);
- the s2d fast-conv gate (``ops/conv.py``) and the im2col/phase deconv gate
  (``ops/deconv.py``) lower for cpu AND tpu in one multi-platform lowering.

None of these programs donate (they are op-level, not train-state programs),
so their contracts assert lowering validity + custom-call hygiene only.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sheeprl_tpu import ops
from sheeprl_tpu.analysis.programs import register_fused_program


def _gru_args(B: int = 16, K: int = 128, H: int = 128):
    return (
        jnp.ones((B, K), jnp.float32),
        jnp.ones((B, H), jnp.float32),
        jnp.ones((K, 3 * H), jnp.float32),
        jnp.ones((3 * H,), jnp.float32),
        jnp.ones((3 * H,), jnp.float32),
        jnp.ones((3 * H,), jnp.float32),
    )


@register_fused_program(
    "ops.gru_pallas_step",
    donated=False,
    platforms=("tpu",),
    allow_custom_calls=("tpu_custom_call",),
    expect_custom_calls=("tpu_custom_call",),
    doc="fused Pallas LayerNorm-GRU step lowers for TPU with the Mosaic kernel",
)
def _aot_gru_pallas_step():
    def step(inp, hx, w, b, scale, bias):
        return ops.fused_ln_gru_step(inp, hx, w, b, scale, bias, eps=1e-3)

    return jax.jit(step), _gru_args()


@register_fused_program(
    "ops.gru_platform_dispatch",
    donated=False,
    platforms=("tpu",),
    allow_custom_calls=("tpu_custom_call",),
    expect_custom_calls=("tpu_custom_call",),
    doc="the exact tpu=Pallas/default=reference dispatch LayerNormGRUCell builds",
)
def _aot_gru_platform_dispatch():
    # the exact dispatch LayerNormGRUCell builds on a TPU process: the tpu
    # branch is the Pallas kernel, every other platform the XLA reference.
    # (A CPU lowering of this dispatch is EXPECTED to fail — platform_dependent
    # lowers every branch, and Mosaic refuses CPU — which is exactly why
    # models.py only builds it under the jax.default_backend() gate; the
    # negative is pinned in tests/test_ops/test_tpu_lowering.py.)
    def dispatch(inp, hx, w, b, scale, bias):
        return jax.lax.platform_dependent(
            tpu=lambda: ops.fused_ln_gru_step(inp, hx, w, b, scale, bias, eps=1e-3),
            default=lambda: ops.ln_gru_step_reference(inp, hx, w, b, scale, bias, eps=1e-3),
        )

    return jax.jit(dispatch), _gru_args()


@register_fused_program(
    "ops.gru_step_grad",
    donated=False,
    platforms=("tpu",),
    allow_custom_calls=("tpu_custom_call",),
    doc="gradient THROUGH the fused GRU step lowers for TPU (custom-VJP backward)",
)
def _aot_gru_step_grad():
    args = _gru_args()

    def loss(w):
        inp, hx, _, b, scale, bias = args
        return ops.fused_ln_gru_step(inp, hx, w, b, scale, bias, eps=1e-3).sum()

    # the custom-VJP backward recomputes in reference math — the property that
    # matters is that the WHOLE gradient program lowers cleanly for TPU
    return jax.jit(jax.grad(loss)), (args[2],)


@register_fused_program(
    "ops.fast_conv",
    donated=False,
    platforms=("cpu", "tpu"),
    doc="s2d fast-conv gate (cpu=s2d decomposition / default=native) lowers for both platforms",
)
def _aot_fast_conv():
    from sheeprl_tpu.ops.conv import FastConv2x

    module = FastConv2x(features=8, kernel_size=4, max_fast_cin=8)
    x = jnp.ones((2, 16, 16, 3), jnp.float32)
    params = module.init(jax.random.PRNGKey(0), x)
    return jax.jit(lambda p, x: module.apply(p, x)), (params, x)


@register_fused_program(
    "ops.fast_conv_grad",
    donated=False,
    platforms=("cpu", "tpu"),
    doc="gradient through the conv gate lowers for both platforms",
)
def _aot_fast_conv_grad():
    from sheeprl_tpu.ops.conv import FastConv2x

    module = FastConv2x(features=8, kernel_size=4, max_fast_cin=8)
    x = jnp.ones((2, 16, 16, 3), jnp.float32)
    params = module.init(jax.random.PRNGKey(0), x)

    def loss(p):
        return module.apply(p, x).sum()

    return jax.jit(jax.grad(loss)), (params,)


@register_fused_program(
    "ops.fast_deconv",
    donated=False,
    platforms=("cpu", "tpu"),
    doc="im2col/phase deconv gate (cpu=phase form / default=native) lowers for both platforms",
)
def _aot_fast_deconv():
    from sheeprl_tpu.ops.deconv import FusedConvTranspose4x4S2

    module = FusedConvTranspose4x4S2(features=6)
    x = jnp.ones((2, 8, 8, 4), jnp.float32)
    params = module.init(jax.random.PRNGKey(0), x)
    return jax.jit(lambda p, x: module.apply(p, x)), (params, x)
